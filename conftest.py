"""Rootdir conftest (pytest only honors ``pytest_addoption`` from
here).

pytest.ini pins ``--numprocesses=4 --dist loadfile`` (xdist). When
xdist is disabled — the tier-1 command passes ``-p no:xdist`` — those
pinned addopts would die at argument parsing before a single test runs.
Re-register the flags as inert in that case, so the run degrades to one
process instead of erroring out. (Lowercase short options like ``-n``
are reserved by pytest, which is why the ini uses the long spelling.)
"""


def pytest_addoption(parser):
    try:
        parser.addoption("--numprocesses", dest="_no_xdist_n",
                         default=None)
        parser.addoption("--dist", dest="_no_xdist_dist", default=None)
    except ValueError:
        pass  # real xdist is loaded and owns these flags
