"""Parameter server + comm watchdog tests (reference: test/ps/,
dist_fleet_ctr.py subprocess harness; CommTaskManager watchdog)."""

import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ps, rpc, watchdog


class TestSparseTable:
    def test_lazy_init_and_update(self):
        t = ps.MemorySparseTable(8, learning_rate=0.5, init_std=0.0)
        rows = t.pull([3, 7, 3])
        assert rows.shape == (3, 8)
        np.testing.assert_allclose(rows, 0.0)     # init_std 0
        t.push([3], np.ones((1, 8)))
        np.testing.assert_allclose(t.pull([3]), -0.5)
        assert t.size() == 2

    def test_save_load(self, tmp_path):
        t = ps.MemorySparseTable(4, init_std=0.1)
        t.pull([1, 2, 3])
        t.save(str(tmp_path / "table"))
        t2 = ps.MemorySparseTable(4)
        t2.load(str(tmp_path / "table"))
        assert t2.size() == 3
        np.testing.assert_allclose(t2.pull([1]), t.pull([1]))

    def test_dense_table(self):
        t = ps.MemoryDenseTable([4, 2], learning_rate=1.0, seed=0)
        v0 = t.pull()
        t.push(np.ones((4, 2)))
        np.testing.assert_allclose(t.pull(), v0 - 1.0, rtol=1e-6)


class TestPsOverRpc:
    def test_client_server_roundtrip(self):
        server = ps.PsServer("ps0", rank=0, world_size=1)
        try:
            client = ps.PsClient("ps0")
            client.create_sparse_table(0, embedding_dim=8, init_std=0.0,
                                       learning_rate=0.1)
            vals = client.pull_sparse(0, [5, 9])
            assert vals.shape == (2, 8)
            client.push_sparse(0, [5], np.ones((1, 8)))
            np.testing.assert_allclose(client.pull_sparse(0, [5]), -0.1,
                                       rtol=1e-5)
            assert client.table_size(0) == 2
            client.create_dense_table(1, [3], learning_rate=1.0)
            d0 = client.pull_dense(1)
            client.push_dense(1, np.ones(3))
            np.testing.assert_allclose(client.pull_dense(1), d0 - 1,
                                       rtol=1e-5)
        finally:
            server.stop()


class TestWatchdog:
    def test_flags_stalled_collective(self):
        events = []
        wd = watchdog.CommWatchdog(timeout_s=0.1, poll_s=0.05,
                                   on_timeout=events.append)
        tid = wd.enter("all_reduce", "test")
        time.sleep(0.3)
        assert wd.timed_out and wd.timed_out[0]["op"] == "all_reduce"
        assert events
        wd.exit(tid)
        wd.stop()

    def test_fast_op_not_flagged(self):
        wd = watchdog.CommWatchdog(timeout_s=5.0, poll_s=0.05)
        tid = wd.enter("broadcast")
        wd.exit(tid)
        time.sleep(0.15)
        assert not wd.timed_out
        wd.stop()

    def test_comm_guard(self):
        from paddle_tpu.distributed.watchdog import comm_guard, get_watchdog
        with comm_guard("allgather"):
            assert get_watchdog()._inflight
        assert not get_watchdog()._inflight


class TestPsPersistenceGeoShrink:
    """PS depth (SURVEY item 18): server-side persistence, geo-SGD async
    communicator, stale-row eviction."""

    def test_save_load_persistables_roundtrip(self, tmp_path):
        server = ps.PsServer("ps_persist", rank=0, world_size=1)
        try:
            client = ps.PsClient("ps_persist")
            client.create_sparse_table(10, embedding_dim=4, init_std=0.01)
            client.create_dense_table(11, [3], learning_rate=1.0)
            client.push_sparse(10, [7], np.ones((1, 4)))
            client.push_dense(11, np.ones(3))
            v_sparse = client.pull_sparse(10, [7])
            v_dense = client.pull_dense(11)
            saved = client.save_persistables(str(tmp_path / "ck"))
            assert ("sparse", 10) in saved and ("dense", 11) in saved
            # trash the live state, then restore
            client.push_sparse(10, [7], np.full((1, 4), 100.0))
            client.push_dense(11, np.full(3, 100.0))
            loaded = client.load_persistables(str(tmp_path / "ck"))
            assert ("sparse", 10) in loaded and ("dense", 11) in loaded
            np.testing.assert_allclose(client.pull_sparse(10, [7]),
                                       v_sparse, rtol=1e-6)
            np.testing.assert_allclose(client.pull_dense(11), v_dense,
                                       rtol=1e-6)
        finally:
            server.stop()

    def test_geo_communicator_bounded_staleness(self):
        server = ps.PsServer("ps_geo", rank=0, world_size=1)
        try:
            client = ps.PsClient("ps_geo")
            client.create_dense_table(20, [4], learning_rate=1.0)
            geo = ps.GeoCommunicator(client, 20, k_steps=2)
            base = geo.value.copy()
            g = np.ones(4, np.float32)
            geo.step(g, lr=0.1)         # local only
            # server unchanged after 1 step
            np.testing.assert_allclose(client.pull_dense(20), base,
                                       rtol=1e-6)
            geo.step(g, lr=0.1)         # k_steps reached -> sync
            np.testing.assert_allclose(client.pull_dense(20),
                                       base - 0.2, rtol=1e-5)
            # two communicators (two workers) both merge their deltas
            geo2 = ps.GeoCommunicator(client, 20, k_steps=1)
            geo2.step(g, lr=0.1)
            np.testing.assert_allclose(client.pull_dense(20),
                                       base - 0.3, rtol=1e-5)
        finally:
            server.stop()

    def test_shrink_evicts_stale_rows(self):
        t = ps.MemorySparseTable(4, init_std=0.0)
        t.pull([1, 2, 3])
        for _ in range(10):
            t.pull([1])                 # keep row 1 warm
        assert t.size() == 3
        n = t.shrink(unseen_ticks=5)
        assert n == 2 and t.size() == 1
        # evicted rows lazily re-init on next access
        assert t.pull([2]).shape == (1, 4)


class TestGraphPs:
    def test_graph_table_local(self):
        """SURVEY missing #6 (reference common_graph_table.h:501): graph
        table with edge types, neighbor/node sampling, features."""
        t = ps.GraphTable(seed=0)
        t.add_edges(0, [1, 1, 1, 2, 2], [10, 11, 12, 20, 21],
                    weights=[0.1, 0.2, 0.3, 0.4, 0.5])
        assert t.size(0) == 2
        nb, ct = t.sample_neighbors(0, [1, 2, 3], sample_size=2)
        assert ct.tolist()[0] == 2 and ct.tolist()[1] == 2 \
            and ct.tolist()[2] == 0
        assert set(nb[:2].tolist()) <= {10, 11, 12}
        assert set(nb[2:4].tolist()) <= {20, 21}
        nb_all, ct_all, w = t.sample_neighbors(0, [1], -1,
                                               need_weight=True)
        assert sorted(nb_all.tolist()) == [10, 11, 12]
        assert len(w) == 3
        nodes = t.sample_nodes(0, 2)
        assert set(nodes.tolist()) <= {1, 2}
        assert sorted(t.sample_nodes(0, -1).tolist()) == [1, 2]
        # mixed weighted/unweighted adds stay aligned (default weight 1.0)
        t.add_edges(0, [1], [13])                      # unweighted append
        nb_m, ct_m, w_m = t.sample_neighbors(0, [1], -1, need_weight=True)
        assert len(nb_m) == len(w_m) == 4
        assert w_m[nb_m.tolist().index(13)] == 1.0
        np.testing.assert_array_equal(t.pull_graph_list(0, 0, 10), [1, 2])
        t.set_node_feat(0, [1, 2], "h", np.eye(2, dtype=np.float32))
        feats = t.get_node_feat(0, [2, 1, 7], "h")
        np.testing.assert_array_equal(feats[0], [0, 1])
        np.testing.assert_array_equal(feats[1], [1, 0])
        assert feats[2] is None

    def test_graph_table_over_rpc_and_geometric_bridge(self):
        """Remote GNN sampling: the graph lives on the PS server, workers
        sample through PsClient; geometric.sample_neighbors_remote keeps
        the local sample_neighbors return contract."""
        server = ps.PsServer("ps_graph", rank=0, world_size=1)
        try:
            client = ps.PsClient("ps_graph")
            client.create_graph_table(7, seed=3)
            client.add_graph_edges(7, 0, [0, 0, 0, 1], [5, 6, 7, 8])
            nb, ct = client.sample_neighbors(7, 0, [0, 1], 2)
            assert list(ct) == [2, 1]
            assert set(np.asarray(nb)[:2].tolist()) <= {5, 6, 7}
            client.set_node_feat(7, 0, [0], "emb",
                                 np.ones((1, 4), np.float32))
            got = client.get_node_feat(7, 0, [0], "emb")
            np.testing.assert_array_equal(got[0], np.ones(4))
            assert list(client.pull_graph_list(7, 0, 0, 10)) == [0, 1]

            import paddle_tpu.geometric as geo
            import paddle_tpu as paddle
            nbrs, counts = geo.sample_neighbors_remote(
                client, 7, paddle.to_tensor(np.asarray([0, 1])),
                sample_size=-1)
            assert np.asarray(counts._value).tolist() == [3, 1]
            assert sorted(np.asarray(nbrs._value).tolist()) == [5, 6, 7, 8]

            # persistence round-trip includes the graph table
            import tempfile
            with tempfile.TemporaryDirectory() as d:
                saved = client.save_persistables(d)
                assert ("graph", 7) in [tuple(s) for s in saved]
                client.add_graph_edges(7, 0, [2], [9])  # post-save edit
                loaded = client.load_persistables(d)
                assert ("graph", 7) in [tuple(s) for s in loaded]
                assert list(client.pull_graph_list(7, 0, 0, 10)) == [0, 1]
        finally:
            server.stop()


class TestFsClients:
    def test_local_fs_surface(self, tmp_path):
        """reference fleet/utils/fs.py LocalFS:113 — the FS contract the
        PS/elastic checkpoint flows save through."""
        from paddle_tpu.distributed.fleet.fs import (FSFileExistsError,
                                                     FSFileNotExistsError,
                                                     LocalFS)
        fs = LocalFS()
        assert fs.need_upload_download() is False
        d = tmp_path / "ckpt"
        fs.mkdirs(str(d))
        assert fs.is_dir(str(d)) and fs.is_exist(str(d))
        f = d / "a.txt"
        f.write_text("hello")
        fs.touch(str(d / "b.txt"))
        with pytest.raises(FSFileExistsError):
            fs.touch(str(f), exist_ok=False)
        dirs, files = fs.ls_dir(str(d))
        assert sorted(files) == ["a.txt", "b.txt"] and dirs == []
        fs.mkdirs(str(d / "sub"))
        assert fs.list_dirs(str(d)) == ["sub"]
        fs.mv(str(f), str(d / "c.txt"))
        assert fs.cat(str(d / "c.txt")) == "hello"
        with pytest.raises(FSFileNotExistsError):
            fs.mv(str(d / "nope"), str(d / "x"))
        fs.upload(str(d / "c.txt"), str(tmp_path / "up.txt"))
        assert fs.is_file(str(tmp_path / "up.txt"))
        fs.upload_dir(str(d), str(tmp_path / "copy"))
        assert fs.is_dir(str(tmp_path / "copy" / "sub"))
        fs.delete(str(d))
        assert not fs.is_exist(str(d))

    def test_hdfs_client_command_plumbing(self, tmp_path):
        """HDFSClient builds ``hadoop fs`` commands (reference
        fs.py:447); verified against a stub hadoop executable that logs
        its argv and emulates -test/-ls."""
        import stat
        from paddle_tpu.distributed.fleet.fs import ExecuteError, HDFSClient
        home = tmp_path / "hadoop_home"
        (home / "bin").mkdir(parents=True)
        log = tmp_path / "argv.log"
        stub = home / "bin" / "hadoop"
        stub.write_text(f"""#!/bin/sh
echo "$@" >> {log}
case " $@ " in
  *" -ls "*) echo "drwxr-xr-x - u g 0 2026-01-01 00:00 /data/sub"
             echo "-rw-r--r-- 1 u g 5 2026-01-01 00:00 /data/a.txt" ;;
esac
exit 0
""")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        c = HDFSClient(hadoop_home=str(home),
                       configs={"fs.default.name": "hdfs://x:9000"})
        assert c.need_upload_download() is True
        assert c.is_exist("/data")
        dirs, files = c.ls_dir("/data")
        assert dirs == ["sub"] and files == ["a.txt"]
        c.mkdirs("/data/new")
        c.upload("local.bin", "/data/local.bin")
        lines = log.read_text().splitlines()
        assert any("-D fs.default.name=hdfs://x:9000" in ln
                   for ln in lines)
        assert any("-mkdir -p /data/new" in ln for ln in lines)
        assert any("-put local.bin /data/local.bin" in ln for ln in lines)
        # missing binary is loud
        bad = HDFSClient(hadoop_home=str(tmp_path / "nope"))
        with pytest.raises(ExecuteError, match="hadoop binary not found"):
            bad.mkdirs("/x")


class TestPsIngestionAndTrainer:
    """VERDICT r4 #6: the PS training RUNTIME — MultiSlot ingestion +
    data_generator face + Hogwild/Downpour async trainer loop — not just
    tables exercised from test code."""

    SLOTS = None

    def _slots(self):
        from paddle_tpu.distributed import fleet
        return [fleet.SlotDesc("user_id", "uint64"),
                fleet.SlotDesc("ad_ids", "uint64"),
                fleet.SlotDesc("dense_feat", "float", dim=3),
                fleet.SlotDesc("label", "float", dim=1)]

    def _write_ctr_file(self, path, n=1200, seed=0):
        """Synthetic CTR process with learnable additive id effects,
        emitted through the data_generator protocol."""
        import io

        from paddle_tpu.distributed import fleet
        rng = np.random.RandomState(seed)
        n_users, n_ads = 40, 25
        bu = rng.randn(n_users) * 2.0
        ba = rng.randn(n_ads) * 2.0

        class Gen(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    for _ in range(n):
                        u = rng.randint(n_users)
                        ads = rng.randint(0, n_ads, rng.randint(1, 4))
                        aff = bu[u] + ba[ads].mean()
                        dense = rng.randn(3) * 0.1
                        p = 1 / (1 + np.exp(-(aff + dense.sum())))
                        y = float(rng.rand() < p)
                        yield [("user_id", [u]),
                               ("ad_ids", ads.tolist()),
                               ("dense_feat", dense.tolist()),
                               ("label", [y])]
                return it

        buf = io.StringIO()
        Gen().run_from_memory(out=buf)
        with open(path, "w") as f:
            f.write(buf.getvalue())

    def test_multislot_roundtrip_and_validation(self, tmp_path):
        from paddle_tpu.distributed import fleet
        slots = self._slots()
        p = tmp_path / "data.txt"
        self._write_ctr_file(str(p), n=50)
        feed = fleet.MultiSlotDataFeed(slots)
        recs = list(feed.read_file(str(p)))
        assert len(recs) == 50
        r = recs[0]
        assert r["user_id"].dtype == np.int64
        assert r["dense_feat"].shape == (3,)
        assert r["label"].shape == (1,)
        with pytest.raises(ValueError, match="declares"):
            feed.parse_line("3 1 2")          # count > remaining values
        with pytest.raises(ValueError, match="trailing"):
            feed.parse_line("1 7 2 1 2 3 0.1 0.2 0.3 1 1.0 99")

    def test_dataset_shuffle_and_padded_batches(self, tmp_path):
        from paddle_tpu.distributed import fleet
        slots = self._slots()
        p = tmp_path / "data.txt"
        self._write_ctr_file(str(p), n=100)
        ds = fleet.InMemoryDataset(slots, batch_size=32, seed=3)
        ds.load_into_memory([str(p)])
        assert len(ds) == 100
        before = [int(r["user_id"][0]) for r in ds._records[:10]]
        ds.local_shuffle()
        after = [int(r["user_id"][0]) for r in ds._records[:10]]
        assert before != after                 # overwhelmingly likely
        ds.global_shuffle()                    # world=1: local shuffle
        batches = list(ds.batches())
        assert len(batches) == 4               # 3x32 + 1x4
        ids, mask = batches[0]["ad_ids"]
        assert ids.shape == mask.shape and ids.shape[0] == 32
        assert mask.sum(axis=1).min() >= 1     # every row has a feasign
        ds.release_memory()
        assert len(ds) == 0

    def test_global_shuffle_partitions_across_ranks(self, monkeypatch):
        """world>1 global_shuffle: every rank computes the SAME
        permutation of the gathered global record set and takes its
        strided share — together the shares cover each record exactly
        once (reference Dataset GlobalShuffle over the PS channel)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet
        slots = [fleet.SlotDesc("x", "uint64")]
        world = 3
        per_rank = [[{"x": np.asarray([r * 100 + i], np.int64)}
                     for i in range(4)] for r in range(world)]

        shares = []
        for rank in range(world):
            ds = fleet.InMemoryDataset(slots, batch_size=2, seed=7)
            ds._records = list(per_rank[rank])
            monkeypatch.setattr(dist, "get_world_size",
                                lambda group=None: world)
            monkeypatch.setattr(dist, "get_rank",
                                lambda group=None, r=rank: r)

            def fake_gather(out, obj, group=None):
                out.extend(list(per_rank))  # same global view everywhere

            monkeypatch.setattr(dist, "all_gather_object", fake_gather)
            ds.global_shuffle()
            shares.append([int(r["x"][0]) for r in ds._records])
        allrec = sorted(x for s in shares for x in s)
        want = sorted(r * 100 + i for r in range(world) for i in range(4))
        assert allrec == want                  # exact cover, no dupes
        assert all(len(s) == 4 for s in shares)
        # and it is a real shuffle, not identity partitioning
        assert shares[0] != [0, 1, 2, 3]

    def test_geo_sgd_dense_sync(self, tmp_path):
        """geo_k_steps mode: workers train the dense region on a LOCAL
        copy and the GeoCommunicator ships deltas every k steps — the
        model still learns, and the server's dense region converges to
        the trained values (not the init) after the final sync."""
        from paddle_tpu.distributed import fleet, ps
        slots = self._slots()
        p = tmp_path / "ctr.txt"
        self._write_ctr_file(str(p), n=800)
        ds = fleet.InMemoryDataset(slots, batch_size=64, seed=0)
        ds.load_into_memory([str(p)])
        ds.local_shuffle()
        srv = ps.PsServer(name="ps_geo_test")
        try:
            client = ps.PsClient(server_name="ps_geo_test")
            tr = ps.DownpourTrainer(client, slots, embedding_dim=8,
                                    hidden=32, batch_size=64,
                                    n_threads=2, sparse_lr=2.0,
                                    dense_lr=0.5, geo_k_steps=4)
            stats = tr.train(ds, epochs=8)
            assert stats["loss_mean_tail"] < stats["loss_mean_head"] - 0.1
            # train() flushes the residual delta itself — the server is
            # authoritative the moment train() returns
            server_flat = np.asarray(client.pull_dense(
                tr.dense_table_id))
            # the server moved away from the init by the local training
            assert not np.allclose(server_flat, tr.tower.flat0,
                                   atol=1e-3)
            ev = tr.evaluate(ds)
            assert ev["auc"] > 0.7, (stats, ev)
        finally:
            srv.stop()

    def test_full_uint64_feasign_range(self):
        """64-bit hash feasigns (above 2^63-1) parse as the signed
        bit-pattern and round-trip through a sparse table — per-slot
        tables mean no bits are stolen for slot disambiguation."""
        from paddle_tpu.distributed import fleet, ps
        feed = fleet.MultiSlotDataFeed([fleet.SlotDesc("h", "uint64")])
        rec = feed.parse_line("2 18446744073709551615 9223372036854775808")
        assert rec["h"].dtype == np.int64
        assert rec["h"][0] == -1               # uint64 max bit-pattern
        table = ps.MemorySparseTable(4)
        rows = table.pull(rec["h"])
        assert rows.shape == (2, 4)
        table.push(rec["h"], np.ones((2, 4), np.float32))
        assert table.size() == 2

    def test_downpour_hogwild_ctr_end_to_end(self, tmp_path):
        """The whole runtime: records -> InMemoryDataset -> 2 Hogwild
        workers running the Downpour pull/push cycle against live PS
        tables -> loss falls, eval AUC clears 0.75, tables persist and
        reload with bit-identical eval results."""
        from paddle_tpu.distributed import fleet, ps
        slots = self._slots()
        p = tmp_path / "ctr.txt"
        self._write_ctr_file(str(p), n=1200)
        ds = fleet.InMemoryDataset(slots, batch_size=64, seed=0)
        ds.load_into_memory([str(p)])
        ds.local_shuffle()

        srv = ps.PsServer(name="ps_ctr_test")
        try:
            client = ps.PsClient(server_name="ps_ctr_test")
            tr = ps.DownpourTrainer(client, slots, embedding_dim=8,
                                    hidden=32, batch_size=64,
                                    n_threads=2, sparse_lr=2.0,
                                    dense_lr=0.5)
            stats = tr.train(ds, epochs=8)
            assert stats["steps"] >= 8 * (1200 // 64)
            assert stats["loss_mean_tail"] < stats["loss_mean_head"] - 0.1
            ev = tr.evaluate(ds)
            assert ev["auc"] > 0.75, (stats, ev)
            # one table per slot; pulls touch only LIVE feasigns, so
            # sizes equal the actual id vocabularies (40 users, 25 ads)
            assert client.table_size(tr.sparse_table_ids[0]) == 40
            assert client.table_size(tr.sparse_table_ids[1]) == 25

            # persistence: save, wipe, load, bit-identical eval
            ckpt = str(tmp_path / "tables")
            client.save_persistables(ckpt)
            for tid in tr.sparse_table_ids:    # wipe with fresh tables
                client.create_sparse_table(tid, 8)
            wiped = tr.evaluate(ds)
            assert wiped["auc"] < ev["auc"] - 0.05
            client.load_persistables(ckpt)
            back = tr.evaluate(ds)
            assert back["auc"] == ev["auc"]
            assert back["loss"] == ev["loss"]
        finally:
            srv.stop()
