"""bench.py tunnel-flake hardening (VERDICT r4 weak #1 / ask #1): the
backend probe must retry with backoff and, on final failure, emit ONE
structured infra-skip JSON line and exit 0 — never a stack-trace rc=1.
Probe logic tested with a monkeypatched subprocess so no backend is
touched."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def test_is_infra_error_classifies():
    # in-process matcher is STRICT (grpc status classes, case-sensitive)
    assert bench._is_infra_error(
        RuntimeError("UNAVAILABLE: TPU backend setup/compile error"))
    assert bench._is_infra_error(RuntimeError("DEADLINE_EXCEEDED: rpc"))
    assert not bench._is_infra_error(ValueError("bad shape (3, 4)"))
    assert not bench._is_infra_error(AssertionError("loss did not fall"))
    assert not bench._is_infra_error(
        NotImplementedError("feature unavailable on this backend"))
    # probe-stderr matcher is lenient (failure diversity is init-only)
    assert bench._is_infra_error_text("failed to connect to all addresses")
    assert bench._is_infra_error_text("socket closed")
    assert not bench._is_infra_error_text("ModuleNotFoundError: jax")


def test_infra_skip_metric_follows_preset(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_PRESET", "decode")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "decode_tokens_per_sec"
    monkeypatch.setenv("BENCH_PRESET", "flash32k")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "flash_attention_32k_fwd_bwd_ms"


def test_env_flag_tolerant(monkeypatch):
    for v, want in [("1", True), ("true", True), ("YES", True),
                    ("0", False), ("", False), ("false", False)]:
        monkeypatch.setenv("BENCH_SKIP_PROBE", v)
        assert bench._env_flag("BENCH_SKIP_PROBE") is want
    monkeypatch.delenv("BENCH_SKIP_PROBE")
    assert bench._env_flag("BENCH_SKIP_PROBE") is False


def test_probe_skipped_via_env(monkeypatch):
    monkeypatch.setenv("BENCH_SKIP_PROBE", "1")

    def boom(*a, **k):  # probe must not spawn anything when skipped
        raise AssertionError("probe ran despite BENCH_SKIP_PROBE")

    monkeypatch.setattr(subprocess, "run", boom)
    bench.probe_backend()


def test_probe_success_first_try(monkeypatch, capsys):
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setattr(bench, "_PROBE_BACKOFF_S", (0, 0, 0))
    calls = []

    def ok(cmd, **k):
        calls.append(cmd)
        return subprocess.CompletedProcess(cmd, 0, stdout="tpu 1\n",
                                           stderr="")

    monkeypatch.setattr(subprocess, "run", ok)
    bench.probe_backend()
    assert len(calls) == 1
    assert capsys.readouterr().out == ""


def test_probe_retries_then_infra_skip(monkeypatch, capsys):
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setattr(bench, "_PROBE_ATTEMPTS", 3)
    monkeypatch.setattr(bench, "_PROBE_BACKOFF_S", (0, 0, 0))
    attempts = []

    def hang(cmd, timeout=None, **k):
        attempts.append(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(subprocess, "run", hang)
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 0                      # infra-skip, NOT rc=1
    assert len(attempts) == 3                      # bounded retry
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert out["metric"] == "llama_pretrain_tokens_per_sec_per_chip"
    assert "hung" in out["detail"]


def test_probe_propagates_non_infra_failure(monkeypatch, capsys):
    """A broken env (import error) is a real regression: rc!=0, no
    infra-skip JSON, no retry burn."""
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    calls = []

    def broken(cmd, **k):
        calls.append(cmd)
        return subprocess.CompletedProcess(
            cmd, 1, stdout="",
            stderr="ModuleNotFoundError: No module named 'jax'\n")

    monkeypatch.setattr(subprocess, "run", broken)
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 1
    assert len(calls) == 1                         # no pointless retries
    assert capsys.readouterr().out == ""           # no infra-skip JSON


@pytest.fixture
def _restore_signals():
    """run_walled installs SIGTERM/SIGINT handlers; monkeypatch cannot
    undo signal.signal, so restore by hand or a later driver SIGTERM to
    the suite would invoke the leftover forward() handler."""
    import signal
    saved = [(s, signal.getsignal(s))
             for s in (signal.SIGTERM, signal.SIGINT)]
    yield
    for s, h in saved:
        signal.signal(s, h)


class _FakeChild:
    def __init__(self, lines=(), rc=0, hang=False):
        self.pid = 12345
        self.stdout = iter(lines)
        self._rc = rc
        self._hang = hang

    def wait(self, timeout=None):
        if self._hang and timeout is not None:
            raise subprocess.TimeoutExpired("bench", timeout)
        return self._rc


def test_walled_run_times_out_to_infra_skip(monkeypatch, capsys,
                                            _restore_signals):
    monkeypatch.setattr(subprocess, "Popen",
                        lambda *a, **k: _FakeChild(hang=True))
    killed = []
    monkeypatch.setattr(os, "killpg", lambda pid, sig: killed.append(pid))
    monkeypatch.setattr(bench, "_WALL_TIMEOUT_S", 7)
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 0
    assert killed == [12345]
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert "wall limit" in out["detail"]


def test_walled_timeout_after_metric_is_not_double_emitted(
        monkeypatch, capsys, _restore_signals):
    """Post-result teardown stall: the metric line already went out, so
    the wall kill must NOT add a second contradictory JSON line."""
    metric = json.dumps({"metric": "decode_tokens_per_sec", "value": 1})
    monkeypatch.setattr(
        subprocess, "Popen",
        lambda *a, **k: _FakeChild(lines=[metric + "\n"], hang=True))
    monkeypatch.setattr(os, "killpg", lambda pid, sig: None)
    monkeypatch.setattr(bench, "_WALL_TIMEOUT_S", 7)
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines == [metric]                       # exactly one JSON line


def test_walled_run_propagates_child_rc(monkeypatch, capsys,
                                        _restore_signals):
    monkeypatch.setattr(subprocess, "Popen",
                        lambda *a, **k: _FakeChild(rc=3))
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 3
    assert capsys.readouterr().out == ""


def test_probe_rejects_silent_cpu_fallback(monkeypatch, capsys):
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    monkeypatch.setattr(bench, "_PROBE_ATTEMPTS", 2)
    monkeypatch.setattr(bench, "_PROBE_BACKOFF_S", (0, 0))

    def cpu_fallback(cmd, **k):
        return subprocess.CompletedProcess(cmd, 0, stdout="cpu 8\n",
                                           stderr="")

    monkeypatch.setattr(subprocess, "run", cpu_fallback)
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert "cpu" in out["detail"]
    # explicit opt-in keeps the CPU smoke path usable
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    bench.probe_backend()                          # must not exit


def test_probe_recovers_on_second_attempt(monkeypatch, capsys):
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setattr(bench, "_PROBE_BACKOFF_S", (0, 0, 0))
    state = {"n": 0}

    def flaky(cmd, timeout=None, **k):
        state["n"] += 1
        if state["n"] == 1:
            return subprocess.CompletedProcess(
                cmd, 1, stdout="",
                stderr="jax.errors.JaxRuntimeError: UNAVAILABLE: boom\n")
        return subprocess.CompletedProcess(cmd, 0, stdout="tpu 1\n",
                                           stderr="")

    monkeypatch.setattr(subprocess, "run", flaky)
    bench.probe_backend()                          # must not exit
    assert state["n"] == 2
    assert capsys.readouterr().out == ""
