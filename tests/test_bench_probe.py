"""bench.py tunnel-flake hardening (VERDICT r4 weak #1 / ask #1): the
backend probe must retry with backoff and, on final failure, emit ONE
structured infra-skip JSON line and exit 0 — never a stack-trace rc=1.
Probe logic tested with a monkeypatched subprocess so no backend is
touched."""

import json
import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def test_is_infra_error_classifies():
    # in-process matcher is STRICT (grpc status classes, case-sensitive)
    assert bench._is_infra_error(
        RuntimeError("UNAVAILABLE: TPU backend setup/compile error"))
    assert bench._is_infra_error(RuntimeError("DEADLINE_EXCEEDED: rpc"))
    assert not bench._is_infra_error(ValueError("bad shape (3, 4)"))
    assert not bench._is_infra_error(AssertionError("loss did not fall"))
    assert not bench._is_infra_error(
        NotImplementedError("feature unavailable on this backend"))
    # probe-stderr matcher is lenient (failure diversity is init-only)
    assert bench._is_infra_error_text("failed to connect to all addresses")
    assert bench._is_infra_error_text("socket closed")
    assert not bench._is_infra_error_text("ModuleNotFoundError: jax")


def test_infra_skip_metric_follows_preset(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_PRESET", "decode")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "decode_tokens_per_sec"
    monkeypatch.setenv("BENCH_PRESET", "flash32k")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "flash_attention_32k_fwd_bwd_ms"
    monkeypatch.setenv("BENCH_PRESET", "prefix")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "prefix_cached_ttft_ms"
    monkeypatch.setenv("BENCH_PRESET", "fleet")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "fleet_affinity_ttft_ms"
    monkeypatch.setenv("BENCH_PRESET", "slo")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "slo_shipper_overhead_pct"
    monkeypatch.setenv("BENCH_PRESET", "overload")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "overload_p99_ttft_ms"
    monkeypatch.setenv("BENCH_PRESET", "mixed")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "mixed_p99_ttft_ms"
    monkeypatch.setenv("BENCH_PRESET", "spec")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "spec_tokens_per_step"
    monkeypatch.setenv("BENCH_PRESET", "chaos")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "chaos_goodput_ratio"
    monkeypatch.setenv("BENCH_PRESET", "tp")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "tp_device_calls_per_step"
    monkeypatch.setenv("BENCH_PRESET", "disagg")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "disagg_p99_ttft_ms"
    monkeypatch.setenv("BENCH_PRESET", "cp")
    bench._emit_infra_skip("tunnel down")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["metric"] == "cp_p99_ttft_steps"


@pytest.mark.slow
def test_prefix_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=prefix (ISSUE 2 satellite):
    one JSON line, cached TTFT strictly below uncached (vs_baseline is
    their ratio), and the engine actually served prefix hits. r8: the
    run also dumps the engine's metrics-registry snapshot and links it
    from extra.metrics_snapshot."""
    env = dict(os.environ, BENCH_PRESET="prefix", BENCH_ALLOW_CPU="1",
               BENCH_NO_WALL="1", BENCH_SKIP_PROBE="1",
               BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "prefix_cached_ttft_ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 1.0    # cached strictly beats uncached
    assert out["extra"]["prefix_hit_tokens"] > 0
    assert out["extra"]["uncached_ttft_ms"] > out["value"]
    snap_path = out["extra"]["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_prefix.json")
    snap = json.load(open(snap_path))
    assert snap["counters"]["engine_prefix_hit_tokens_total"] > 0
    assert snap["histograms"]["engine_ttft_seconds"]["count"] > 0


@pytest.mark.slow
def test_fleet_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=fleet (ISSUE 4 satellite):
    one JSON line, prefix-affinity routing strictly beats round-robin
    on the shared-system-prompt workload (vs_baseline = rr/affinity
    cached TTFT > 1, and more prefix tokens served from cache), and the
    aggregated per-worker + merged registry snapshot is dumped."""
    env = dict(os.environ, BENCH_PRESET="fleet", BENCH_ALLOW_CPU="1",
               BENCH_NO_WALL="1", BENCH_SKIP_PROBE="1",
               BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "fleet_affinity_ttft_ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 1.0    # affinity beats round-robin
    assert out["extra"]["affinity_prefix_hit_tokens"] > \
        out["extra"]["rr_prefix_hit_tokens"]
    assert out["extra"]["affinity_hits"] > 0
    snap_path = out["extra"]["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_fleet.json")
    snap = json.load(open(snap_path))
    assert set(snap["workers"]) == {"w0", "w1", "router"}
    merged = snap["fleet"]
    assert merged["counters"]["engine_prefix_hit_tokens_total"] > 0
    assert merged["counters"]["fleet_submitted_total"] == \
        snap["workers"]["router"]["counters"]["fleet_submitted_total"]
    assert merged["histograms"]["engine_ttft_seconds"]["count"] == sum(
        snap["workers"][w]["histograms"]["engine_ttft_seconds"]["count"]
        for w in ("w0", "w1"))


@pytest.mark.slow
def test_slo_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=slo (ISSUE 5 satellite): one
    JSON line, the SLO engine + shipper cost under 5% of step wall (the
    acceptance budget), the shipper actually delivered telemetry to the
    JSONL sink, and the aggregated snapshot carries the shipper's
    self-observation counters."""
    env = dict(os.environ, BENCH_PRESET="slo", BENCH_ALLOW_CPU="1",
               BENCH_NO_WALL="1", BENCH_SKIP_PROBE="1",
               BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "slo_shipper_overhead_pct"
    assert out["value"] < 5.0          # telemetry tax under the 5% budget
    assert out["vs_baseline"] > 0.95
    ship = out["extra"]["shipper"]
    assert ship["shipped"] > 0
    assert ship["sink_errors"] == 0
    assert out["extra"]["slo_states"] == {"ttft_p99": "ok",
                                          "error_rate": "ok"}
    with open(out["extra"]["telemetry_jsonl"]) as fh:
        payloads = [json.loads(ln) for ln in fh if ln.strip()]
    assert payloads and all(p["kind"] == "fleet_telemetry"
                            for p in payloads)
    snap_path = out["extra"]["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_slo.json")
    snap = json.load(open(snap_path))
    assert "shipper" in snap["workers"]
    assert snap["workers"]["shipper"]["counters"][
        "shipper_shipped_total"] > 0


@pytest.mark.slow
def test_overload_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=overload (ISSUE 6 satellite):
    one JSON line; the QoS accounting (admitted/throttled/shed/served
    on the virtual clock) replays bit-identically across the two QoS-on
    sims (extra.qos.deterministic); every shed request is accounted
    (tally shed == qos_shed_total sum == shed_rate * submitted); and
    Jain's fairness index is recorded for both configs with the
    aggregated snapshot dumped."""
    env = dict(os.environ, BENCH_PRESET="overload",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "overload_p99_ttft_ms"
    assert out["value"] > 0
    extra = out["extra"]
    # the virtual-clock policy replay must be bit-deterministic
    assert extra["qos"]["deterministic"] is True
    for key in ("jain_fairness_on", "jain_fairness_off"):
        assert 0.0 < extra[key] <= 1.0
    assert out["vs_baseline"] == pytest.approx(
        extra["jain_fairness_on"] / extra["jain_fairness_off"],
        rel=1e-3)
    # shed accounting: tally == per-tenant counters == shed_rate
    shed_tally = sum(t["shed"] for t in extra["tally_on"].values())
    shed_counters = sum(int(t["shed"]) for t in
                        extra["qos"]["per_tenant"].values())
    assert shed_tally == shed_counters == extra["qos"]["shed_total"]
    assert extra["shed_rate"] == pytest.approx(
        extra["qos"]["shed_total"] / extra["submitted"], abs=1e-3)
    # the flood engaged all three policies under the fixed seed
    assert extra["qos"]["shed_total"] > 0
    assert sum(int(t["throttled"]) for t in
               extra["qos"]["per_tenant"].values()) > 0
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_overload.json")
    snap = json.load(open(snap_path))
    assert "tenant=t_heavy" in snap["workers"]
    assert "tenant=t_light" in snap["workers"]
    assert snap["workers"]["tenant=t_light"]["counters"][
        "qos_shed_total"] == 0
    assert snap["fleet"]["histograms"]["engine_ttft_seconds"][
        "count"] > 0


@pytest.mark.slow
def test_mixed_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=mixed (ISSUE 7 satellite):
    one JSON line; the chunked and admission runs of the same seeded
    flood produce bit-identical greedy outputs; chunked p99 TTFT is no
    worse than admission p99 TTFT (the perf claim, on the same engine
    config); and the chunk windows stayed inside the documented bucket
    set (no third program shape)."""
    env = dict(os.environ, BENCH_PRESET="mixed",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "mixed_p99_ttft_ms"
    assert out["value"] > 0
    extra = out["extra"]
    # the correctness oracle: same flood, same greedy outputs
    assert extra["outputs_identical"] is True
    # the perf claim: chunking flattens (or at worst matches) the tail
    assert (extra["chunked_p99_ttft_ms"]
            <= extra["admission_p99_ttft_ms"])
    assert out["vs_baseline"] >= 1.0
    # shape discipline: every chunk window is a documented power-of-two
    # bucket (the default page-sized chunk rides exactly {16})
    assert extra["chunk_prog_windows"] == [16]
    assert extra["prefill_chunks"] > 0
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_mixed.json")
    snap = json.load(open(snap_path))
    assert snap["counters"]["engine_prefill_chunks_total"] == \
        extra["prefill_chunks"]
    assert snap["histograms"]["engine_step_budget_used"]["count"] > 0
    # ISSUE 13: the phase-breakdown dump rides beside the metrics one
    prof = json.load(open(extra["profile_snapshot"]))
    assert prof["chunked"]["steps"] > 0
    assert "prefill_chunk" in prof["chunked"]["phases"]
    assert prof["compiles"]["chunked"]["unexpected"] == 0


@pytest.mark.slow
def test_spec_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=spec (ISSUE 8 satellite):
    one JSON line; spec ON emits bit-identical outputs to plain greedy
    on the same seeded prompt mix (the speculation oracle — every
    accepted token is the verify program's argmax); the draft-friendly
    repetitive mix earns at least 1.2 tokens per verify step; and the
    accept accounting in the snapshot is self-consistent with the
    BENCH row."""
    env = dict(os.environ, BENCH_PRESET="spec",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "spec_tokens_per_step"
    extra = out["extra"]
    # the correctness oracle: speculation changes WHEN tokens are
    # computed, never WHICH tokens come out
    assert extra["outputs_identical"] is True
    # the perf claim: drafts pay on the repetitive mix
    assert out["value"] >= 1.2
    assert 1.0 <= extra["tokens_per_step_mix"] <= out["value"] + 1e-9
    assert 0.0 < extra["accept_rate_mix"] <= 1.0
    assert extra["accepted"] <= extra["proposed"]
    # deterministic accounting: the snapshot's counters back the row
    snap = json.load(open(extra["metrics_snapshot"]))
    assert snap["counters"]["engine_spec_proposed_total"] == \
        extra["proposed"]
    assert snap["counters"]["engine_spec_accepted_total"] == \
        extra["accepted"]
    assert snap["histograms"]["engine_spec_accept_len"]["count"] > 0


@pytest.mark.slow
def test_tp_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=tp (ISSUE 10 satellite): one
    JSON line; sharded (tp=2 and tp=4) outputs bit-identical to the
    unsharded engine on the same seeded arrivals; the tp=2 repeat is
    bit-for-bit (same outputs AND same launch count); and the batched
    verify + single-launch mixed step genuinely collapse per-step
    device calls (sharded launches/step ~1, unsharded strictly
    higher)."""
    env = dict(os.environ, BENCH_PRESET="tp",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "tp_device_calls_per_step"
    extra = out["extra"]
    # the correctness oracle: sharding is device wiring, never a
    # quality trade
    assert extra["outputs_identical_tp2"] is True
    assert extra["outputs_identical_tp4"] is True
    assert extra["repeat_bit_identical"] is True
    # the perf claim: O(rows) per-row verify launches collapse into
    # O(1) mixed launches per engine step
    assert out["vs_baseline"] > 1.0
    assert extra["tp2_device_calls"] < extra["unsharded_device_calls"]
    assert out["value"] < extra["unsharded_calls_per_step"]
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_tp.json")
    snap = json.load(open(snap_path))
    assert snap["counters"]["engine_device_calls_total"] > 0
    assert snap["gauges"]["engine_tp_degree"] == 2


@pytest.mark.slow
def test_cp_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=cp (ISSUE 16 satellite): one
    JSON line; the 1-D tp=4 and 2-D (seq=2, tp=4) runs both bit-match
    the unsharded oracle on the same seeded long-prompt flood; the 2-D
    repeat is bit-for-bit with an equal launch count; and the wider
    context-parallel prefill chunk genuinely flattens the long-prompt
    TTFT tail (p99 in engine steps strictly better than 1-D tp at the
    kv-head cap, with strictly fewer device launches)."""
    env = dict(os.environ, BENCH_PRESET="cp",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "cp_p99_ttft_steps"
    extra = out["extra"]
    # the correctness oracle: the 2-D mesh is device wiring, never a
    # quality trade — and the same seed replays bit-for-bit
    assert extra["outputs_identical_tp4"] is True
    assert extra["outputs_identical_2d"] is True
    assert extra["repeat_bit_identical"] is True
    # the perf claim: spreading chunk windows over the seq axis cuts
    # the prefill launches a long prompt needs, so the TTFT tail drops
    assert out["vs_baseline"] > 1.0
    assert out["value"] < extra["tp4_p99_ttft_steps"]
    assert extra["seq2_tp4_device_calls"] < extra["tp4_device_calls"]
    assert extra["mesh_shape"] == {"seq": 2, "tp": 4}
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_cp.json")
    snap = json.load(open(snap_path))
    assert snap["gauges"]["engine_tp_degree"] == 4
    assert snap["gauges"]["engine_seq_degree"] == 2


@pytest.mark.slow
def test_chaos_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=chaos (ISSUE 9 satellite):
    one JSON line; the same-seed chaos run replays bit-for-bit; every
    output completed under faults bit-matches the fault-free run
    (failover is recompute-resume); and the fleet healed back to full
    capacity by the end of the window."""
    env = dict(os.environ, BENCH_PRESET="chaos",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "chaos_goodput_ratio"
    extra = out["extra"]
    # same seed, same faults, same outputs — bit-for-bit
    assert extra["deterministic"] is True
    # the healing oracle: whatever completed under chaos matches the
    # fault-free run token-for-token
    assert extra["outputs_bit_parity"] is True
    assert extra["compared_outputs"] > 0
    # the schedule genuinely injected faults and the fleet healed
    assert sum(extra["faults_fired"].values()) > 0
    assert extra["restarts"] > 0
    assert extra["healthy_workers_end"] == 3
    assert 0.0 < out["value"] <= 1.0
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_chaos.json")
    snap = json.load(open(snap_path))
    assert snap["fleet"]["counters"]["engine_retired_total"] > 0
    # ISSUE 13: the measured chaos run is profiled and bundle-dumping
    # (the plain repeat proves the observers didn't perturb it —
    # deterministic above); every failover left a postmortem bundle
    assert extra["postmortem_bundles"] > 0
    prof = json.load(open(extra["profile_snapshot"]))
    assert prof["statusz"]["router_profile"]["steps"] > 0
    assert len(prof["postmortems"]) == extra["postmortem_bundles"]
    assert all(n.startswith("postmortem_") for n in prof["postmortems"])


@pytest.mark.slow
def test_disagg_preset_cpu_smoke(tmp_path):
    """End-to-end CPU run of BENCH_PRESET=disagg (ISSUE 14 satellite):
    one JSON line; the role-split and unified runs of the same seeded
    two-tenant mix produce bit-identical greedy outputs; the split
    fleet's prompt-tenant p99 TTFT beats unified (the perf claim —
    decode residency moved off the prefill worker); the same-seed
    split repeat replays bit-for-bit; and the KV pages genuinely moved
    over the transplant path (migration counters in the row AND the
    merged registry snapshot, zero in the unified run)."""
    env = dict(os.environ, BENCH_PRESET="disagg",
               BENCH_ALLOW_CPU="1", BENCH_NO_WALL="1",
               BENCH_SKIP_PROBE="1", BENCH_METRICS_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, bench.__file__], env=env,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1                         # one-JSON-line contract
    out = json.loads(lines[0])
    assert out["metric"] == "disagg_p99_ttft_ms"
    assert out["value"] > 0
    extra = out["extra"]
    # the correctness oracle: disaggregation moves WHERE tokens are
    # computed, never WHICH tokens come out
    assert extra["outputs_identical"] is True
    # the same-seed split repeat replays bit-for-bit (tokens AND
    # migration counters — no wall times in the signature)
    assert extra["deterministic"] is True
    # the perf claim: a dedicated prefill worker flattens the
    # prompt-heavy tenant's TTFT tail
    assert out["vs_baseline"] > 1.0
    assert extra["split_p99_ttft_ms"] < extra["unified_p99_ttft_ms"]
    # pages really rode the transplant path — and only in split mode
    assert extra["migrations"] > 0
    assert extra["migrated_pages"] >= extra["migrations"]
    assert extra["unified_migrations"] == 0
    snap_path = extra["metrics_snapshot"]
    assert snap_path == str(tmp_path / "bench_metrics_disagg.json")
    snap = json.load(open(snap_path))
    assert set(snap["workers"]) == {"w0", "w1", "router"}
    merged = snap["fleet"]["counters"]
    assert merged["fleet_migrations_total"] == extra["migrations"]
    assert merged["fleet_kv_migrated_pages_total"] == \
        extra["migrated_pages"]


def test_staticcheck_cli_clean_in_process(capsys):
    """graftcheck (ISSUE 11 + 12) gates the tree this bench drives —
    bench.py itself is in the scan set. In-process like the probe
    tests above (no subprocess spawn): the CLI must exit 0 at HEAD,
    and the nine-checker run (per-file passes + the shared call
    graph) must stay inside its CI latency budget — the parse/graph
    caches are what keep interprocedural analysis from turning the
    gate into the slowest job in the pipeline."""
    from paddle_tpu.staticcheck.__main__ import main
    t0 = time.perf_counter()
    assert main([]) == 0
    elapsed = time.perf_counter() - t0
    assert "0 findings" in capsys.readouterr().out
    assert elapsed < 3.0, (
        f"nine-checker staticcheck run took {elapsed:.2f}s — the "
        f"parse-once/graph-once caches have regressed")
    # the ISSUE 12 CLI surface: CI annotation format (clean tree ->
    # zero annotation lines) and SC range syntax both run end to end
    assert main(["--format=github"]) == 0
    assert capsys.readouterr().out == ""
    assert main(["--checkers", "SC06-SC09"]) == 0
    assert "0 findings" in capsys.readouterr().out


def test_observability_dump_cli_in_process(tmp_path, capsys):
    """ISSUE 13 satellite: the ``python -m paddle_tpu.observability.dump``
    CLI, driven in-process like the staticcheck gate above. One bundle
    lands in the target dir from the process-default flight recorder +
    registry; usage errors exit 2, help exits 0."""
    from paddle_tpu.observability.dump import USAGE, main
    from paddle_tpu.observability.flight import get_flight_recorder
    get_flight_recorder().record("cli_smoke", origin="test")
    assert main([str(tmp_path), "cli-smoke"]) == 0
    printed = capsys.readouterr().out.strip()
    assert printed.endswith(".json") and os.path.exists(printed)
    bundle = json.load(open(printed))
    assert bundle["reason"] == "cli-smoke"
    assert any(e["kind"] == "cli_smoke"
               for e in bundle["flight"]["events"])
    assert "counters" in bundle["metrics"]
    # usage surface
    assert main([]) == 2
    assert USAGE in capsys.readouterr().err
    assert main(["-h"]) == 0
    assert USAGE in capsys.readouterr().out


@pytest.mark.slow
def test_step_profiler_overhead_under_5pct():
    """ISSUE 13 acceptance: the per-step phase timer must cost < 5%
    wall overhead on the CPU debug engine. Interleaved min-of-5 — the
    minimum is the honest estimator under CI noise, and interleaving
    keeps thermal/cache drift from biasing one arm."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine

    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    rng = np.random.RandomState(29)
    prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
               for n in (6, 9, 7, 11, 5, 8)]

    def drain(eng):
        reqs = [eng.submit(p, max_new_tokens=16) for p in prompts]
        while not (eng.idle() and not eng.backlog):
            eng.admit([])
            eng.decode_once()
        for r in reqs:
            r.wait(timeout=120)

    def timed(profile):
        eng = DecodeEngine(m, capacity=4, s_max=64, chunk=4,
                           block_size=8,
                           profile=True if profile else None)
        drain(eng)                 # warmup: compiles + caches
        t0 = time.perf_counter()
        drain(eng)
        return time.perf_counter() - t0

    off, on = [], []
    for _ in range(5):             # interleaved, never back-to-back
        off.append(timed(False))
        on.append(timed(True))
    ratio = min(on) / min(off)
    assert ratio < 1.05, (
        f"profiler overhead {100 * (ratio - 1):.2f}% >= 5% "
        f"(on={min(on):.4f}s off={min(off):.4f}s)")


def test_env_flag_tolerant(monkeypatch):
    for v, want in [("1", True), ("true", True), ("YES", True),
                    ("0", False), ("", False), ("false", False)]:
        monkeypatch.setenv("BENCH_SKIP_PROBE", v)
        assert bench._env_flag("BENCH_SKIP_PROBE") is want
    monkeypatch.delenv("BENCH_SKIP_PROBE")
    assert bench._env_flag("BENCH_SKIP_PROBE") is False


class _FakeProbe:
    """Stands in for the probe's Popen child (communicate/wait/pid)."""

    def __init__(self, rc=0, out="", err="", hang=False):
        self.pid = 999_999_999          # nonexistent: killpg is patched
        self.returncode = rc
        self._out = out
        self._err = err
        self._hang = hang

    def communicate(self, timeout=None):
        if self._hang:
            raise subprocess.TimeoutExpired("probe", timeout)
        return self._out, self._err

    def wait(self, timeout=None):
        return self.returncode


def _patch_probe(monkeypatch, results):
    """Install a fake Popen handing out ``results`` per attempt; returns
    the list of spawn calls. killpg is stubbed so fake pids are never
    signalled for real."""
    calls = []
    it = iter(results)

    def popen(cmd, **k):
        calls.append(cmd)
        return next(it)

    monkeypatch.setattr(subprocess, "Popen", popen)
    monkeypatch.setattr(os, "killpg", lambda pid, sig: None)
    monkeypatch.setattr(bench, "_PROBE_BACKOFF_S", (0, 0, 0))
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    return calls


def test_probe_skipped_via_env(monkeypatch):
    monkeypatch.setenv("BENCH_SKIP_PROBE", "1")

    def boom(*a, **k):  # probe must not spawn anything when skipped
        raise AssertionError("probe ran despite BENCH_SKIP_PROBE")

    monkeypatch.setattr(subprocess, "Popen", boom)
    bench.probe_backend()


def test_probe_success_first_try(monkeypatch, capsys):
    calls = _patch_probe(monkeypatch, [_FakeProbe(out="tpu 1\n")])
    bench.probe_backend()
    assert len(calls) == 1
    assert capsys.readouterr().out == ""
    assert not bench._LIVE_CHILDREN                # bookkeeping drained


def test_probe_retries_then_infra_skip(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_PROBE_ATTEMPTS", 3)
    calls = _patch_probe(monkeypatch, [_FakeProbe(hang=True)
                                       for _ in range(3)])
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 0                      # infra-skip, NOT rc=1
    assert len(calls) == 3                         # bounded retry
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert out["metric"] == "llama_pretrain_tokens_per_sec_per_chip"
    assert "hung" in out["detail"]
    assert not bench._LIVE_CHILDREN


def test_probe_propagates_non_infra_failure(monkeypatch, capsys):
    """A broken env (import error) is a real regression: rc!=0, no
    infra-skip JSON, no retry burn."""
    calls = _patch_probe(monkeypatch, [
        _FakeProbe(rc=1, err="ModuleNotFoundError: No module named "
                             "'jax'\n")])
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 1
    assert len(calls) == 1                         # no pointless retries
    assert capsys.readouterr().out == ""           # no infra-skip JSON


def test_probe_rejects_silent_cpu_fallback(monkeypatch, capsys):
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    monkeypatch.setattr(bench, "_PROBE_ATTEMPTS", 2)
    _patch_probe(monkeypatch, [_FakeProbe(out="cpu 8\n")
                               for _ in range(2)])
    with pytest.raises(SystemExit) as ei:
        bench.probe_backend()
    assert ei.value.code == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert "cpu" in out["detail"]
    # explicit opt-in keeps the CPU smoke path usable
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    _patch_probe(monkeypatch, [_FakeProbe(out="cpu 8\n")])
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    bench.probe_backend()                          # must not exit


def test_probe_recovers_on_second_attempt(monkeypatch, capsys):
    calls = _patch_probe(monkeypatch, [
        _FakeProbe(rc=1, err="jax.errors.JaxRuntimeError: UNAVAILABLE: "
                             "boom\n"),
        _FakeProbe(out="tpu 1\n")])
    bench.probe_backend()                          # must not exit
    assert len(calls) == 2
    assert capsys.readouterr().out == ""


def test_parent_handlers_reap_live_children(monkeypatch, capsys):
    """A driver SIGTERM during ANY phase (probe included) must SIGKILL
    every live child process group before the parent exits."""
    import signal
    saved = [(s, signal.getsignal(s))
             for s in (signal.SIGTERM, signal.SIGINT)]
    killed = []
    monkeypatch.setattr(os, "killpg",
                        lambda pid, sig: killed.append((pid, sig)))
    try:
        bench._install_parent_handlers()
        bench._LIVE_CHILDREN.append(424242)
        handler = signal.getsignal(signal.SIGTERM)
        with pytest.raises(SystemExit) as ei:
            handler(signal.SIGTERM, None)
        assert ei.value.code == 128 + signal.SIGTERM
        assert (424242, signal.SIGKILL) in killed
    finally:
        bench._LIVE_CHILDREN.clear()
        for s, h in saved:
            signal.signal(s, h)


@pytest.fixture
def _restore_signals():
    """run_walled installs SIGTERM/SIGINT handlers; monkeypatch cannot
    undo signal.signal, so restore by hand or a later driver SIGTERM to
    the suite would invoke the leftover forward() handler."""
    import signal
    saved = [(s, signal.getsignal(s))
             for s in (signal.SIGTERM, signal.SIGINT)]
    yield
    for s, h in saved:
        signal.signal(s, h)


class _FakeChild:
    def __init__(self, lines=(), rc=0, hang=False):
        self.pid = 12345
        self.stdout = iter(lines)
        self._rc = rc
        self._hang = hang

    def wait(self, timeout=None):
        if self._hang and timeout is not None:
            raise subprocess.TimeoutExpired("bench", timeout)
        return self._rc


def test_walled_run_times_out_to_infra_skip(monkeypatch, capsys,
                                            _restore_signals):
    monkeypatch.setattr(subprocess, "Popen",
                        lambda *a, **k: _FakeChild(hang=True))
    killed = []
    monkeypatch.setattr(os, "killpg", lambda pid, sig: killed.append(pid))
    monkeypatch.setattr(bench, "_WALL_TIMEOUT_S", 7)
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 0
    assert killed == [12345]
    out = json.loads(capsys.readouterr().out.strip())
    assert out["error"] == "backend_unavailable"
    assert "wall limit" in out["detail"]


def test_walled_timeout_after_metric_is_not_double_emitted(
        monkeypatch, capsys, _restore_signals):
    """Post-result teardown stall: the metric line already went out, so
    the wall kill must NOT add a second contradictory JSON line."""
    metric = json.dumps({"metric": "decode_tokens_per_sec", "value": 1})
    monkeypatch.setattr(
        subprocess, "Popen",
        lambda *a, **k: _FakeChild(lines=[metric + "\n"], hang=True))
    monkeypatch.setattr(os, "killpg", lambda pid, sig: None)
    monkeypatch.setattr(bench, "_WALL_TIMEOUT_S", 7)
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines == [metric]                       # exactly one JSON line


def test_walled_run_propagates_child_rc(monkeypatch, capsys,
                                        _restore_signals):
    monkeypatch.setattr(subprocess, "Popen",
                        lambda *a, **k: _FakeChild(rc=3))
    with pytest.raises(SystemExit) as ei:
        bench.run_walled()
    assert ei.value.code == 3
    assert capsys.readouterr().out == ""
