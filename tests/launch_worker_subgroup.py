"""3-process worker exercising PROPER eager subgroup collectives
(VERDICT #7): ranks {0, 2} form a 2-of-3 group and run
allreduce/broadcast/all_to_all/reduce_scatter over the per-group KV
namespace while rank 1 never enters — group-local rendezvous, no
full-world deadlock (reference: per-ring comms, process_group.h:47).

Run under ``python -m paddle_tpu.distributed.launch --nproc_per_node 3``.
"""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 3, f"expected world=3, got {world}"

    # all processes create the group in the same order (gid contract)
    g02 = dist.new_group([0, 2])

    # 2-of-3 subgroup allreduce: ranks 0 and 2 sum (1 + 3) = 4; rank 1
    # is a non-member — its tensor must be untouched and the call must
    # return immediately
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t, group=g02)
    if rank in (0, 2):
        np.testing.assert_allclose(np.asarray(t._value), np.full((4,), 4.0))
    else:
        np.testing.assert_allclose(np.asarray(t._value), np.full((4,), 2.0))

    # subgroup broadcast from global rank 2
    b = paddle.to_tensor(np.full((3,), float(rank * 10), np.float32))
    dist.broadcast(b, src=2, group=g02)
    if rank in (0, 2):
        np.testing.assert_allclose(np.asarray(b._value), np.full((3,), 20.0))

    # subgroup all_gather (order = group-rank order: [rank0, rank2])
    if rank in (0, 2):
        outs = []
        dist.all_gather(outs, paddle.to_tensor(
            np.full((2,), float(rank), np.float32)), group=g02)
        assert len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[0]._value), [0.0, 0.0])
        np.testing.assert_allclose(np.asarray(outs[1]._value), [2.0, 2.0])

    # subgroup all_to_all: group-rank r sends [base+i] to group-rank i
    if rank in (0, 2):
        gr = g02.get_group_rank(rank)
        ins = [paddle.to_tensor(np.full((2,), float(gr * 10 + i),
                                        np.float32)) for i in range(2)]
        outs = []
        dist.all_to_all(outs, ins, group=g02)
        for i in range(2):
            np.testing.assert_allclose(
                np.asarray(outs[i]._value),
                np.full((2,), float(i * 10 + gr)))

        # subgroup reduce_scatter
        rs = paddle.zeros([2])
        dist.reduce_scatter(rs, ins, group=g02)
        expect = np.full((2,), float(0 * 10 + gr) + float(1 * 10 + gr))
        np.testing.assert_allclose(np.asarray(rs._value), expect)

    # several rounds in a row (round counter + deferred KV cleanup)
    for step in range(4):
        t = paddle.to_tensor(np.full((2,), float(step), np.float32))
        dist.all_reduce(t, group=g02)
        if rank in (0, 2):
            np.testing.assert_allclose(np.asarray(t._value),
                                       np.full((2,), 2.0 * step))

    dist.barrier()
    print(f"rank {rank}: SUBGROUP_OK")


if __name__ == "__main__":
    main()
