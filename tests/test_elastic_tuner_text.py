"""Elastic manager, auto-tuner, text module tests (reference:
test/collective/fleet/test_elastic_manager.py, auto_tuner tests,
test_viterbi_decode_op.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_tuner import AutoTuner, prune_cfg
from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  ElasticManager,
                                                  ElasticStatus,
                                                  FileKVStore)


class TestElastic:
    def test_membership_and_rank_env(self, tmp_path):
        store = FileKVStore(str(tmp_path))
        a = ElasticManager(store=store, host="hostA", np=2)
        b = ElasticManager(store=store, host="hostB", np=2)
        a.register()
        b.register()
        assert sorted(a.members()) == ["hostA", "hostB"]
        assert a.exact_mode() and b.exact_mode()
        env = b.rank_env()
        assert env["PADDLE_TRAINER_ID"] == "1"
        assert env["PADDLE_TRAINERS_NUM"] == "2"

    def test_scale_change_triggers_restart(self, tmp_path):
        store = FileKVStore(str(tmp_path))
        a = ElasticManager(store=store, host="hostA", np=2)
        b = ElasticManager(store=store, host="hostB", np=2)
        a.register()
        b.register()
        assert a.watch() == ElasticStatus.HOLD   # records membership
        b.exit()                                  # hostB leaves
        assert a.watch() == ElasticStatus.RESTART
        env = a.rank_env()
        assert env["PADDLE_TRAINERS_NUM"] == "1"

    def test_ttl_lease_expiry(self, tmp_path):
        import json, os, time
        store = FileKVStore(str(tmp_path))
        m = ElasticManager(store=store, host="hostA", np=1,
                           heartbeat_interval=1)
        m.register()
        assert m.members() == ["hostA"]
        # backdate the lease past its ttl
        path = store._path(m._key())
        payload = json.load(open(path))
        payload["ts"] -= 10
        json.dump(payload, open(path, "w"))
        assert m.members() == []

    def test_launcher_status_mapping(self, tmp_path):
        class FakeProc:
            def __init__(self, code):
                self._code = code

            def poll(self):
                return self._code

        from paddle_tpu.distributed.fleet.elastic import LauncherInterface
        store = FileKVStore(str(tmp_path))
        m = ElasticManager(store=store, host="h", np=1)
        m.register()
        m.watch()  # seed membership
        lf = LauncherInterface()
        lf.procs = [FakeProc(0)]
        assert m.watch(lf) == ElasticStatus.COMPLETED
        lf.procs = [FakeProc(ELASTIC_EXIT_CODE)]
        assert m.watch(lf) == ElasticStatus.RESTART
        lf.procs = [FakeProc(1)]
        assert m.watch(lf) == ElasticStatus.ERROR


class TestAutoTuner:
    CFG = {"world_size": 8,
           "model_cfg": {"num_attention_heads": 16, "hidden_size": 1024,
                         "num_layers": 8, "global_batch_size": 16},
           "micro_batch_size": [1, 2],
           "sharding_stage": [1],
           "use_recompute": [False]}

    def test_prune_rules(self):
        ok = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
              "sharding_degree": 1, "sharding_stage": 1,
              "micro_batch_size": 2, "use_recompute": False}
        assert prune_cfg(ok, self.CFG)
        bad = dict(ok, mp_degree=3)          # 2*3*2*1 != 8
        assert not prune_cfg(bad, self.CFG)
        bad = dict(ok, pp_degree=4, mp_degree=1)  # 8 % pp==0 but layers 8%4==0 ok -> make layers fail
        cfg = dict(self.CFG, model_cfg=dict(self.CFG["model_cfg"],
                                            num_layers=6))
        assert not prune_cfg(bad, cfg)

    def test_grid_search_finds_best(self):
        tuner = AutoTuner(dict(self.CFG))

        def runner(cfg):
            # fake cost: prefer dp=8 pure data parallel, mbs 2
            if cfg["dp_degree"] == 8 and cfg["micro_batch_size"] == 2:
                return 1.0
            if cfg["pp_degree"] > 2:
                raise RuntimeError("OOM")    # simulated failure
            return 10.0 / cfg["dp_degree"] + cfg["mp_degree"]

        best = tuner.tune(runner)
        assert best["cfg"]["dp_degree"] == 8
        assert best["cfg"]["micro_batch_size"] == 2
        assert best["time"] == 1.0
        # errored trials recorded, not chosen
        errs = [h for h in tuner.recorder.history if h["error"]]
        assert errs

    def test_search_once_protocol(self):
        tuner = AutoTuner(dict(self.CFG))
        c1 = tuner.search_once()
        assert c1 is not None
        tuner.add_cfg(c1, metric_value=5.0)
        c2 = tuner.search_once()
        assert c2 is not None and c2 != c1


class TestText:
    def test_viterbi_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        b, t, n = 2, 4, 3
        pot = rng.rand(b, t, n).astype(np.float32)
        trans = rng.rand(n, n).astype(np.float32)
        from paddle_tpu.text import viterbi_decode
        scores, paths = viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            include_bos_eos_tag=False)
        import itertools
        for bi in range(b):
            best, best_path = -1e9, None
            for path in itertools.product(range(n), repeat=t):
                s = pot[bi, 0, path[0]]
                for i in range(1, t):
                    s += trans[path[i - 1], path[i]] + pot[bi, i, path[i]]
                if s > best:
                    best, best_path = s, path
            np.testing.assert_allclose(float(scores._value[bi]), best,
                                       rtol=1e-5)
            assert tuple(np.asarray(paths._value)[bi]) == best_path

    def test_uci_housing(self, tmp_path):
        rng = np.random.RandomState(0)
        data = rng.rand(50, 14)
        f = tmp_path / "housing.data"
        np.savetxt(f, data)
        from paddle_tpu.text import UCIHousing
        ds = UCIHousing(data_file=str(f), mode="train")
        assert len(ds) == 40
        x, y = ds[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_imdb_and_imikolov(self, tmp_path):
        f = tmp_path / "imdb.tsv"
        f.write_text("1\tgreat movie great fun\n0\tbad awful movie\n"
                     "1\tloved it\n0\tterrible\n1\tsuperb acting\n")
        from paddle_tpu.text import Imdb, Imikolov
        ds = Imdb(data_file=str(f), mode="train")
        test = Imdb(data_file=str(f), mode="test")
        assert len(ds) == 4 and len(test) == 1   # 80/20 split
        doc, label = ds[0]
        assert label == 1 and doc.dtype == np.int64
        f2 = tmp_path / "corpus.txt"
        f2.write_text("a b c d e f\ng h i j k l\n")
        ng = Imikolov(data_file=str(f2), window_size=5, mode="train")
        assert len(ng) > 0 and ng[0].shape == (5,)

    def test_viterbi_ragged_lengths(self):
        """Padded rows must not contribute (regression: lengths ignored)."""
        rng = np.random.RandomState(1)
        pot = rng.rand(2, 4, 3).astype(np.float32)
        trans = rng.rand(3, 3).astype(np.float32)
        from paddle_tpu.text import viterbi_decode
        # row 0 truncated to length 2: score must equal a fresh T=2 decode
        s_full, p_full = viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            lengths=paddle.to_tensor(np.array([2, 4], np.int32)),
            include_bos_eos_tag=False)
        s_short, p_short = viterbi_decode(
            paddle.to_tensor(pot[:1, :2]), paddle.to_tensor(trans),
            include_bos_eos_tag=False)
        np.testing.assert_allclose(float(s_full._value[0]),
                                   float(s_short._value[0]), rtol=1e-5)
        assert tuple(np.asarray(p_full._value)[0][:2]) == \
            tuple(np.asarray(p_short._value)[0])


class TestPlannerAndMeasuredTuning:
    """VERDICT #9: a minimal Completer/Planner proposes (dp, mp, pp,
    sharding) from model + world size via a memory/FLOPs cost model, and
    the auto-tuner gains a measure hook that runs REAL trial steps."""

    def test_planner_proposes_feasible_plan(self):
        from paddle_tpu.distributed.auto_parallel_static.planner import (
            Planner)
        from paddle_tpu.models.llama import LlamaForCausalLM
        model = LlamaForCausalLM("tiny")
        plan = Planner().plan(model, 8, batch_size=8, seq_len=256)
        assert plan.dp * plan.mp * plan.pp == 8
        assert plan.cost < float("inf")
        assert plan.memory_per_device > 0
        assert model.config.num_hidden_layers % plan.pp == 0

    def test_planner_memory_pressure_forces_model_sharding(self):
        """With a budget barely above params/dev, pure DP (full replica
        per device) must be infeasible and the plan must split the model
        (mp*pp > 1 or ZeRO-3)."""
        from paddle_tpu.distributed.auto_parallel_static.planner import (
            Planner)
        from paddle_tpu.models.llama import LlamaForCausalLM
        model = LlamaForCausalLM("tiny")
        n_params = sum(p.size for p in model.parameters())
        tight = Planner(hbm_bytes=n_params * 14 * 0.3)
        plan = tight.plan(model, 8, batch_size=8, seq_len=256)
        assert plan.mp * plan.pp > 1 or plan.zero_stage == 3
        # and an impossible budget raises with a clear message
        import pytest
        with pytest.raises(RuntimeError, match="no feasible"):
            Planner(hbm_bytes=1000).plan(model, 8, batch_size=8,
                                         seq_len=256)

    def test_engine_prepare_auto_mode(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        paddle.seed(0)
        model = LlamaForCausalLM("debug")
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        engine = dist.Engine(model=model, loss=None, optimizer=opt)
        # llama takes (ids) and Engine's loss_fn convention is (out, y) —
        # supply a causal-LM loss through the loss hook
        engine._loss = lambda out, y: paddle.nn.functional.cross_entropy(
            out[:, :-1, :].reshape([-1, out.shape[-1]]),
            y[:, 1:].reshape([-1]))
        engine.prepare(mode="auto", batch_size=8, seq_len=32)
        assert engine.plan.dp * engine.plan.mp * engine.plan.pp == 8
        ids = np.random.randint(0, 128, (8, 32), dtype=np.int32)
        loss = engine._step(paddle.to_tensor(ids), paddle.to_tensor(ids))
        assert np.isfinite(float(loss))

    def test_tuner_measures_real_trials(self):
        from paddle_tpu.distributed.auto_tuner import (AutoTuner,
                                                       trial_runner)
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn

        def model_factory():
            paddle.seed(3)
            return LlamaForCausalLM("debug")

        def make_batch():
            ids = paddle.to_tensor(
                np.random.randint(0, 128, (8, 32), dtype=np.int32))
            return ids, ids

        runner = trial_runner(model_factory, llama_loss_fn, make_batch,
                              warmup=1, iters=1)
        tuner = AutoTuner({
            "world_size": 8,
            "model_cfg": {"num_attention_heads": 4, "hidden_size": 64,
                          "num_layers": 2, "global_batch_size": 8},
            "micro_batch_size": [8],
            "sharding_stage": [0],
            "use_recompute": [False],
            "task_limit": 3,
        })
        best = tuner.tune(runner)
        assert best is not None and best["time"] > 0
        measured = [h for h in tuner.recorder.history
                    if h.get("time") is not None]
        assert len(measured) >= 1  # real steps actually ran
