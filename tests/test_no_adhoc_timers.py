"""Timer-discipline lint (ISSUE 3 satellite): serving code must stamp
time through ``paddle_tpu.observability.now`` — the one clock the
metrics registry, request traces, and engine spans share — never via
ad-hoc ``time.perf_counter()`` pairs. A raw call sneaking back into the
inference package would let a hand-rolled latency number disagree with
the trace-derived histograms, which is exactly the drift the
observability layer exists to end."""

import pathlib

INFERENCE = (pathlib.Path(__file__).resolve().parent.parent
             / "paddle_tpu" / "inference")

BANNED = "time.perf_counter"


def test_inference_package_has_no_raw_perf_counter():
    offenders = []
    for py in sorted(INFERENCE.glob("*.py")):
        text = py.read_text()
        for lineno, line in enumerate(text.splitlines(), 1):
            if BANNED in line:
                offenders.append(f"{py.name}:{lineno}: {line.strip()}")
    assert not offenders, (
        "raw time.perf_counter() in paddle_tpu/inference/ — use "
        "`from ..observability import now` instead:\n"
        + "\n".join(offenders))


def test_lint_covers_fleet_modules():
    """ISSUE 4 grew the package by fleet.py/fleet_metrics.py; the glob
    above must actually be scanning them (a rename or package move
    would silently shrink the lint's coverage)."""
    scanned = {py.name for py in INFERENCE.glob("*.py")}
    for required in ("serving.py", "fleet.py", "fleet_metrics.py",
                     "prefix_cache.py", "scheduler.py"):
        assert required in scanned, (
            f"{required} missing from the timer-lint scan set "
            f"{sorted(scanned)}")


def test_shared_clock_is_perf_counter():
    """The alias must BE the high-resolution monotonic clock (the lint
    bans the spelling, not the clock)."""
    import time

    from paddle_tpu.observability import now
    assert now is time.perf_counter
