"""Timer-discipline lint (ISSUE 3 satellite, extended by ISSUE 5,
ported to graftcheck by ISSUE 11): serving code must stamp time through
``paddle_tpu.observability.now`` — the one clock the metrics registry,
request traces, and engine spans share — never via ad-hoc
``time.perf_counter()`` pairs. A raw call sneaking back into the
inference package would let a hand-rolled latency number disagree with
the trace-derived histograms, which is exactly the drift the
observability layer exists to end.

ISSUE 5 widened the net to the observability package itself and the
stall watchdog: those modules DEFINE and CONSUME the shared clock, so
they are additionally banned from ``time.monotonic`` (the watchdog's
old clock) — everything goes through ``observability.now``. The single
exemption is the alias-definition line in ``observability/metrics.py``
(``now = time.perf_counter``), which is the one place the raw spelling
is the point.

ISSUE 11: the scan logic lives in
:class:`paddle_tpu.staticcheck.timers.AdhocTimerChecker` (SC01) and
the scan-set lists in :mod:`paddle_tpu.staticcheck.config`; this file
is a thin wrapper that keeps the historic test names (and therefore
the historic CI gate) alive. Byte-equivalence of the verdicts against
the pre-port lint is asserted in ``tests/test_staticcheck.py``.
"""

from paddle_tpu.staticcheck import AdhocTimerChecker, run
from paddle_tpu.staticcheck.config import (WATCHDOG,
                                           timer_inference_paths,
                                           timer_shared_clock_paths)


def test_inference_package_has_no_raw_perf_counter():
    res = run(sources=timer_inference_paths(),
              checkers=[AdhocTimerChecker])
    assert res.ok, (
        "raw time.perf_counter() in paddle_tpu/inference/ — use "
        "`from ..observability import now` instead:\n"
        + "\n".join(f.render() for f in res.findings))


def test_observability_and_watchdog_use_shared_clock():
    """ISSUE 5: the telemetry substrate itself must not fork the clock
    — observability/ and the stall watchdog are banned from BOTH raw
    spellings (perf_counter AND the watchdog's old monotonic), modulo
    the alias-definition line in metrics.py."""
    res = run(sources=timer_shared_clock_paths(),
              checkers=[AdhocTimerChecker])
    assert res.ok, (
        "raw timer call in observability/ or distributed/watchdog.py "
        "— use `observability.now`:\n"
        + "\n".join(f.render() for f in res.findings))


def test_lint_covers_fleet_modules():
    """ISSUE 4 grew the package by fleet.py/fleet_metrics.py and
    ISSUE 6 by qos.py/traffic.py; ISSUE 7's chunked prefill rides
    inside serving.py/scheduler.py/qos.py, ISSUE 8 added spec_decode.py
    (the n-gram drafter must stay pure — a wall clock in the draft path
    would de-determinize the verify oracle), ISSUE 9 added chaos.py
    (the fault schedule's clock is the fleet STEP INDEX), and ISSUE 10
    added sharding.py (mesh/spec construction is pure wiring), so those
    staying in the scan set keeps their timing under the lint too. The
    config group must actually be scanning them (a rename or package
    move would silently shrink the lint's coverage). QoS/traffic in
    particular must never grow a wall clock — their determinism
    contract is injected clocks only."""
    scanned = {p.name for p in timer_inference_paths()}
    for required in ("serving.py", "fleet.py", "fleet_metrics.py",
                     "prefix_cache.py", "scheduler.py", "qos.py",
                     "traffic.py", "spec_decode.py", "chaos.py",
                     "sharding.py"):
        assert required in scanned, (
            f"{required} missing from the timer-lint scan set "
            f"{sorted(scanned)}")


def test_lint_covers_observability_modules():
    """ISSUE 5 grew observability/ by slo.py/export.py; the widened
    scan set must include them and the watchdog."""
    scanned = {p.name for p in timer_shared_clock_paths()}
    for required in ("metrics.py", "tracing.py", "slo.py", "export.py"):
        assert required in scanned, (
            f"{required} missing from the observability lint scan set "
            f"{sorted(scanned)}")
    assert WATCHDOG.exists(), "distributed/watchdog.py moved"


def test_shared_clock_is_perf_counter():
    """The alias must BE the high-resolution monotonic clock (the lint
    bans the spelling, not the clock)."""
    import time

    from paddle_tpu.observability import now
    assert now is time.perf_counter
