"""Timer-discipline lint (ISSUE 3 satellite, extended by ISSUE 5):
serving code must stamp time through ``paddle_tpu.observability.now``
— the one clock the metrics registry, request traces, and engine spans
share — never via ad-hoc ``time.perf_counter()`` pairs. A raw call
sneaking back into the inference package would let a hand-rolled
latency number disagree with the trace-derived histograms, which is
exactly the drift the observability layer exists to end.

ISSUE 5 widens the net to the observability package itself and the
stall watchdog: those modules DEFINE and CONSUME the shared clock, so
they are additionally banned from ``time.monotonic`` (the watchdog's
old clock) — everything goes through ``observability.now``. The single
exemption is the alias-definition line in ``observability/metrics.py``
(``now = time.perf_counter``), which is the one place the raw spelling
is the point."""

import pathlib

_ROOT = pathlib.Path(__file__).resolve().parent.parent / "paddle_tpu"
INFERENCE = _ROOT / "inference"
OBSERVABILITY = _ROOT / "observability"
WATCHDOG = _ROOT / "distributed" / "watchdog.py"

BANNED = "time.perf_counter"
_ALIAS_DEF = "now = time.perf_counter"


def _offenders(paths, banned, allow_alias_def=False):
    out = []
    for py in paths:
        for lineno, line in enumerate(py.read_text().splitlines(), 1):
            if allow_alias_def and line.strip() == _ALIAS_DEF:
                continue            # the alias definition itself
            for token in banned:
                if token in line:
                    out.append(f"{py.name}:{lineno}: {line.strip()}")
    return out


def test_inference_package_has_no_raw_perf_counter():
    offenders = _offenders(sorted(INFERENCE.glob("*.py")), (BANNED,))
    assert not offenders, (
        "raw time.perf_counter() in paddle_tpu/inference/ — use "
        "`from ..observability import now` instead:\n"
        + "\n".join(offenders))


def test_observability_and_watchdog_use_shared_clock():
    """ISSUE 5: the telemetry substrate itself must not fork the clock
    — observability/ and the stall watchdog are banned from BOTH raw
    spellings (perf_counter AND the watchdog's old monotonic), modulo
    the alias-definition line in metrics.py."""
    paths = sorted(OBSERVABILITY.glob("*.py")) + [WATCHDOG]
    offenders = _offenders(paths, (BANNED, "time.monotonic"),
                           allow_alias_def=True)
    assert not offenders, (
        "raw timer call in observability/ or distributed/watchdog.py "
        "— use `observability.now`:\n" + "\n".join(offenders))


def test_lint_covers_fleet_modules():
    """ISSUE 4 grew the package by fleet.py/fleet_metrics.py and
    ISSUE 6 by qos.py/traffic.py; ISSUE 7's chunked prefill rides
    inside serving.py/scheduler.py/qos.py (StepBudget, plan_prefill,
    the chunk loop), ISSUE 8 added spec_decode.py (the n-gram
    drafter must stay pure — a wall clock in the draft path would
    de-determinize the verify oracle), and ISSUE 9 added chaos.py
    (the fault schedule's clock is the fleet STEP INDEX — a wall
    clock anywhere in it would break same-seed replay), and ISSUE 10
    added sharding.py (mesh/spec construction is pure wiring — a
    timer there would be a smell on its own), so those
    staying in the scan set keeps their timing under the lint too. The glob above must
    actually be scanning them
    (a rename or package move would silently shrink the lint's
    coverage). QoS/traffic in particular must never grow a wall clock —
    their determinism contract is injected clocks only."""
    scanned = {py.name for py in INFERENCE.glob("*.py")}
    for required in ("serving.py", "fleet.py", "fleet_metrics.py",
                     "prefix_cache.py", "scheduler.py", "qos.py",
                     "traffic.py", "spec_decode.py", "chaos.py",
                     "sharding.py"):
        assert required in scanned, (
            f"{required} missing from the timer-lint scan set "
            f"{sorted(scanned)}")


def test_lint_covers_observability_modules():
    """ISSUE 5 grew observability/ by slo.py/export.py; the widened
    scan set must include them and the watchdog."""
    scanned = {py.name for py in OBSERVABILITY.glob("*.py")}
    for required in ("metrics.py", "tracing.py", "slo.py", "export.py"):
        assert required in scanned, (
            f"{required} missing from the observability lint scan set "
            f"{sorted(scanned)}")
    assert WATCHDOG.exists(), "distributed/watchdog.py moved"


def test_shared_clock_is_perf_counter():
    """The alias must BE the high-resolution monotonic clock (the lint
    bans the spelling, not the clock)."""
    import time

    from paddle_tpu.observability import now
    assert now is time.perf_counter
