"""Per-op numeric tests vs NumPy reference + finite-difference-style grad
checks vs jax.grad (the OpTest analogue, reference
test/legacy_test/op_test.py:379)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t._value)


class TestCreation:
    def test_zeros_ones_full(self):
        assert _np(paddle.zeros([2, 3])).sum() == 0
        assert _np(paddle.ones([2, 3])).sum() == 6
        assert np.allclose(_np(paddle.full([2, 2], 3.5)), 3.5)

    def test_arange_linspace(self):
        assert np.allclose(_np(paddle.arange(5)), np.arange(5))
        assert np.allclose(_np(paddle.arange(1, 10, 2)), np.arange(1, 10, 2))
        assert np.allclose(_np(paddle.linspace(0, 1, 5)), np.linspace(0, 1, 5))

    def test_eye_diag_tril(self):
        assert np.allclose(_np(paddle.eye(3)), np.eye(3))
        x = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
        assert np.allclose(_np(paddle.tril(x)), np.tril(_np(x)))
        assert np.allclose(_np(paddle.triu(x, 1)), np.triu(_np(x), 1))

    def test_like(self):
        x = paddle.ones([2, 2])
        assert np.allclose(_np(paddle.zeros_like(x)), 0)
        assert np.allclose(_np(paddle.full_like(x, 7)), 7)


class TestMath:
    def test_binary_broadcast(self):
        a = paddle.to_tensor(np.random.randn(3, 1, 4).astype(np.float32))
        b = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        for op, ref in [(paddle.add, np.add), (paddle.subtract, np.subtract),
                        (paddle.multiply, np.multiply),
                        (paddle.maximum, np.maximum)]:
            assert np.allclose(_np(op(a, b)), ref(_np(a), _np(b)), atol=1e-6)

    def test_unary(self):
        x = paddle.to_tensor(np.abs(np.random.randn(4, 4)).astype(np.float32) + 0.1)
        # XLA:CPU transcendental approximations differ from libm by ~3e-5
        assert np.allclose(_np(paddle.log(x)), np.log(_np(x)), atol=5e-4)
        assert np.allclose(_np(paddle.sqrt(x)), np.sqrt(_np(x)), atol=1e-5)
        assert np.allclose(_np(paddle.rsqrt(x)), 1 / np.sqrt(_np(x)), atol=5e-4)
        assert np.allclose(_np(paddle.tanh(x)), np.tanh(_np(x)), atol=5e-4)

    def test_scale_clip(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        assert np.allclose(_np(paddle.scale(x, 2.0, 1.0)), [3, 5, 7])
        assert np.allclose(_np(paddle.clip(x, 1.5, 2.5)), [1.5, 2, 2.5])

    def test_cumsum(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert np.allclose(_np(paddle.cumsum(x, axis=1)),
                           np.cumsum(_np(x), axis=1))

    def test_add_n(self):
        xs = [paddle.ones([2, 2]) for _ in range(3)]
        assert np.allclose(_np(paddle.add_n(xs)), 3)

    def test_dunders(self):
        x = paddle.to_tensor([2.0, 4.0])
        assert np.allclose(_np(x + 1), [3, 5])
        assert np.allclose(_np(1 - x), [-1, -3])
        assert np.allclose(_np(x * x), [4, 16])
        assert np.allclose(_np(x / 2), [1, 2])
        assert np.allclose(_np(x ** 2), [4, 16])
        assert np.allclose(_np(-x), [-2, -4])
        assert bool((x > 3)._value[1])


class TestManipulation:
    def test_reshape_transpose(self):
        x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        assert paddle.reshape(x, [4, 6]).shape == [4, 6]
        assert paddle.reshape(x, [-1, 8]).shape == [3, 8]
        y = paddle.transpose(x, [2, 0, 1])
        assert y.shape == [4, 2, 3]

    def test_concat_split_stack(self):
        a = paddle.ones([2, 3])
        b = paddle.zeros([2, 3])
        c = paddle.concat([a, b], axis=0)
        assert c.shape == [4, 3]
        s = paddle.split(c, 2, axis=0)
        assert np.allclose(_np(s[0]), 1) and np.allclose(_np(s[1]), 0)
        st = paddle.stack([a, b], axis=1)
        assert st.shape == [2, 2, 3]
        parts = paddle.split(paddle.ones([7, 2]), [3, -1], axis=0)
        assert parts[1].shape == [4, 2]

    def test_squeeze_unsqueeze_flatten(self):
        x = paddle.ones([2, 1, 3, 1])
        assert paddle.squeeze(x, [1]).shape == [2, 3, 1]
        assert paddle.unsqueeze(x, [0]).shape == [1, 2, 1, 3, 1]
        assert paddle.flatten(x, 1, -1).shape == [2, 3]

    def test_gather_scatter(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        idx = paddle.to_tensor([0, 2])
        g = paddle.gather(x, idx, axis=0)
        assert np.allclose(_np(g), _np(x)[[0, 2]])
        upd = paddle.zeros([2, 3])
        s = paddle.scatter(x, idx, upd)
        assert np.allclose(_np(s)[[0, 2]], 0)

    def test_tile_expand(self):
        x = paddle.to_tensor([[1.0, 2.0]])
        assert paddle.tile(x, [2, 2]).shape == [2, 4]
        assert paddle.expand(x, [3, 2]).shape == [3, 2]

    def test_where_masked(self):
        x = paddle.to_tensor([1.0, -1.0, 2.0])
        out = paddle.where(x > 0, x, paddle.zeros_like(x))
        assert np.allclose(_np(out), [1, 0, 2])

    def test_getitem_setitem(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert np.allclose(_np(x[1]), [4, 5, 6, 7])
        assert np.allclose(_np(x[:, 1:3][0]), [1, 2])
        x[0, 0] = 99.0
        assert _np(x)[0, 0] == 99.0

    def test_cast(self):
        x = paddle.ones([2], dtype="float32")
        assert paddle.cast(x, "int32").dtype == jnp.int32


class TestReduction:
    def test_reductions(self):
        arr = np.random.randn(3, 4).astype(np.float32)
        x = paddle.to_tensor(arr)
        assert np.allclose(_np(paddle.sum(x)), arr.sum(), atol=1e-5)
        assert np.allclose(_np(paddle.mean(x, axis=1)), arr.mean(1), atol=1e-6)
        assert np.allclose(_np(paddle.max(x, axis=0)), arr.max(0))
        assert np.allclose(_np(paddle.std(x)), arr.std(ddof=1), atol=1e-5)
        assert int(paddle.argmax(x).item()) == arr.argmax()

    def test_topk_sort(self):
        x = paddle.to_tensor([3.0, 1.0, 4.0, 1.5])
        v, i = paddle.topk(x, 2)
        assert np.allclose(_np(v), [4, 3])
        assert np.allclose(_np(i), [2, 0])
        assert np.allclose(_np(paddle.sort(x)), np.sort([3, 1, 4, 1.5]))

    def test_logsumexp(self):
        arr = np.random.randn(5).astype(np.float32)
        x = paddle.to_tensor(arr)
        ref = np.log(np.exp(arr).sum())
        assert np.allclose(_np(paddle.logsumexp(x)), ref, atol=1e-5)


class TestLinalg:
    def test_matmul_transpose_flags(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(5, 4).astype(np.float32)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_y=True)
        assert np.allclose(_np(out), a @ b.T, atol=1e-5)

    def test_einsum(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        assert np.allclose(_np(out), a @ b, atol=1e-5)

    def test_norm(self):
        arr = np.random.randn(3, 4).astype(np.float32)
        x = paddle.to_tensor(arr)
        assert np.allclose(_np(paddle.norm(x)), np.linalg.norm(arr), atol=1e-5)
        assert np.allclose(_np(paddle.norm(x, p=1, axis=1)),
                           np.abs(arr).sum(1), atol=1e-5)

    def test_solve_inv(self):
        a = np.random.randn(3, 3).astype(np.float32)
        a = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        b = np.random.randn(3, 2).astype(np.float32)
        x = paddle.linalg_solve if hasattr(paddle, "linalg_solve") else None
        from paddle_tpu.ops.linalg import solve, inv, cholesky
        assert np.allclose(_np(solve(paddle.to_tensor(a), paddle.to_tensor(b))),
                           np.linalg.solve(a, b), atol=1e-4)
        assert np.allclose(_np(inv(paddle.to_tensor(a))), np.linalg.inv(a),
                           atol=1e-4)
        L = _np(cholesky(paddle.to_tensor(a)))
        assert np.allclose(L @ L.T, a, atol=1e-4)


class TestGradChecks:
    """Compare tape backward against jax.grad on the same composite
    function (numeric-gradient analogue of OpTest.check_grad)."""

    def _check(self, paddle_fn, jax_fn, *shapes, atol=1e-5):
        arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
        tensors = [paddle.to_tensor(a, stop_gradient=False) for a in arrays]
        out = paddle_fn(*tensors)
        out.backward()
        refs = jax.grad(jax_fn, argnums=tuple(range(len(arrays))))(
            *[jnp.asarray(a) for a in arrays])
        for t, r in zip(tensors, refs):
            assert np.allclose(_np(t.grad), np.asarray(r), atol=atol), \
                f"grad mismatch for {paddle_fn}"

    def test_matmul_grad(self):
        self._check(lambda a, b: paddle.sum(paddle.matmul(a, b)),
                    lambda a, b: jnp.sum(a @ b), (3, 4), (4, 2))

    def test_elementwise_chain_grad(self):
        self._check(lambda a: paddle.mean(paddle.tanh(a) * paddle.exp(a)),
                    lambda a: jnp.mean(jnp.tanh(a) * jnp.exp(a)), (5, 5))

    def test_reduction_grad(self):
        self._check(lambda a: paddle.max(a * a),
                    lambda a: jnp.max(a * a), (4, 4))

    def test_getitem_grad(self):
        self._check(lambda a: paddle.sum(a[1:, :2] ** 2),
                    lambda a: jnp.sum(a[1:, :2] ** 2), (4, 4))

    def test_concat_grad(self):
        self._check(
            lambda a, b: paddle.sum(paddle.concat([a, b], axis=1) ** 2),
            lambda a, b: jnp.sum(jnp.concatenate([a, b], axis=1) ** 2),
            (2, 3), (2, 2))

    def test_softmax_ce_grad(self):
        import paddle_tpu.nn.functional as F
        labels = np.array([0, 2, 1])
        self._check(
            lambda a: F.cross_entropy(a, paddle.to_tensor(labels)),
            lambda a: -jnp.mean(jax.nn.log_softmax(a)[jnp.arange(3), labels]),
            (3, 4))

    def test_broadcast_grad(self):
        self._check(lambda a, b: paddle.sum(a * b),
                    lambda a, b: jnp.sum(a * b), (3, 1), (1, 4))


class TestAutogradEngine:
    def test_accumulation_two_paths(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x + x * 3
        y.backward()
        assert np.allclose(_np(x.grad), [7.0])  # 2x + 3

    def test_shared_subexpr(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        h = paddle.exp(x)
        z = paddle.sum(h * h)
        z.backward()
        assert np.allclose(_np(x.grad), 2 * np.exp([1, 2]) ** 2, rtol=1e-5)

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_detach(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = (x * 2).detach()
        assert y.stop_gradient and y._grad_node is None

    def test_grad_api(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = paddle.to_tensor([4.0], stop_gradient=False)
        z = x * x * y
        gx, gy = paddle.grad(z, [x, y])
        assert np.allclose(_np(gx), [24.0])
        assert np.allclose(_np(gy), [9.0])
        assert x.grad is None  # paddle.grad must not pollute .grad

    def test_grad_allow_unused(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([1.0], stop_gradient=False)
        z = x * 2
        gx, gy = paddle.grad(z, [x, y], allow_unused=True)
        assert gy is None

    def test_backward_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []
        x.register_hook(lambda g: seen.append(1))
        (x * 2).backward()
        assert seen

    def test_retain_graph(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert np.allclose(_np(x.grad), [4.0])


class TestRandom:
    def test_seed_reproducible(self):
        paddle.seed(7)
        a = paddle.randn([4])
        paddle.seed(7)
        b = paddle.randn([4])
        assert np.allclose(_np(a), _np(b))

    def test_uniform_range(self):
        x = paddle.uniform([1000], min=2.0, max=3.0)
        assert float(paddle.min(x)) >= 2.0 and float(paddle.max(x)) <= 3.0

    def test_randperm(self):
        p = _np(paddle.randperm(10))
        assert sorted(p.tolist()) == list(range(10))

    def test_multinomial(self):
        probs = paddle.to_tensor([0.0, 0.0, 1.0])
        s = paddle.multinomial(probs, 5, replacement=True)
        assert np.all(_np(s) == 2)


class TestExtras:
    """Long-tail ops (ops/extras.py) vs NumPy (reference: tensor/math.py
    addmm/trace/diff, manipulation.py unfold/as_strided, linalg.py cdist)."""

    def test_addmm(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((2, 3)).astype("float32")
        b = rng.standard_normal((3, 4)).astype("float32")
        c = rng.standard_normal((2, 4)).astype("float32")
        out = paddle.addmm(paddle.to_tensor(c), paddle.to_tensor(a),
                           paddle.to_tensor(b), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(_np(out), 0.5 * c + 2.0 * (a @ b),
                                   atol=2e-2)

    def test_cdist(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 3)).astype("float32")
        y = rng.standard_normal((5, 3)).astype("float32")
        out = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y))
        ref = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(_np(out), ref, atol=1e-4)
        out1 = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y), p=1.0)
        ref1 = np.abs(x[:, None, :] - y[None, :, :]).sum(-1)
        np.testing.assert_allclose(_np(out1), ref1, atol=1e-4)

    def test_cummin(self):
        v, i = paddle.cummin(paddle.to_tensor(
            np.array([3., 1., 2., 0., 5.], dtype="float32")))
        assert list(_np(v)) == [3, 1, 1, 0, 0]
        assert list(_np(i)) == [0, 1, 1, 3, 3]

    def test_diag_embed_diagonal_trace(self):
        d = paddle.diag_embed(paddle.to_tensor(
            np.array([1., 2., 3.], dtype="float32")))
        np.testing.assert_allclose(_np(d), np.diag([1., 2., 3.]))
        x = np.arange(12, dtype="float32").reshape(3, 4)
        np.testing.assert_allclose(_np(paddle.diagonal(paddle.to_tensor(x))),
                                   np.diagonal(x))
        assert paddle.trace(paddle.to_tensor(x)).item() == np.trace(x)

    def test_trace_grad(self):
        x = paddle.to_tensor(np.random.randn(3, 3).astype("float32"),
                             stop_gradient=False)
        paddle.trace(x).backward()
        np.testing.assert_allclose(_np(x.grad), np.eye(3))

    def test_diff_frexp_sgn(self):
        x = np.array([1., 3., 6.], dtype="float32")
        np.testing.assert_allclose(
            _np(paddle.diff(paddle.to_tensor(x))), np.diff(x))
        m, e = paddle.frexp(paddle.to_tensor(np.array([8., 0.5], "float32")))
        np.testing.assert_allclose(_np(m) * 2.0 ** _np(e), [8., 0.5])
        np.testing.assert_allclose(
            _np(paddle.sgn(paddle.to_tensor(np.array([-2., 0., 5.], "float32")))),
            [-1., 0., 1.])

    def test_take_unfold_unflatten_as_strided(self):
        x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
        assert list(_np(paddle.take(x, paddle.to_tensor(
            np.array([0, 5, 11]))))) == [0, 5, 11]
        u = paddle.unfold(paddle.to_tensor(np.arange(9, dtype="float32")),
                          0, 3, 2)
        np.testing.assert_allclose(
            _np(u), [[0, 1, 2], [2, 3, 4], [4, 5, 6], [6, 7, 8]])
        uf = paddle.unflatten(paddle.to_tensor(
            np.zeros((2, 12), "float32")), 1, [3, 4])
        assert uf.shape == [2, 3, 4]
        s = paddle.as_strided(paddle.to_tensor(np.arange(6, dtype="float32")),
                              [2, 3], [3, 1])
        np.testing.assert_allclose(_np(s), [[0, 1, 2], [3, 4, 5]])

    def test_scatter_nd_nonzero_splits(self):
        out = paddle.scatter_nd(
            paddle.to_tensor(np.array([[1], [2], [1]])),
            paddle.to_tensor(np.array([1., 2., 3.], "float32")), [4])
        assert list(_np(out)) == [0, 4, 2, 0]
        nz = paddle.nonzero(paddle.to_tensor(np.array([0, 3, 0, 7])))
        assert _np(nz).ravel().tolist() == [1, 3]
        vs = paddle.vsplit(paddle.to_tensor(np.zeros((4, 2), "float32")), 2)
        assert len(vs) == 2 and vs[0].shape == [2, 2]
        hs = paddle.hsplit(paddle.to_tensor(np.zeros((2, 6), "float32")), [2, 4])
        assert [t.shape for t in hs] == [[2, 2], [2, 2], [2, 2]]

    def test_renorm_polygamma_vander(self):
        r = paddle.renorm(paddle.to_tensor(
            np.ones((2, 3), "float32") * 3), 2.0, 0, 1.0)
        assert abs(np.linalg.norm(_np(r)[0]) - 1.0) < 1e-3
        from scipy.special import polygamma as spg
        got = paddle.polygamma(paddle.to_tensor(
            np.array([2.0], "float32")), 1)
        np.testing.assert_allclose(_np(got), spg(1, [2.0]), atol=1e-4)
        v = paddle.vander(paddle.to_tensor(np.array([1., 2., 3.], "float32")))
        np.testing.assert_allclose(_np(v), np.vander([1., 2., 3.]))

    def test_shape_rank_broadcast_shape(self):
        x = paddle.to_tensor(np.zeros((3, 4), "float32"))
        assert list(_np(paddle.shape(x))) == [3, 4]
        assert paddle.rank(x).item() == 2
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]

    def test_linalg_cond_householder(self):
        rng = np.random.default_rng(2)
        a = rng.standard_normal((4, 4)).astype("float32")
        a = a @ a.T + 4 * np.eye(4, dtype="float32")
        t = paddle.to_tensor(a)
        for p_ in [None, "fro", 1, -2]:
            got = paddle.linalg.cond(t, p_).item()
            ref = np.linalg.cond(a, 2 if p_ is None else p_)
            assert abs(got - ref) / abs(ref) < 1e-2, (p_, got, ref)
        import scipy.linalg as sla
        m = rng.standard_normal((5, 3))
        (hq, tau), _ = sla.qr(m, mode="raw")
        q_ref = sla.lapack.dorgqr(np.asfortranarray(hq[:, :3]), tau)[0]
        got = paddle.linalg.householder_product(
            paddle.to_tensor(hq.astype("float32")),
            paddle.to_tensor(tau.astype("float32")))
        np.testing.assert_allclose(_np(got), q_ref[:, :3], atol=1e-3)


class TestInplace:
    """Inplace variants (ops/inplace.py) — value semantics, autograd
    adoption, and the reference's inplace-on-leaf guard."""

    def test_value_semantics(self):
        x = paddle.to_tensor(np.array([1., 4., 9.], "float32"))
        y = x.sqrt_()
        assert y is x
        np.testing.assert_allclose(_np(x), [1, 2, 3])
        x.add_(paddle.to_tensor(np.ones(3, "float32")))
        np.testing.assert_allclose(_np(x), [2, 3, 4])
        x.zero_()
        assert _np(x).sum() == 0
        x.fill_(7.0)
        np.testing.assert_allclose(_np(x), 7)
        m = paddle.to_tensor(np.zeros((3, 3), "float32"))
        m.fill_diagonal_(2.0)
        np.testing.assert_allclose(_np(m), 2 * np.eye(3))
        r = paddle.to_tensor(np.arange(6, dtype="float32"))
        r.reshape_([2, 3])
        assert r.shape == [2, 3]

    def test_autograd_through_inplace(self):
        import math
        w = paddle.to_tensor(np.array([0.5], "float32"), stop_gradient=False)
        z = w * 3.0
        z.tanh_()
        z.backward()
        ref = 3.0 * (1 - math.tanh(1.5) ** 2)
        assert abs(w.grad.item() - ref) < 1e-3
        # chain of two inplace mutations
        v = paddle.to_tensor(np.array([0.5], "float32"), stop_gradient=False)
        u = v * 1.0
        u.sin_()
        u.exp_()
        u.backward()
        refg = math.exp(math.sin(0.5)) * math.cos(0.5)
        assert abs(v.grad.item() - refg) < 1e-3

    def test_leaf_guard(self):
        w = paddle.to_tensor(np.array([1.0], "float32"), stop_gradient=False)
        with pytest.raises(RuntimeError):
            w.tanh_()
        # stop_gradient leaves may mutate freely
        s = paddle.to_tensor(np.array([1.0], "float32"))
        s.tanh_()

    def test_module_level_and_fills(self):
        assert hasattr(paddle, "add_") and hasattr(paddle, "tanh_")
        t = paddle.to_tensor(np.zeros((50,), "float32"))
        t.cauchy_()
        g = paddle.to_tensor(np.zeros((50,), "float32"))
        g.geometric_(0.3)
        assert _np(g).min() >= 1

    def test_setitem_grad_after_shadow_fix(self):
        w = paddle.to_tensor(np.array([1., 2., 3.], "float32"),
                             stop_gradient=False)
        a = w * 2.0
        a[0] = 5.0
        a.sum().backward()
        np.testing.assert_allclose(_np(w.grad), [0., 2., 2.])


class TestFrameworkShims:
    """Framework compat surface (framework/core.py)."""

    def test_dtype_info(self):
        fi = paddle.finfo("float32")
        assert fi.bits == 32 and fi.eps > 0 and fi.max > 1e38
        bi = paddle.finfo("bfloat16")
        assert bi.bits == 16
        ii = paddle.iinfo("int32")
        assert ii.max == 2 ** 31 - 1

    def test_places_and_modes(self):
        assert paddle.CPUPlace() == paddle.CPUPlace()
        assert paddle.CUDAPlace(0) != paddle.CPUPlace()
        assert paddle.in_dynamic_mode()
        paddle.enable_static()
        try:
            assert not paddle.in_dynamic_mode()
        finally:
            paddle.disable_static()

    def test_create_parameter_and_queries(self):
        w = paddle.create_parameter([3, 4])
        assert not w.stop_gradient and w.shape == [3, 4]
        b = paddle.create_parameter([4], is_bias=True)
        assert _np(b).sum() == 0
        assert paddle.is_floating_point(paddle.to_tensor([1.0]))
        assert paddle.is_integer(paddle.to_tensor([1]))
        assert paddle.is_tensor(w)

    def test_flops(self):
        import paddle_tpu.nn as nn
        net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                            nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
        assert paddle.flops(net, [1, 3, 8, 8]) > 0

    def test_batch_and_rng_state(self):
        r = paddle.batch(lambda: iter(range(5)), 2)
        assert [len(b) for b in r()] == [2, 2, 1]
        s = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(s)

    def test_top_level_parity_vs_reference(self):
        """Every name in the reference's top-level __all__ exists."""
        import re, pathlib
        if not pathlib.Path("/root/reference").exists():
            pytest.skip("reference Paddle checkout not present")
        ref = pathlib.Path(
            "/root/reference/python/paddle/__init__.py").read_text()
        names = set(re.findall(r"^\s+'([A-Za-z_][A-Za-z0-9_]*)',\s*$",
                               ref, re.M))
        missing = [x for x in sorted(names) if not hasattr(paddle, x)]
        assert missing == [], missing


class TestTensorMethodParity:
    def test_reference_tensor_method_surface(self):
        """Every method in the reference tensor/__init__.py
        tensor_method_func list exists on Tensor."""
        import re, pathlib
        if not pathlib.Path("/root/reference").exists():
            pytest.skip("reference Paddle checkout not present")
        t = paddle.to_tensor([1.0])
        ref = pathlib.Path(
            "/root/reference/python/paddle/tensor/__init__.py").read_text()
        names = set(re.findall(r"^\s+'([A-Za-z_][A-Za-z0-9_]*)',\s*$",
                               ref, re.M))
        missing = [n for n in sorted(names) if not hasattr(t, n)]
        assert missing == [], missing

    def test_new_methods_work(self):
        x = paddle.to_tensor(np.array([[4., 0.], [0., 9.]], "float32"))
        np.testing.assert_allclose(_np(x.inverse()),
                                   np.diag([0.25, 1 / 9.]), atol=1e-5)
        assert paddle.to_tensor([1.0]).is_floating_point()
        a = paddle.to_tensor(np.array([1., 2.], "float32"))
        assert abs(_np(a.atan2(paddle.to_tensor(
            np.array([1., 1.], "float32"))))[0] - np.arctan2(1, 1)) < 1e-6
        w = paddle.to_tensor(np.array([0.5], "float32"))
        w.erfinv_()
        assert np.isfinite(_np(w)).all()
