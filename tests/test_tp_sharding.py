"""Tensor-parallel sharded decode engine (ISSUE 10): the paged KV
pools shard over the kv-head axis, every paged program lowers through
jit + shard_map, and the decode+verify+prefill-chunk step collapses
into ONE mixed launch. The correctness contract under test is strict
BIT-parity of greedy tokens:

- tp=2 and tp=4 engines vs the unsharded engine on the same seeded
  model, with prefix cache + chunked prefill + spec decode + int8 KV
  each exercised (sharding is device wiring, never a quality trade);
- the engine vs the mp-sharded ``generate()`` path (two independent
  sharded implementations of the same math);
- ``mesh=None`` vs the r14 engine (the default path is untouched);
- a sharded fleet worker after crash + auto-restart vs the solo oracle
  (failover composes with tensor parallelism).

Host-side machinery (allocator, tables, scheduler, QoS) is replicated,
so the allocator-conservation invariant must hold unchanged on a
sharded pool under COW."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import DecodeEngine
from paddle_tpu.inference.sharding import (make_tp_mesh,
                                           validate_tp_config)


def _model(preset="debug"):
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM(preset)
    m.eval()
    return m


def _drain(eng, reqs):
    eng.admit([])
    for _ in range(10000):
        eng.decode_once()
        eng.admit([])
        if eng.idle():
            break
    return [np.asarray(r.wait(timeout=120)) for r in reqs]


def _run(m, prompts, max_new=8, mesh=None, **kw):
    eng = DecodeEngine(m, capacity=4, s_max=64, chunk=4, block_size=8,
                       mesh=mesh, **kw)
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    outs = _drain(eng, reqs)
    return outs, eng


def _prompts(rng, vocab, sizes):
    return [rng.randint(1, vocab, (n,)).astype(np.int32)
            for n in sizes]


class TestShardedEngineParity:
    def test_tp2_all_features_parity(self):
        """The acceptance oracle: prefix cache + chunked prefill + spec
        decode + int8 KV all ON, tp=2 vs unsharded — greedy tokens
        bit-identical, and the sharded engine provably spends FEWER
        device launches (batched verify + single mixed step)."""
        m = _model()
        rng = np.random.RandomState(0)
        shared = rng.randint(1, 128, (10,)).astype(np.int32)
        wave1 = [np.tile(rng.randint(1, 128, (5,)).astype(np.int32), 4),
                 shared]                             # seeds the cache
        wave2 = [np.concatenate([shared, rng.randint(  # hit + COW
                     1, 128, (7,)).astype(np.int32)]),
                 rng.randint(1, 128, (19,)).astype(np.int32)]
        kw = dict(prefix_cache=True, chunked_prefill=True,
                  spec_decode=True, kv_dtype="int8")

        def run(mesh):
            eng = DecodeEngine(m, capacity=4, s_max=64, chunk=4,
                               block_size=8, mesh=mesh, **kw)
            outs = []
            for wave in (wave1, wave2):   # second wave sees the cache
                reqs = [eng.submit(p, max_new_tokens=10) for p in wave]
                outs += _drain(eng, reqs)
            return outs, eng

        base, eng0 = run(None)
        outs, eng2 = run(make_tp_mesh(2))
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        s0, s2 = eng0.stats(), eng2.stats()
        assert s2["prefix_hit_tokens"] > 0       # the cache was hit
        assert s2["spec"]["proposed"] > 0        # speculation ran
        assert s2["prefill_chunks"] > 0          # chunked prefill ran
        # the launch-collapse claim, on the engine's own counter
        assert s2["device_calls"] < s0["device_calls"]

    def test_tp4_parity(self):
        """tp=4 over the tiny preset (4 kv heads -> 1 head per shard,
        the deepest split the model admits)."""
        m = _model("tiny")
        rng = np.random.RandomState(1)
        prompts = _prompts(rng, 900, (9, 17))
        base, _ = _run(m, prompts, chunked_prefill=True,
                       spec_decode=True)
        outs, eng = _run(m, prompts, mesh=make_tp_mesh(4),
                         chunked_prefill=True, spec_decode=True)
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        assert eng.stats()["tp_degree"] == 4
        assert eng.stats()["mesh_shape"] == {"tp": 4}

    def test_tp2_matches_mp_sharded_generate(self):
        """Two independent sharded implementations of the same math:
        the shard_map engine vs the GSPMD mp-sharded generate() path
        must agree token-for-token (and with the unsharded model)."""
        import warnings

        import paddle_tpu.distributed as dist
        m = _model()
        rng = np.random.RandomState(2)
        p = rng.randint(1, 128, (10,)).astype(np.int32)
        ref = np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=6,
            temperature=0.0)._value)[0]
        outs, _ = _run(m, [p], max_new=6, mesh=make_tp_mesh(2))
        np.testing.assert_array_equal(outs[0], ref)
        mesh = dist.ProcessMesh(shape=[1, 1, 1, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep",
                                           "mp"])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # tiny dims
            dist.shard_model_state(m, mesh)
        mp_out = np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=6,
            temperature=0.0)._value)[0]
        np.testing.assert_array_equal(outs[0], mp_out)

    def test_mesh_none_keeps_r14_outputs(self):
        """The regression satellite: a default-constructed engine
        (mesh=None) must keep producing exactly the solo greedy
        outputs — the sharding hooks compile to the identical
        programs."""
        m = _model()
        rng = np.random.RandomState(3)
        prompts = _prompts(rng, 128, (7, 12, 20))
        for kw in (dict(),
                   dict(chunked_prefill=True, spec_decode=True,
                        kv_dtype="int8", prefix_cache=True)):
            outs, eng = _run(m, prompts, **kw)
            assert eng.mesh is None
            assert eng.stats()["tp_degree"] == 1
            assert "mesh_shape" not in eng.stats()
            for p, o in zip(prompts, outs):
                ref = np.asarray(m.generate(
                    paddle.to_tensor(p[None, :]), max_new_tokens=8,
                    temperature=0.0)._value)[0]
                np.testing.assert_array_equal(o, ref)


class TestValidation:
    def test_mesh_requires_paged(self):
        m = _model()
        with pytest.raises(ValueError, match="paged"):
            DecodeEngine(m, capacity=2, s_max=64, paged=False,
                         mesh=make_tp_mesh(2))

    def test_axis_name_checked(self):
        m = _model()
        with pytest.raises(ValueError, match="tp_axis"):
            DecodeEngine(m, capacity=2, s_max=64,
                         mesh=make_tp_mesh(2, axis="model"))

    def test_divisibility_checked(self):
        m = _model()     # debug: 4 heads / 2 kv heads
        with pytest.raises(ValueError, match="kv"):
            DecodeEngine(m, capacity=2, s_max=64, mesh=make_tp_mesh(4))
        cfg = m.config
        validate_tp_config(cfg, 2)      # sanity: tp=2 is fine
        with pytest.raises(ValueError):
            validate_tp_config(cfg, 0)

    def test_mesh_needs_enough_devices(self):
        with pytest.raises(ValueError, match="devices"):
            make_tp_mesh(64)

    def test_fleet_rejects_oversubscribed_submeshes(self):
        from paddle_tpu.inference.fleet import ServingFleet
        m = _model()
        with pytest.raises(ValueError, match="devices"):
            ServingFleet(m, n_workers=5, tp_degree=2,
                         engine_kwargs=dict(capacity=2, s_max=64))


class TestShardedFleet:
    def test_sharded_workers_on_disjoint_submeshes(self):
        """n_workers x tp_degree <= devices: each worker's engine runs
        tp=2 over its own device pair, and routed traffic bit-matches
        the solo unsharded engine."""
        from paddle_tpu.inference.fleet import ServingFleet
        m = _model()
        rng = np.random.RandomState(5)
        prompts = _prompts(rng, 128, (5, 11, 19, 8))
        fleet = ServingFleet(m, n_workers=2, tp_degree=2,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8))
        try:
            devs = [tuple(w.engine.mesh.devices.flat)
                    for w in fleet.workers]
            assert len(set(devs[0]) & set(devs[1])) == 0  # disjoint
            assert fleet.stats()["tp_degree"] == 2
            reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
            fleet.run_until_drained()
            outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        finally:
            fleet.close()
        solo = []
        for p in prompts:
            o, _ = _run(m, [p])
            solo.append(o[0])
        for a, b in zip(outs, solo):
            np.testing.assert_array_equal(a, b)

    def test_sharded_worker_failover_restart_bit_matches_solo(self):
        """ISSUE 9 x ISSUE 10: crash a SHARDED worker mid-flight; the
        fleet fails over, auto-restarts it on the SAME submesh, and
        every request still completes bit-identical to the solo
        oracle."""
        from paddle_tpu.inference.chaos import (FaultEvent,
                                                FaultInjector,
                                                FaultPlan)
        from paddle_tpu.inference.fleet import (RestartPolicy,
                                                ServingFleet)
        m = _model()
        rng = np.random.RandomState(6)
        prompts = _prompts(rng, 128, (10, 10, 10, 10))
        vt = [0.0]
        fleet = ServingFleet(
            m, n_workers=2, policy="round_robin", tp_degree=2,
            engine_kwargs=dict(capacity=2, s_max=64, chunk=4,
                               block_size=8),
            restart=RestartPolicy(auto=True, backoff_base_s=1.0,
                                  clock=lambda: vt[0]))
        FaultInjector(FaultPlan(
            [FaultEvent(1, "worker_crash", "w1")])).install(fleet)
        try:
            old_devs = tuple(fleet.workers[1].engine.mesh.devices.flat)
            reqs = [fleet.submit(p, max_new_tokens=10)
                    for p in prompts]
            fleet.step()
            vt[0] += 0.25
            fleet.step()                    # w1 crashes mid-step
            assert not fleet.workers[1].healthy
            steps = 0
            while not fleet.workers[1].healthy:
                vt[0] += 0.25
                fleet.step()
                steps += 1
                assert steps <= 6, "restart missed the backoff bound"
            # the rebuilt worker reconstructed the SAME submesh
            new_devs = tuple(fleet.workers[1].engine.mesh.devices.flat)
            assert new_devs == old_devs
            assert fleet.workers[1].engine.stats()["tp_degree"] == 2
            fleet.run_until_drained()
            outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        finally:
            fleet.close()
        for p, o in zip(prompts, outs):
            ref = np.asarray(m.generate(
                paddle.to_tensor(p[None, :]), max_new_tokens=10,
                temperature=0.0)._value)[0]
            np.testing.assert_array_equal(o, ref)


class TestShardedPoolInvariants:
    def test_allocator_conservation_under_cow(self):
        """The allocator stays host-side precisely because its
        decisions are device-count-independent: under prefix sharing +
        COW on a SHARDED pool the conservation identity
        (total_allocated - total_freed == used) must hold at every
        step, and the final occupancy must match the unsharded engine
        page-for-page."""
        m = _model()
        rng = np.random.RandomState(7)
        shared = rng.randint(1, 128, (10,)).astype(np.int32)  # 8+2:
        #                 the 2-token tail page is the COW trigger
        prompts = [shared,
                   np.concatenate([shared, rng.randint(
                       1, 128, (5,)).astype(np.int32)]),
                   np.concatenate([shared, rng.randint(
                       1, 128, (9,)).astype(np.int32)])]

        def run(mesh):
            eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                               block_size=8, prefix_cache=True,
                               mesh=mesh)
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            eng.admit([])
            for _ in range(10000):
                eng.decode_once()
                st = eng._alloc.stats()
                assert (st["total_allocated"] - st["total_freed"]
                        == st["used"])
                eng.admit([])
                if eng.idle():
                    break
            outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
            return outs, eng

        base, eng0 = run(None)
        outs, eng2 = run(make_tp_mesh(2))
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        st0, st2 = eng0._alloc.stats(), eng2._alloc.stats()
        assert st2["total_allocated"] - st2["total_freed"] \
            == st2["used"]
        # replicated allocator: page accounting identical by value
        for key in ("used", "total_allocated", "total_freed",
                    "high_watermark"):
            assert st2[key] == st0[key], key
        assert eng2.stats()["prefix_hit_tokens"] \
            == eng0.stats()["prefix_hit_tokens"] > 0

    def test_pool_arrays_actually_sharded(self):
        """The tentpole's point: the per-device KV footprint is
        1/tp of the pool (the kv-head axis is split, not copied)."""
        m = _model()
        eng = DecodeEngine(m, capacity=2, s_max=64, block_size=8,
                           mesh=make_tp_mesh(2), kv_dtype="int8")
        for arr in (eng._kp, eng._vp):
            shard = arr.addressable_shards[0]
            assert shard.data.shape[3] == arr.shape[3] // 2
        for arr in (eng._kscale, eng._vscale):
            shard = arr.addressable_shards[0]
            assert shard.data.shape[2] == arr.shape[2] // 2

    def test_device_calls_gauge_and_counter(self):
        """Telemetry satellite: engine_device_calls_total counts every
        launch and engine_tp_degree reads the mesh, with the
        worker-labeled snapshot intact."""
        m = _model()
        rng = np.random.RandomState(8)
        outs, eng = _run(m, _prompts(rng, 128, (9,)),
                         mesh=make_tp_mesh(2), spec_decode=True)
        snap = eng.metrics.snapshot()
        assert snap["gauges"]["engine_tp_degree"] == 2
        assert snap["counters"]["engine_device_calls_total"] \
            == eng.stats()["device_calls"] > 0
