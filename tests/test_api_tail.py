"""Round-2 API-tail coverage: functional autodiff, LBFGS, weight/spectral
norm, signal, fft Hermitian, sparse tail, asp, incubate graph ops, shims
(reference: the corresponding python/paddle modules)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _np(t):
    return np.asarray(t._value)


class TestFunctionalAutodiff:
    def test_jacobian_hessian_graph_forms(self):
        x = paddle.to_tensor(np.array([1., 2., 3.], "float32"),
                             stop_gradient=False)
        y = x * x
        J = paddle.autograd.jacobian(y, x)
        np.testing.assert_allclose(_np(J), np.diag([2., 4., 6.]), atol=1e-5)
        x2 = paddle.to_tensor(np.array([1., 2.], "float32"),
                              stop_gradient=False)
        z = (x2 * x2 * x2).sum()
        H = paddle.autograd.hessian(z, x2)
        np.testing.assert_allclose(_np(H), np.diag([6., 12.]), atol=1e-4)

    def test_incubate_jvp_vjp(self):
        import paddle_tpu.incubate.autograd as ia
        f = lambda t: paddle.tanh(t)
        x = paddle.to_tensor(np.array([0.5], "float32"))
        v = paddle.to_tensor(np.array([1.0], "float32"))
        _, tan = ia.jvp(f, x, v)
        _, cot = ia.vjp(f, x, v)
        ref = 1 - np.tanh(0.5) ** 2
        assert abs(_np(tan)[0] - ref) < 1e-6
        assert abs(_np(cot)[0] - ref) < 1e-6
        Jc = ia.Jacobian(lambda t: t * t,
                         paddle.to_tensor(np.array([1., 2.], "float32")))
        np.testing.assert_allclose(_np(Jc[:]), np.diag([2., 4.]), atol=1e-5)


class TestLBFGS:
    def test_least_squares_convergence(self):
        rng = np.random.default_rng(0)
        A = rng.standard_normal((6, 3)).astype("float32")
        b = rng.standard_normal(6).astype("float32")
        x = paddle.to_tensor(np.zeros(3, "float32"), stop_gradient=False)
        opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=30,
                                     line_search_fn="strong_wolfe",
                                     parameters=[x])

        def closure():
            r = paddle.to_tensor(A) @ x - paddle.to_tensor(b)
            loss = (r * r).sum()
            loss.backward()
            return loss

        opt.step(closure)
        x_star = np.linalg.lstsq(A, b, rcond=None)[0]
        np.testing.assert_allclose(_np(x), x_star, atol=1e-3)


class TestWeightReparam:
    def test_weight_norm_roundtrip(self):
        from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
        lin = nn.Linear(4, 3)
        w0 = _np(lin.weight).copy()
        weight_norm(lin, "weight", dim=1)
        x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
        ref = _np(x) @ w0 + _np(lin.bias)
        np.testing.assert_allclose(_np(lin(x)), ref, atol=1e-4)
        loss = (lin(x) ** 2).sum()
        loss.backward()
        assert lin.weight_g.grad is not None and lin.weight_v.grad is not None
        remove_weight_norm(lin, "weight")
        np.testing.assert_allclose(_np(lin(x)), ref, atol=1e-4)

    def test_spectral_norm_sigma_one(self):
        from paddle_tpu.nn.utils import spectral_norm
        lin = nn.Linear(8, 6)
        spectral_norm(lin, "weight", n_power_iterations=20)
        lin(paddle.to_tensor(np.random.randn(1, 8).astype("float32")))
        sv = np.linalg.svd(_np(lin.weight), compute_uv=False)[0]
        assert abs(sv - 1.0) < 0.05

    def test_spectral_norm_grad_flows_through_sigma(self):
        # sigma = u.(W v) must stay on the tape (reference
        # spectral_norm_hook.py divides by the live sigma tensor): analytic
        # grads must match finite differences. n_power_iterations=0 keeps
        # the persisted u fixed so FD evaluates a deterministic function.
        from paddle_tpu.nn.utils import spectral_norm
        lin = nn.Linear(3, 2)
        spectral_norm(lin, "weight", n_power_iterations=0)
        x = paddle.to_tensor(np.random.randn(4, 3).astype("float32"))
        w0 = _np(lin.weight_orig).copy()

        def loss_with(w):
            lin.weight_orig._in_place_update(paddle.to_tensor(w)._value)
            return float((lin(x) ** 2).sum())

        lin.weight_orig._in_place_update(paddle.to_tensor(w0)._value)
        out = (lin(x) ** 2).sum()
        out.backward()
        g = _np(lin.weight_orig.grad)
        eps, fd = 1e-3, np.zeros_like(w0)
        for i in range(w0.shape[0]):
            for j in range(w0.shape[1]):
                wp, wm = w0.copy(), w0.copy()
                wp[i, j] += eps
                wm[i, j] -= eps
                fd[i, j] = (loss_with(wp) - loss_with(wm)) / (2 * eps)
        assert np.abs(g - fd).max() / (np.abs(fd).max() + 1e-9) < 2e-2

    def test_spectral_norm_instances_differ_and_respect_seed(self):
        from paddle_tpu.nn.utils import spectral_norm
        paddle.seed(11)
        a = spectral_norm(nn.Linear(8, 6), "weight")
        b = spectral_norm(nn.Linear(8, 6), "weight")
        # distinct instances draw distinct power-iteration vectors: with
        # identical weights and zero iterations, sigma = ||W^T u|| depends
        # only on the drawn u, so the normalized weights must differ
        assert not np.allclose(_np(a.weight), _np(a.weight_orig))
        e = nn.Linear(8, 6)
        f = nn.Linear(8, 6)
        f.weight._in_place_update(e.weight._value)
        spectral_norm(e, "weight", n_power_iterations=0)
        spectral_norm(f, "weight", n_power_iterations=0)
        assert not np.allclose(_np(e.weight), _np(f.weight))
        paddle.seed(11)
        c = spectral_norm(nn.Linear(8, 6), "weight")
        d = spectral_norm(nn.Linear(8, 6), "weight")
        np.testing.assert_allclose(_np(a.weight_orig), _np(c.weight_orig))
        np.testing.assert_allclose(_np(a.weight), _np(c.weight), atol=1e-6)
        np.testing.assert_allclose(_np(b.weight), _np(d.weight), atol=1e-6)


class TestSignal:
    def test_stft_istft_roundtrip(self):
        from paddle_tpu import signal as S
        x = np.sin(np.linspace(0, 40 * np.pi, 1024)).astype("float32")
        w = np.hanning(256).astype("float32")
        spec = S.stft(paddle.to_tensor(x), 256, hop_length=64,
                      window=paddle.to_tensor(w))
        assert spec.shape == [129, 17]
        rec = S.istft(spec, 256, hop_length=64, window=paddle.to_tensor(w),
                      length=1024)
        assert np.abs(_np(rec) - x)[128:-128].max() < 1e-3


class TestFftHermitian:
    def test_hfft2_matches_scipy(self):
        import scipy.fft as sfft
        x = (np.random.randn(4, 5) + 1j * np.random.randn(4, 5)).astype(
            "complex64")
        np.testing.assert_allclose(_np(paddle.fft.hfft2(paddle.to_tensor(x))),
                                   sfft.hfft2(x), atol=1e-3)
        xr = np.random.randn(4, 6).astype("float32")
        np.testing.assert_allclose(
            _np(paddle.fft.ihfft2(paddle.to_tensor(xr))),
            sfft.ihfft2(xr), atol=1e-5)


class TestLinalgTail:
    def test_lu_unpack_reconstructs(self):
        A = np.random.randn(4, 4).astype("float32")
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(A))
        P, L, U = paddle.linalg.lu_unpack(lu_t, piv)
        np.testing.assert_allclose(_np(P) @ _np(L) @ _np(U), A, atol=1e-4)

    def test_pca_lowrank_top_singulars(self):
        X = np.random.randn(20, 5).astype("float32")
        u, s, v = paddle.linalg.pca_lowrank(paddle.to_tensor(X), q=3)
        Xc = X - X.mean(0)
        s_ref = np.linalg.svd(Xc, compute_uv=False)[:3]
        np.testing.assert_allclose(_np(s), s_ref, rtol=1e-3)


class TestSparseTail:
    def test_unary_binary_tail(self):
        import paddle_tpu.sparse as sp
        d = np.array([[0., .5], [.2, 0.]], "float32")
        coo = sp.sparse_coo_tensor(
            paddle.to_tensor(np.array([[0, 1], [1, 0]])),
            paddle.to_tensor(np.array([.5, .2], "float32")), [2, 2])
        np.testing.assert_allclose(_np(sp.asin(coo).to_dense()),
                                   np.arcsin(d), atol=1e-6)
        np.testing.assert_allclose(
            _np(sp.mv(coo, paddle.to_tensor(np.ones(2, "float32")))),
            d @ [1, 1])
        am = sp.addmm(paddle.to_tensor(np.ones((2, 2), "float32")), coo,
                      paddle.to_tensor(np.eye(2, dtype="float32")),
                      beta=0.5, alpha=2.0)
        np.testing.assert_allclose(_np(am), 0.5 + 2 * d)
        assert abs(sp.sum(coo).item() - 0.7) < 1e-6
        assert sp.slice(coo, [0], [0], [1]).shape == [1, 2]


class TestASP:
    def test_prune_and_decorate(self):
        import paddle_tpu.incubate as inc
        net = nn.Linear(8, 8)
        inc.asp.prune_model(net)
        assert abs(inc.asp.calculate_density(net.weight) - 0.5) < 0.01
        opt = inc.asp.decorate(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()))
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        loss = (net(x) ** 2).sum()
        loss.backward()
        opt.step()
        assert abs(inc.asp.calculate_density(net.weight) - 0.5) < 0.01


class TestIncubateGraphAndMisc:
    def test_softmax_mask_fuse_upper_triangle(self):
        import paddle_tpu.incubate as inc
        out = inc.softmax_mask_fuse_upper_triangle(paddle.to_tensor(
            np.random.randn(1, 1, 4, 4).astype("float32")))
        arr = _np(out)[0, 0]
        assert abs(arr[0, 0] - 1.0) < 1e-5 and arr[0, 1] < 1e-6
        np.testing.assert_allclose(arr.sum(-1), np.ones(4), atol=1e-5)

    def test_segment_reexports(self):
        import paddle_tpu.incubate as inc
        out = inc.segment_sum(
            paddle.to_tensor(np.array([1., 2., 3.], "float32")),
            paddle.to_tensor(np.array([0, 0, 1])))
        np.testing.assert_allclose(_np(out), [3., 3.])

    def test_utils_and_shims(self):
        import paddle_tpu.utils as U
        assert U.require_version("0.0.1")
        with pytest.raises(ImportError):
            U.try_import("definitely_not_a_module_xyz")
        assert paddle.amp.is_bfloat16_supported()
        paddle.jit.set_verbosity(0)
        from paddle_tpu.profiler import SortedKeys, SummaryView
        assert SortedKeys.CPUTotal is not None
        from paddle_tpu.inference import DataType, get_num_bytes_of_data_type
        assert get_num_bytes_of_data_type(DataType.BFLOAT16) == 2
        s = paddle.device.current_stream()
        with paddle.device.stream_guard(s):
            pass


class TestFusedNN:
    """incubate.nn fused layers + functionals (reference:
    incubate/nn/layer/fused_transformer.py)."""

    def test_fused_matmul_bias(self):
        import paddle_tpu.incubate.nn.functional as FF
        x = paddle.to_tensor(np.random.randn(2, 6, 16).astype("float32"))
        w = np.random.randn(16, 8).astype("float32")
        b = np.random.randn(8).astype("float32")
        got = FF.fused_matmul_bias(x, paddle.to_tensor(w),
                                   paddle.to_tensor(b))
        np.testing.assert_allclose(_np(got), _np(x) @ w + b, atol=1e-4)

    def test_fused_mha_matches_manual(self):
        import paddle_tpu.incubate.nn as inn
        B, S, D, H = 2, 6, 16, 4
        x = paddle.to_tensor(np.random.randn(B, S, D).astype("float32"))
        mha = inn.FusedMultiHeadAttention(D, H, dropout_rate=0.0,
                                          attn_dropout_rate=0.0)
        mha.eval()
        out = mha(x)
        qkv = np.einsum("bse,nhde->bsnhd", _np(x), _np(mha.qkv_weight)) \
            + _np(mha.qkv_bias)[None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        sc = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D // H)
        pr = np.exp(sc - sc.max(-1, keepdims=True))
        pr /= pr.sum(-1, keepdims=True)
        ctx = np.einsum("bhst,bthd->bshd", pr, v).reshape(B, S, D)
        res = _np(x) + ctx @ _np(mha.linear_weight) + _np(mha.linear_bias)
        mean = res.mean(-1, keepdims=True)
        var = res.var(-1, keepdims=True)
        ref = (res - mean) / np.sqrt(var + 1e-5) * _np(mha.ln_scale) \
            + _np(mha.ln_bias)
        np.testing.assert_allclose(_np(out), ref, atol=1e-3)

    def test_fused_ffn_encoder_multitransformer_ecmoe(self):
        import paddle_tpu.incubate.nn as inn
        B, S, D, H = 2, 5, 16, 4
        x = paddle.to_tensor(np.random.randn(B, S, D).astype("float32"))
        ffn = inn.FusedFeedForward(D, 32, dropout_rate=0.0)
        out = ffn(x)
        loss = (out * out).sum()
        loss.backward()
        assert ffn.linear1_weight.grad is not None
        enc = inn.FusedTransformerEncoderLayer(D, H, 32, dropout_rate=0.0)
        enc.eval()
        assert enc(x).shape == [B, S, D]
        mt = inn.FusedMultiTransformer(D, H, 32, num_layers=2)
        mt.eval()
        assert mt(x).shape == [B, S, D]
        moe = inn.FusedEcMoe(D, 32, 4, "gelu")
        assert moe(x).shape == [B, S, D]

    def test_varlen_attention_masks(self):
        import paddle_tpu.incubate.nn.functional as FF
        q = paddle.to_tensor(np.random.randn(2, 2, 4, 8).astype("float32"))
        out = FF.variable_length_memory_efficient_attention(
            q, q, q, paddle.to_tensor(np.array([2, 4])),
            paddle.to_tensor(np.array([2, 4])))
        np.testing.assert_allclose(_np(out)[0, :, 2:], 0.0)


class TestSelectedRowsStringTensor:
    """SURVEY item 2 gap notes: SelectedRows (sparse-gradient exchange
    format, reference selected_rows.h:27) and StringTensor."""

    def test_merge_and_to_dense(self):
        from paddle_tpu.framework import SelectedRows
        sr = SelectedRows([2, 0, 2], np.array(
            [[1., 1.], [2., 2.], [3., 3.]], np.float32), height=4)
        assert sr.shape == [4, 2] and sr.has_key(2) and not sr.has_key(1)
        m = sr.merge()
        np.testing.assert_array_equal(m.rows(), [0, 2])
        np.testing.assert_allclose(_np(m.value()), [[2, 2], [4, 4]])
        dense = _np(sr.to_dense())
        np.testing.assert_allclose(
            dense, [[2, 2], [0, 0], [4, 4], [0, 0]])

    def test_from_dense_grad_and_ps_push(self):
        from paddle_tpu.framework import SelectedRows
        import paddle_tpu.distributed.ps as ps
        # dense embedding grad where only rows {1, 3} were touched
        g = np.zeros((8, 4), np.float32)
        g[1] = 1.0
        g[3] = 2.0
        sr = SelectedRows.from_dense_grad(paddle.to_tensor(g), [3, 1, 3])
        assert sr.rows().tolist() == [1, 3]
        table = ps.MemorySparseTable(4, init_std=0.0, learning_rate=0.1)

        class _Client:  # direct-table client shim
            def push_sparse(self, tid, ids, grads):
                table.push(ids, grads)
        sr.push_to_ps(_Client(), 0)
        np.testing.assert_allclose(table.pull([1]), -0.1, rtol=1e-5)
        np.testing.assert_allclose(table.pull([3]), -0.2, rtol=1e-5)
        np.testing.assert_allclose(table.pull([0]), 0.0)

    def test_string_tensor(self):
        from paddle_tpu.framework import StringTensor
        st = StringTensor([["ab", "cd"], ["ef", "gh"]])
        assert st.shape == [2, 2] and st.dtype == "pstring"
        assert st[0][1] == "cd"
        assert st[1].shape == [2]
        assert len(st) == 2
        assert st == StringTensor([["ab", "cd"], ["ef", "gh"]])
