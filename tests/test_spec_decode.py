"""Self-speculative decoding + int8 paged KV (ISSUE 8).

Tentpole coverage: the n-gram drafter's contract (deterministic, limit-
clamped, recency-preferring), the verify/accept step's correctness
oracle (spec ON outputs bit-match plain greedy decode — including under
chunked prefill and preemption mid-flight), implicit KV rollback
accounting (allocator conservation under reject-heavy load), and the
int8 quantized pool: round-trip error bounds, the running-max ratio-1.0
no-op, pool-edge scale indexing, Pallas-interpret vs XLA-reference
bit-exactness, and engine-level greedy token parity with fp KV.

Satellite coverage: spec lifecycle/metric accounting (proposed/accepted
counters, accept-length histogram, accept-rate gauge, spec_verify trace
marks) and multi-token TPOT accounting (decode_chunk marks carry
n_tokens; served_tokens counts emissions, not steps).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.spec_decode import NgramDrafter


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


def _drive(eng, pending, iters=600):
    for _ in range(iters):
        eng.admit(pending)
        eng.decode_once()
        if eng.idle() and not pending:
            return
    raise AssertionError("engine did not drain the workload")


def _run(m, prompts, max_new, iters=600, **kw):
    from paddle_tpu.inference.serving import DecodeEngine, _Request
    eng = DecodeEngine(m, **kw)
    reqs = [_Request(p, max_new) for p in prompts]
    _drive(eng, list(reqs), iters=iters)
    return eng, reqs, [r.wait(timeout=1) for r in reqs]


class TestNgramDrafter:
    def test_periodic_tail_drafts_the_continuation(self):
        d = NgramDrafter(max_draft=4)
        ctx = np.asarray([5, 6, 7, 5, 6, 7, 5, 6], np.int32)
        # suffix [7, 5, 6] matched at position 2 -> continue with the
        # tokens that followed it (everything resident past the match)
        np.testing.assert_array_equal(d.propose(ctx), [7, 5, 6])

    def test_no_match_returns_empty(self):
        d = NgramDrafter(max_draft=4)
        assert d.propose(np.arange(1, 9, dtype=np.int32)).size == 0

    def test_limit_clamps_draft_length(self):
        d = NgramDrafter(max_draft=4)
        ctx = np.asarray([5, 6, 7, 5, 6, 7, 5, 6], np.int32)
        assert d.propose(ctx, limit=2).size <= 2
        assert d.propose(ctx, limit=0).size == 0

    def test_deterministic_and_pure(self):
        d = NgramDrafter(max_draft=4)
        rng = np.random.RandomState(11)
        for _ in range(50):
            ctx = rng.randint(0, 8, (rng.randint(2, 40),)).astype(
                np.int32)
            before = ctx.copy()
            a, b = d.propose(ctx), d.propose(ctx)
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(ctx, before)  # no mutation
            assert a.size <= 4 and a.dtype == np.int32

    def test_drafts_only_tokens_seen_in_context(self):
        d = NgramDrafter(max_draft=4)
        rng = np.random.RandomState(12)
        for _ in range(50):
            ctx = rng.randint(0, 6, (rng.randint(2, 30),)).astype(
                np.int32)
            assert set(d.propose(ctx)) <= set(ctx.tolist())


class TestSpecEngine:
    def test_knob_validation(self):
        from paddle_tpu.inference.serving import DecodeEngine
        with pytest.raises(ValueError, match="paged"):
            DecodeEngine(_model(), paged=False, spec_decode=True)
        with pytest.raises(ValueError, match="paged"):
            DecodeEngine(_model(), paged=False, kv_dtype="int8")
        with pytest.raises(ValueError, match="kv_dtype"):
            DecodeEngine(_model(), kv_dtype="fp16")
        with pytest.raises(ValueError, match="spec_max_draft"):
            DecodeEngine(_model(), spec_decode=True, spec_max_draft=0)

    def test_spec_bit_matches_greedy(self):
        """The tentpole oracle: spec ON emits EXACTLY the plain greedy
        tokens (every accepted token is the verify program's argmax),
        on a mix of draft-friendly periodic prompts and draft-hostile
        random ones — and actually accepts drafts on the former."""
        m = _model()
        rng = np.random.RandomState(7)
        prompts = [np.tile(rng.randint(1, 128, (8,)).astype(np.int32), 4),
                   rng.randint(1, 128, (17,)).astype(np.int32),
                   np.tile(rng.randint(1, 128, (6,)).astype(np.int32), 5)]
        solo = [_solo(m, p, 16) for p in prompts]
        kw = dict(capacity=4, s_max=128, chunk=4, block_size=16)
        _, _, plain = _run(m, prompts, 16, **kw)
        eng, reqs, spec = _run(m, prompts, 16, spec_decode=True, **kw)
        for s, a, b in zip(solo, plain, spec):
            np.testing.assert_array_equal(a, s)
            np.testing.assert_array_equal(b, s)
        st = eng.stats()["spec"]
        assert st["proposed"] > 0 and st["accepted"] > 0
        assert st["verify_steps"] > 0
        assert 1.0 <= st["tokens_per_step"] <= eng.spec_max_draft + 1
        # lifecycle: every verify step left a spec_verify trace mark
        assert sum(r.trace.count("spec_verify") for r in reqs) \
            == st["verify_steps"]

    def test_spec_with_chunked_prefill_bit_matches(self):
        m = _model()
        rng = np.random.RandomState(8)
        prompts = [np.tile(rng.randint(1, 128, (7,)).astype(np.int32), 5),
                   rng.randint(1, 128, (29,)).astype(np.int32)]
        kw = dict(capacity=4, s_max=128, chunk=4, block_size=16)
        _, _, plain = _run(m, prompts, 12, **kw)
        _, _, spec = _run(m, prompts, 12, spec_decode=True,
                          chunked_prefill=True, **kw)
        for a, b in zip(plain, spec):
            np.testing.assert_array_equal(a, b)

    def test_spec_survives_preemption(self):
        """A pool small enough that decode growth must preempt rows:
        preempted-mid-flight spec rows re-queue with their full emitted
        history and the final outputs still bit-match solo greedy."""
        m = _model()
        rng = np.random.RandomState(9)
        prompts = [rng.randint(1, 128, (24,)).astype(np.int32)
                   for _ in range(3)]
        eng, reqs, out = _run(
            m, prompts, 16, capacity=3, s_max=64, chunk=4,
            block_size=8, n_blocks=13, spec_decode=True, iters=2000)
        for p, o in zip(prompts, out):
            np.testing.assert_array_equal(o, _solo(m, p, 16))
        assert eng.stats()["preempted"] > 0   # the scenario happened

    def test_rollback_conserves_allocator_accounting(self):
        """Rejected drafts roll back by lens rewind — no page churn.
        Under a reject-heavy random workload the allocator conservation
        invariant holds and the pool drains to empty at idle."""
        m = _model()
        rng = np.random.RandomState(10)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (9, 17, 23, 31)]
        eng, _, out = _run(m, prompts, 12, capacity=4, s_max=96,
                           chunk=4, block_size=16, prefix_cache=False,
                           spec_decode=True)
        assert all(o is not None for o in out)
        a = eng._alloc
        assert a.total_allocated - a.total_freed == a.in_use == 0

    def test_qos_accounting_reproduces_bit_for_bit(self):
        """Acceptance: accept-rate and per-tenant token accounting
        reproduce EXACTLY across a repeat of the same seeded two-tenant
        workload — speculation adds no nondeterminism (tenants are
        charged accepted tokens only, and the accept chain is a pure
        function of the weights and prompts)."""
        from paddle_tpu.inference.qos import QoSPolicy, TenantPolicy
        from paddle_tpu.inference.serving import DecodeEngine
        m = _model()
        rng = np.random.RandomState(30)
        prompts = [np.tile(rng.randint(1, 128, (6,)).astype(np.int32),
                           4) for _ in range(4)]

        def once():
            qos = QoSPolicy([
                TenantPolicy("a", rate=1e6, burst=1e6, weight=2.0),
                TenantPolicy("b", rate=1e6, burst=1e6)])
            eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                               block_size=16, qos=qos, spec_decode=True)
            reqs = [eng.submit(p, max_new_tokens=12,
                               tenant="ab"[i % 2])
                    for i, p in enumerate(prompts)]
            _drive(eng, [])
            outs = [np.asarray(r.wait(timeout=5)) for r in reqs]
            return eng.stats()["spec"], qos.stats(), outs

        s1, q1, o1 = once()
        s2, q2, o2 = once()
        assert s1 == s2
        assert q1 == q2
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(a, b)

    def test_served_tokens_counts_emissions_not_steps(self):
        """Multi-token TPOT fix: decode_chunk marks carry n_tokens, so
        a request's served_tokens equals its emitted decode tokens
        (max_new minus the prefill-produced first token) in BOTH the
        plain chunked path and the spec path."""
        m = _model()
        rng = np.random.RandomState(13)
        p = np.tile(rng.randint(1, 128, (8,)).astype(np.int32), 4)
        kw = dict(capacity=2, s_max=128, chunk=4, block_size=16)
        _, (rp,), _ = _run(m, [p], 16, **kw)
        _, (rs,), _ = _run(m, [p], 16, spec_decode=True, **kw)
        assert rp.trace.served_tokens == 15
        assert rs.trace.served_tokens == 15
        # spec took fewer decode marks for the same tokens
        assert rs.trace.count("decode_chunk") \
            <= rp.trace.count("decode_chunk") * 4


class TestInt8PagedKV:
    def test_token_insert_round_trip_bound(self):
        """One quantized write: dequant error per element is at most
        half the per-(page, head) scale step."""
        import jax.numpy as jnp
        from paddle_tpu.kernels.paged_attention import KV_SCALE_EPS
        from paddle_tpu.models.llama import _quantized_token_insert
        rng = np.random.RandomState(20)
        tok = rng.randn(2, 3, 8).astype(np.float32)
        pool = jnp.zeros((4, 16, 3, 8), jnp.int8)
        scales = jnp.full((4, 3), KV_SCALE_EPS, jnp.float32)
        page = jnp.asarray([1, 2], jnp.int32)
        off = jnp.asarray([0, 5], jnp.int32)
        pool, scales = _quantized_token_insert(
            pool, scales, page, off, jnp.asarray(tok))
        pool, scales = np.asarray(pool), np.asarray(scales)
        for b, (pg, o) in enumerate([(1, 0), (2, 5)]):
            deq = pool[pg, o].astype(np.float32) * scales[pg][:, None]
            step = scales[pg][:, None]
            assert np.all(np.abs(deq - tok[b]) <= 0.5 * step + 1e-7)
            # scale is exactly amax/127 for a fresh page
            np.testing.assert_allclose(
                scales[pg], np.abs(tok[b]).max(-1) / 127.0, rtol=1e-6)

    def test_running_max_noop_keeps_codes_bit_identical(self):
        """Inserting a SMALLER token into a page must not perturb the
        resident codes: ratio old/new == 1.0 exactly, round(q*1.0)==q."""
        import jax.numpy as jnp
        from paddle_tpu.kernels.paged_attention import KV_SCALE_EPS
        from paddle_tpu.models.llama import _quantized_token_insert
        rng = np.random.RandomState(21)
        big = (rng.randn(1, 2, 8) * 4).astype(np.float32)
        small = (rng.randn(1, 2, 8) * 0.01).astype(np.float32)
        pool = jnp.zeros((3, 16, 2, 8), jnp.int8)
        scales = jnp.full((3, 2), KV_SCALE_EPS, jnp.float32)
        page = jnp.asarray([1], jnp.int32)
        pool, scales = _quantized_token_insert(
            pool, scales, page, jnp.asarray([0], jnp.int32),
            jnp.asarray(big))
        before = np.asarray(pool)[1, 0].copy()
        s_before = np.asarray(scales)[1].copy()
        pool, scales = _quantized_token_insert(
            pool, scales, page, jnp.asarray([1], jnp.int32),
            jnp.asarray(small))
        np.testing.assert_array_equal(np.asarray(pool)[1, 0], before)
        np.testing.assert_array_equal(np.asarray(scales)[1], s_before)

    def test_gather_dequant_pool_edge_scale_indexing(self):
        """Each block dequantizes with ITS page's per-head scale — pin
        the indexing with the first and LAST allocatable page carrying
        distinct per-head scales over all-ones codes."""
        import jax.numpy as jnp
        from paddle_tpu.kernels.paged_attention import (
            KV_SCALE_EPS, gather_pages_dequant)
        N, bs, kvh, hd = 6, 8, 2, 4
        pages = jnp.ones((N, bs, kvh, hd), jnp.int8)
        scales = np.full((N, kvh), KV_SCALE_EPS, np.float32)
        scales[1] = [2.0, 3.0]
        scales[N - 1] = [5.0, 7.0]
        table = jnp.asarray([[1, N - 1]], jnp.int32)
        g = np.asarray(gather_pages_dequant(
            pages, table, jnp.asarray(scales)))
        assert g.shape == (1, 2 * bs, kvh, hd)
        np.testing.assert_array_equal(g[0, :bs, 0], 2.0)
        np.testing.assert_array_equal(g[0, :bs, 1], 3.0)
        np.testing.assert_array_equal(g[0, bs:, 0], 5.0)
        np.testing.assert_array_equal(g[0, bs:, 1], 7.0)

    def test_pallas_interpret_matches_xla_reference_bit_exact(self):
        """The int8 Pallas kernel body and the XLA reference share one
        block-update helper, so interpret mode must agree BIT-EXACTLY
        (assert_array_equal, not allclose)."""
        import jax.numpy as jnp
        from paddle_tpu.kernels.paged_attention import (
            _paged_attn_reference_int8, paged_attention_pallas)
        rng = np.random.RandomState(22)
        B, kvh, G, hd, N, bs = 3, 2, 2, 16, 8, 16
        q = jnp.asarray(rng.randn(B, kvh, G, hd).astype(np.float32))
        kp = jnp.asarray(
            rng.randint(-127, 128, (N, bs, kvh, hd)).astype(np.int8))
        vp = jnp.asarray(
            rng.randint(-127, 128, (N, bs, kvh, hd)).astype(np.int8))
        ks = jnp.asarray(rng.rand(N, kvh).astype(np.float32) * 0.1)
        vs = jnp.asarray(rng.rand(N, kvh).astype(np.float32) * 0.1)
        tables = jnp.asarray(rng.permutation(np.arange(1, 7))[:6]
                             .reshape(3, 2).astype(np.int32))
        lens = jnp.asarray([5, 16, 23], jnp.int32)
        out_k = paged_attention_pallas(q, kp, vp, tables, lens,
                                       interpret=True,
                                       kv_scales=(ks, vs))
        out_r = _paged_attn_reference_int8(q, kp, vp, tables, lens,
                                           (ks, vs))
        np.testing.assert_array_equal(np.asarray(out_k),
                                      np.asarray(out_r))

    def test_int8_greedy_tokens_match_fp(self):
        """Engine-level acceptance: on the seeded debug model, int8 KV
        changes logits by less than the greedy argmax margin — emitted
        tokens are identical to the fp pool (prefix cache and chunked
        prefill on, to exercise COW scale copies and the scatter path)."""
        m = _model()
        rng = np.random.RandomState(23)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 21, 33)]
        kw = dict(capacity=4, s_max=96, chunk=4, block_size=16)
        _, _, fp = _run(m, prompts, 10, **kw)
        _, _, q8 = _run(m, prompts, 10, kv_dtype="int8", **kw)
        _, _, q8c = _run(m, prompts, 10, kv_dtype="int8",
                         chunked_prefill=True, **kw)
        for a, b, c in zip(fp, q8, q8c):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)

    def test_recycled_page_scale_resets(self):
        """A page freed by one request and recycled by the next must
        drop the previous tenant's running-max scale before the next
        write — otherwise scales only ever coarsen. Pin the drain
        contract directly on a live int8 engine."""
        import jax.numpy as jnp
        import numpy as _np
        from paddle_tpu.kernels.paged_attention import KV_SCALE_EPS
        from paddle_tpu.inference.serving import DecodeEngine
        eng = DecodeEngine(_model(), capacity=2, s_max=64, chunk=4,
                           block_size=8, prefix_cache=False,
                           kv_dtype="int8")
        assert eng._alloc.track_allocations
        (pg,) = eng._alloc.allocate(1)
        eng._drain_scale_resets()           # fresh hand-out: at floor
        # a tenant wrote outliers into the page...
        eng._kscale = eng._kscale.at[:, pg].set(9.0)
        eng._vscale = eng._vscale.at[:, pg].set(9.0)
        eng._alloc.free([pg])
        again = eng._alloc.allocate(1)      # LIFO: same page comes back
        assert again == [pg]
        eng._drain_scale_resets()           # ...which must not leak
        _np.testing.assert_array_equal(
            _np.asarray(eng._kscale[:, pg]), _np.float32(KV_SCALE_EPS))
        _np.testing.assert_array_equal(
            _np.asarray(eng._vscale[:, pg]), _np.float32(KV_SCALE_EPS))
        # fp engines never track, so the hand-out log stays empty
        eng_fp = DecodeEngine(_model(), capacity=2, s_max=64, chunk=4,
                              block_size=8, prefix_cache=False)
        eng_fp._alloc.allocate(2)
        assert eng_fp._alloc.drain_allocated() == []
