"""Chunked prefill (ISSUE 7): prompt prefill split into page-sized
chunks scheduled INTO decode steps under a per-step token budget.

Covers the StepBudget/plan_prefill scheduler contract, bit-identical
greedy outputs chunked-vs-monolithic-vs-solo (including preemption mid-
prefill and prefix-hit composition), lifecycle/metric accounting
(engine_prefill_chunks_total, prefill_chunk trace marks, first_token at
last-chunk completion, prefill-backlog gauge), and the compiled-shape
discipline: a mixed flood with the default page-sized chunk rides ONLY
the 16-slot prefix-prefill bucket — no third program shape."""

import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.scheduler import RequestScheduler, StepBudget


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


def _drive(eng, pending, iters=400):
    for _ in range(iters):
        eng.admit(pending)
        eng.decode_once()
        if eng.idle() and not pending:
            return
    raise AssertionError("engine did not drain the workload")


class _Req:
    """Bare scheduler item for StepBudget/plan_prefill unit tests."""

    def __init__(self, seq, priority=0):
        self._sched_seq = seq
        self.priority = priority


class TestStepBudget:
    def test_take_funds_whole_items_only(self):
        b = StepBudget(10)
        assert b.take(6) and b.used == 6 and b.remaining == 4
        assert not b.take(5)               # would overdraw: refused
        assert b.used == 6                 # refusal records nothing
        assert b.take(4) and b.remaining == 0

    def test_force_records_overdraft(self):
        """Decode lanes are never throttled — force=True always funds,
        and the spend still lands in ``used`` so the step histogram
        sees the real token load."""
        b = StepBudget(4)
        assert b.take(8, force=True)
        assert b.used == 8 and b.remaining == 0

    def test_zero_and_negative_are_free(self):
        b = StepBudget(0)
        assert b.take(0) and b.take(-3)
        assert b.used == 0

    def test_plan_prefill_stops_at_first_unaffordable(self):
        """Head-of-line order survives the budget: a later SMALL chunk
        must not overtake a starved earlier big one."""
        s = RequestScheduler()
        a, b, c = _Req(0), _Req(1), _Req(2)
        funded = s.plan_prefill(StepBudget(10), [(a, 8), (b, 8), (c, 1)])
        assert funded == [(a, 8)]          # b unaffordable, c NOT slid in

    def test_plan_prefill_priority_over_arrival(self):
        s = RequestScheduler()
        lo, hi = _Req(0, priority=0), _Req(1, priority=5)
        funded = s.plan_prefill(StepBudget(8), [(lo, 8), (hi, 8)])
        assert funded == [(hi, 8)]

    def test_fair_share_orders_by_vtime(self):
        """Under QoS, the tenant with the SMALLEST virtual time gets
        the next chunk — a long prompt's chunks rotate with other
        tenants' work instead of monopolising the budget."""
        from paddle_tpu.inference.qos import (FairShareScheduler,
                                              QoSPolicy, TenantPolicy)
        qos = QoSPolicy([TenantPolicy("a"), TenantPolicy("b")])
        s = FairShareScheduler(qos)
        ra, rb = _Req(0), _Req(1)
        ra.tenant, rb.tenant = "a", "b"
        s.charge("a", 100)                 # a already consumed a lot
        funded = s.plan_prefill(StepBudget(8), [(ra, 8), (rb, 8)])
        assert funded == [(rb, 8)]


class TestChunkedEngine:
    def test_requires_paged(self):
        from paddle_tpu.inference.serving import DecodeEngine
        with pytest.raises(ValueError, match="paged"):
            DecodeEngine(_model(), capacity=2, s_max=64, chunk=4,
                         paged=False, chunked_prefill=True)

    def test_bit_identical_vs_monolithic_and_solo(self):
        """The correctness oracle: same engine config, admission
        prefill vs chunked prefill, greedy outputs bit-identical (and
        both match solo generate)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(21)
        # mixed short/long: single-chunk, multi-chunk, and a prompt
        # whose final chunk is partial
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 37, 7, 29)]
        solo = [_solo(m, p, 8) for p in prompts]

        def run(**kw):
            eng = DecodeEngine(m, capacity=4, s_max=96, chunk=4,
                               block_size=16, **kw)
            reqs = [_Request(p, 8) for p in prompts]
            _drive(eng, list(reqs))
            return eng, [r.wait(timeout=1) for r in reqs]

        mono_eng, mono = run()
        ch_eng, ch = run(chunked_prefill=True)
        for c, a, s in zip(ch, mono, solo):
            np.testing.assert_array_equal(c, a)
            np.testing.assert_array_equal(c, s)
        # chunk accounting: one chunk per page-sized window of prompt
        want = sum(math.ceil(p.size / 16) for p in prompts)
        assert ch_eng.stats()["prefill_chunks"] == want
        assert mono_eng.stats().get("prefill_chunks", 0) == 0
        # prefill COMPLETIONS match the monolithic count 1:1
        assert ch_eng.prefills == mono_eng.prefills == len(prompts)

    def test_trace_marks_and_first_token_at_last_chunk(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(22)
        p = rng.randint(1, 128, (37,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                           block_size=16, chunked_prefill=True)
        r = _Request(p, 6)
        _drive(eng, [r])
        tr = r.trace
        assert tr.count("prefill_chunk") == math.ceil(p.size / 16)
        # TTFT spans admission -> LAST chunk's first token
        assert tr.first("first_token") >= tr.last("prefill_chunk")
        assert tr.ttft is not None and tr.is_complete()

    def test_step_budget_one_chunk_per_step(self):
        """step_budget small enough for one chunk per step: the prompt
        takes ceil(n/chunk) decode steps to become resident, and the
        budget histogram records every step's spend."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(23)
        p = rng.randint(1, 128, (40,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                           block_size=8, chunked_prefill=True,
                           step_budget=8)
        r = _Request(p, 4)
        eng.admit([r])
        row = next(x for x in eng._rows if x is not None)
        for step in range(1, 5):
            eng.decode_once()
            assert row["pf_pos"] == 8 * step      # exactly one chunk
        h = eng.metrics.get("engine_step_budget_used")
        assert h.count >= 4
        _drive(eng, [])
        np.testing.assert_array_equal(r.wait(timeout=1), _solo(m, p, 4))

    def test_prefill_backlog_gauge(self):
        """stats()/gauge report queued prompt tokens not yet prefilled:
        scheduler backlog + in-flight rows' unprefilled remainders."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(24)
        p1 = rng.randint(1, 128, (24,)).astype(np.int32)
        p2 = rng.randint(1, 128, (16,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=1, s_max=96, chunk=4,
                           block_size=8, chunked_prefill=True,
                           step_budget=8)
        r1, r2 = _Request(p1, 4), _Request(p2, 4)
        eng.admit([r1, r2])                # r1 takes the slot, r2 queued
        assert eng.stats()["prefill_backlog"] == 40
        assert eng.metrics.get(
            "engine_prefill_backlog_tokens").value == 40
        eng.decode_once()                  # one 8-token chunk of r1
        assert eng.stats()["prefill_backlog"] == 32
        _drive(eng, [])
        assert eng.stats()["prefill_backlog"] == 0
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      _solo(m, p1, 4))
        np.testing.assert_array_equal(r2.wait(timeout=1),
                                      _solo(m, p2, 4))

    def test_preempt_mid_prefill_resumes_losslessly(self):
        """A high-priority arrival evicts a row that is still MID
        chunked prefill; the victim resumes through re-admission (its
        completed pages may prefix-hit) and still bit-matches solo."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(25)
        p_lo = rng.randint(1, 128, (20,)).astype(np.int32)
        p_hi = rng.randint(1, 128, (17,)).astype(np.int32)
        solo_lo, solo_hi = _solo(m, p_lo, 4), _solo(m, p_hi, 4)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4,
                           chunked_prefill=True, step_budget=8)
        lo = _Request(p_lo, 4)
        eng.admit([lo])
        eng.decode_once()                  # lo mid-prefill: 8/20 tokens
        row = next(x for x in eng._rows if x is not None)
        assert "pf_seq" in row and row["pf_pos"] == 8
        hi = _Request(p_hi, 4, priority=5)
        pending = [hi]                     # needs all 3 usable pages
        _drive(eng, pending)
        assert eng.stats()["preempted"] >= 1
        np.testing.assert_array_equal(hi.wait(timeout=1), solo_hi)
        np.testing.assert_array_equal(lo.wait(timeout=1), solo_lo)

    def test_preempt_after_first_token_resumes_with_tokens(self):
        """A chunked row preempted AFTER decode started resumes from
        its emitted tokens (the r7 recompute path), and first_token is
        marked exactly once across the stints."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(26)
        prompts = [rng.randint(1, 128, (7,)).astype(np.int32)
                   for _ in range(2)]
        solo = [_solo(m, p, 12) for p in prompts]
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4,
                           chunked_prefill=True)
        reqs = [_Request(p, 12) for p in prompts]
        _drive(eng, list(reqs))
        assert eng.stats()["preempted"] >= 1
        for r, s in zip(reqs, solo):
            np.testing.assert_array_equal(r.wait(timeout=1), s)
            assert r.trace.count("first_token") == 1

    def test_grow_evicts_mid_prefill_row_no_livelock(self):
        """Tiny-pool regression: a decode-complete row needing ONE grow
        page with an equal-priority neighbor still mid-prefill must
        evict the prefilling row (least work lost, lossless resume) —
        not self-preempt into an admit→prefill→grow-fail cycle that
        starves the neighbor forever."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(30)
        # 6-tok retires early; 45-tok needs 6 prompt pages + 1 grow
        # page; 13-tok sits mid-prefill holding the last 2 pages
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (6, 45, 13, 31)]
        solo = [_solo(m, p, 10) for p in prompts]
        eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                           block_size=8, n_blocks=9,
                           chunked_prefill=True, step_budget=8)
        reqs = [_Request(p, 10) for p in prompts]
        _drive(eng, list(reqs), iters=500)
        assert eng.stats()["preempted"] >= 1
        for r, s in zip(reqs, solo):
            np.testing.assert_array_equal(r.wait(timeout=1), s)

    def test_prefix_hit_composes_with_chunking(self):
        """A resubmitted shared prefix skips its cached pages: fewer
        chunks for the second request, outputs still bit-match solo."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(27)
        head = rng.randint(1, 128, (24,)).astype(np.int32)  # 3 pages
        p2 = np.concatenate([head, rng.randint(1, 128, (10,))
                             .astype(np.int32)])
        eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                           block_size=8, chunked_prefill=True)
        r1 = _Request(head, 4)
        _drive(eng, [r1])
        cold_chunks = eng.stats()["prefill_chunks"]
        assert cold_chunks == 3
        r2 = _Request(p2, 4)
        _drive(eng, [r2])
        warm_chunks = eng.stats()["prefill_chunks"] - cold_chunks
        # 34-token prompt cold would be 5 chunks; the 24-token prefix
        # is resident, so only the uncached tail is chunked
        assert warm_chunks < 5
        assert eng.metrics.get("engine_prefix_hit_tokens_total").value \
            >= 24
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      _solo(m, head, 4))
        np.testing.assert_array_equal(r2.wait(timeout=1),
                                      _solo(m, p2, 4))

    def test_qos_fair_share_bit_parity(self):
        """Chunked prefill under the fair-share scheduler: per-chunk
        charging reorders service but never corrupts it."""
        from paddle_tpu.inference.qos import QoSPolicy, TenantPolicy
        from paddle_tpu.inference.serving import DecodeEngine

        class _VClock:
            t = 0.0

            def __call__(self):
                return self.t

        m = _model()
        rng = np.random.RandomState(28)
        qos = QoSPolicy([TenantPolicy("h", weight=1.0),
                         TenantPolicy("l", weight=10.0)],
                        clock=_VClock())
        eng = DecodeEngine(m, capacity=2, s_max=96, chunk=4,
                           block_size=16, qos=qos, chunked_prefill=True)
        work = []
        for i in range(4):
            p = rng.randint(1, 128, (5 + 9 * i,)).astype(np.int32)
            work.append((p, eng.submit(p, max_new_tokens=5,
                                       tenant="h" if i % 2 else "l")))
        for _ in range(400):
            eng.admit([])
            eng.decode_once()
            if eng.idle() and not eng.backlog:
                break
        for p, r in work:
            np.testing.assert_array_equal(r.wait(timeout=1),
                                          _solo(m, p, 5))
        assert eng.stats()["prefill_chunks"] >= 4

    def test_no_new_compiled_program_shapes(self):
        """The shape-bucketing acceptance: a mixed flood with the
        default page-sized chunk rides ONLY the already-documented
        16-slot prefix-prefill bucket — no third program shape beyond
        the r7 bucket set, regardless of prompt length mix."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(29)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (5, 18, 33, 60)]
        eng = DecodeEngine(m, capacity=4, s_max=96, chunk=4,
                           block_size=16, chunked_prefill=True)
        reqs = [_Request(p, 4) for p in prompts]
        _drive(eng, list(reqs))
        for r in reqs:
            r.wait(timeout=1)
        # every chunk window bucketed to the one 16-slot program; the
        # full-window cold-prefill shape monolithic admission uses for
        # these prompts never compiled, and paged decode adds no
        # windowed shapes
        assert set(eng._prefix_progs) == {16}
        assert eng._decode_progs == {}
        # a non-default chunk size buckets to ITS one window — still a
        # member of the documented power-of-two set, still one shape
        eng32 = DecodeEngine(m, capacity=4, s_max=96, chunk=4,
                             block_size=16, chunked_prefill=True,
                             prefill_chunk=32)
        reqs = [_Request(p, 4) for p in prompts]
        _drive(eng32, list(reqs))
        assert set(eng32._prefix_progs) <= {16, 32}
