"""Multi-tenant QoS (ISSUE 6): token-bucket admission edge cases,
weighted fair-share scheduling (incl. the no-starvation property sim),
SLO-driven shedding with per-tenant floors, submit-path validation,
tenant-labeled telemetry, and the seeded traffic generator.

Everything policy-level runs on injected virtual clocks — no test here
sleeps or reads wall time to make a decision."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.qos import (AdmissionGate, FairShareScheduler,
                                      QoSPolicy, RequestShedError,
                                      TenantPolicy, TokenBucket,
                                      request_cost, tenant_of)
from paddle_tpu.inference.scheduler import RequestScheduler
from paddle_tpu.inference.traffic import (TenantProfile,
                                          TrafficGenerator, jain_index)
from paddle_tpu.observability import RequestTrace


class _FakeReq:
    """Minimal request stand-in for policy-level tests (the real
    ``_Request`` validates prompts and needs numpy ids)."""

    def __init__(self, tenant=None, cost=10, max_new=4, priority=0,
                 seq=None):
        self.ids = np.ones(max(cost - max_new, 1), np.int32)
        self.max_new = max_new
        self.tenant = tenant
        self.priority = priority
        self._sched_seq = seq
        self.trace = RequestTrace(tenant=tenant)


class _VClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------
class TestTokenBucket:
    def test_starts_full_and_burst_exhausts(self):
        clk = _VClock()
        b = TokenBucket(rate=5.0, burst=20.0, clock=clk)
        assert b.available() == 20.0
        assert b.try_take(12)
        assert b.try_take(8)
        assert not b.try_take(1)           # burst gone, clock frozen

    def test_refill_integrates_injected_clock_and_caps(self):
        clk = _VClock()
        b = TokenBucket(rate=4.0, burst=10.0, clock=clk)
        assert b.try_take(10)
        clk.t = 1.5
        assert b.available() == pytest.approx(6.0)   # 1.5 s * 4/s
        clk.t = 100.0
        assert b.available() == 10.0        # capped at burst
        assert b.try_take(10) and not b.try_take(0.1)

    def test_explicit_t_overrides_clock(self):
        b = TokenBucket(rate=1.0, burst=4.0, clock=_VClock(), t=0.0)
        assert b.try_take(4, t=0.0)
        assert not b.try_take(2, t=1.0)
        assert b.try_take(2, t=2.0)

    def test_time_never_runs_backwards(self):
        b = TokenBucket(rate=10.0, burst=10.0, clock=_VClock(), t=5.0)
        b.try_take(10, t=5.0)
        assert b.available(t=1.0) == 0.0    # stale t: no negative refill


# ---------------------------------------------------------------------------
# policy validation
# ---------------------------------------------------------------------------
class TestTenantPolicy:
    @pytest.mark.parametrize("kw", [
        dict(on_limit="drop"), dict(rate=0.0), dict(rate=-1.0),
        dict(burst=0.0), dict(weight=-0.5), dict(shed_floor=-1),
    ])
    def test_invalid_fields_raise(self, kw):
        with pytest.raises(ValueError):
            TenantPolicy("t", **kw)

    def test_duplicate_tenant_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            QoSPolicy([TenantPolicy("a"), TenantPolicy("a")])

    def test_non_policy_rejected(self):
        with pytest.raises(TypeError):
            QoSPolicy([{"tenant": "a"}])

    def test_unknown_tenant_falls_back_to_default(self):
        qos = QoSPolicy([TenantPolicy("a", weight=3.0)],
                        default=TenantPolicy(weight=7.0, tier=2))
        assert qos.weight("a") == 3.0
        assert qos.weight("zzz") == 7.0 and qos.tier("zzz") == 2

    def test_tenant_of_and_cost(self):
        r = _FakeReq(cost=12, max_new=4)
        assert tenant_of(r) == "default"
        assert request_cost(r) == 12
        assert tenant_of(_FakeReq(tenant="t9")) == "t9"


# ---------------------------------------------------------------------------
# admission gate
# ---------------------------------------------------------------------------
class TestAdmissionGate:
    def _gate(self, clk, **kw):
        pol = TenantPolicy("a", **kw)
        qos = QoSPolicy([pol], clock=clk)
        return qos, qos.gate()

    def test_zero_weight_rejects_and_counts(self):
        qos = QoSPolicy([TenantPolicy("a", weight=0.0)],
                        clock=_VClock())
        g = qos.gate()
        assert g.decide(_FakeReq(tenant="a")) == ("reject",
                                                  "zero_weight")
        assert qos.stats()["a"]["rejected"] == 1

    def test_reject_mode_over_rate(self):
        clk = _VClock()
        qos, g = self._gate(clk, rate=1.0, burst=10.0,
                            on_limit="reject")
        assert g.decide(_FakeReq(tenant="a", cost=10))[0] == "admit"
        assert g.decide(_FakeReq(tenant="a", cost=10)) == (
            "reject", "rate_limited")
        assert qos.stats()["a"]["rejected"] == 1

    def test_throttle_release_fifo_no_queue_jump(self):
        clk = _VClock()
        qos, g = self._gate(clk, rate=10.0, burst=10.0)
        r1 = _FakeReq(tenant="a", cost=10, seq=1)
        r2 = _FakeReq(tenant="a", cost=10, seq=2)
        r3 = _FakeReq(tenant="a", cost=2, max_new=1, seq=3)
        assert g.decide(r1)[0] == "admit"
        assert g.decide(r2)[0] == "throttle"
        # r3 is tiny and WOULD fit the residual bucket — but a sibling
        # is already held: FIFO, no jumping
        assert g.decide(r3)[0] == "throttle"
        assert g.depth("a") == 2 and qos.gate_depth() == 2
        assert g.release() == []
        clk.t = 1.0                         # refill 10: funds r2 only
        assert g.release() == [r2]
        clk.t = 1.25
        assert g.release() == [r3]
        assert g.depth() == 0
        assert qos.stats()["a"]["throttled"] == 2

    def test_release_orders_across_tenants_by_arrival(self):
        clk = _VClock()
        qos = QoSPolicy([TenantPolicy("a", rate=10.0, burst=10.0),
                         TenantPolicy("b", rate=10.0, burst=10.0)],
                        clock=clk)
        g = qos.gate()
        # drain both buckets so the next decide() throttles
        assert qos.bucket("a").try_take(10)
        assert qos.bucket("b").try_take(10)
        rb = _FakeReq(tenant="b", cost=10, seq=5)
        ra = _FakeReq(tenant="a", cost=10, seq=9)
        assert g.decide(rb)[0] == "throttle"
        assert g.decide(ra)[0] == "throttle"
        clk.t = 1.0
        assert g.release() == [rb, ra]      # arrival order, not name

    def test_remove_drops_held_victims(self):
        clk = _VClock()
        qos, g = self._gate(clk, rate=1.0, burst=10.0)
        g.decide(_FakeReq(tenant="a", cost=10))
        victim = _FakeReq(tenant="a", cost=10)
        g.decide(victim)
        assert g.remove([victim]) == 1
        assert g.depth() == 0

    def test_gates_share_buckets_not_queues(self):
        """Two submit surfaces (engine + fleet) drain ONE bucket but
        hold their own throttled queues."""
        clk = _VClock()
        qos = QoSPolicy([TenantPolicy("a", rate=1.0, burst=10.0)],
                        clock=clk)
        g1, g2 = qos.gate(), qos.gate()
        assert g1.decide(_FakeReq(tenant="a", cost=10))[0] == "admit"
        assert g2.decide(_FakeReq(tenant="a", cost=1))[0] == "throttle"
        assert g1.depth() == 0 and g2.depth() == 1
        assert qos.gate_depth("a") == 2 - 1


# ---------------------------------------------------------------------------
# fair-share scheduler
# ---------------------------------------------------------------------------
class TestFairShareScheduler:
    def _qos(self, **weights):
        pols = [TenantPolicy(t, weight=w) for t, w in weights.items()]
        return QoSPolicy(pols, clock=_VClock())

    def test_single_tenant_matches_request_scheduler(self):
        """With one tenant the SFQ layer must reduce to the r7
        contract: priority desc, FCFS asc."""
        specs = [(0, None), (2, None), (0, None), (2, None), (1, None)]
        plain, fair = RequestScheduler(), FairShareScheduler(
            self._qos(a=1.0))
        reqs_p = [_FakeReq(priority=p) for p, _ in specs]
        reqs_f = [_FakeReq(tenant="a", priority=p) for p, _ in specs]
        for rp, rf in zip(reqs_p, reqs_f):
            plain.add(rp)
            fair.add(rf)
        order_p = [reqs_p.index(plain.pop()) for _ in range(len(specs))]
        order_f = [reqs_f.index(fair.pop()) for _ in range(len(specs))]
        assert order_p == order_f

    def test_weighted_service_ratio(self):
        """Both tenants backlogged, weights 3:1, equal request cost —
        served counts converge to the weight ratio."""
        qos = self._qos(a=3.0, b=1.0)
        s = FairShareScheduler(qos)
        for i in range(120):
            s.add(_FakeReq(tenant="a", cost=8))
            s.add(_FakeReq(tenant="b", cost=8))
        counts = {"a": 0, "b": 0}
        for _ in range(80):
            r = s.pop()
            t = tenant_of(r)
            counts[t] += 1
            s.charge(t, 8)
        assert counts["a"] == pytest.approx(60, abs=2)
        assert counts["b"] == pytest.approx(20, abs=2)

    def test_no_starvation_under_sustained_flood(self):
        """Property sim from the ISSUE: 10:1 weight skew, the heavy
        tenant floods continuously (a new arrival after every service),
        the light tenant has a finite queue — every light request is
        served within a bounded number of services, none starves."""
        qos = self._qos(heavy=10.0, light=1.0)
        s = FairShareScheduler(qos)
        light = [_FakeReq(tenant="light", cost=16) for _ in range(10)]
        for _ in range(50):
            s.add(_FakeReq(tenant="heavy", cost=16))
        for r in light:
            s.add(r)
        served_at = {}
        for step in range(400):
            r = s.pop()
            t = tenant_of(r)
            s.charge(t, 16)
            if t == "light":
                served_at[id(r)] = step
                if len(served_at) == len(light):
                    break
            s.add(_FakeReq(tenant="heavy", cost=16))   # sustain flood
        assert len(served_at) == len(light), "light tenant starved"
        # weight ratio 10:1 -> at most ~11 services between light pops
        gaps = sorted(served_at.values())
        assert gaps[0] <= 12
        assert all(b - a <= 13 for a, b in zip(gaps, gaps[1:])), gaps

    def test_idle_tenant_cannot_bank_credit(self):
        """A tenant that idles while another is served re-enters at the
        frontier — it does NOT get a monopoly for its idle time."""
        qos = self._qos(a=1.0, b=1.0)
        s = FairShareScheduler(qos)
        for _ in range(40):
            s.add(_FakeReq(tenant="a", cost=8))
        for _ in range(20):                 # b idle: a alone is served
            t = tenant_of(s.pop())
            assert t == "a"
            s.charge(t, 8)
        for _ in range(20):
            s.add(_FakeReq(tenant="b", cost=8))
        run_b = 0
        for _ in range(10):                 # b re-enters at frontier:
            t = tenant_of(s.pop())          # alternation, not monopoly
            s.charge(t, 8)
            run_b += (t == "b")
        assert run_b <= 6

    def test_peek_pop_coherent_across_add_and_charge(self):
        """The engine peeks, may preempt (re-add victims + charge the
        claimant), then pops — pop must remove exactly the peeked
        request even after the interleaved mutation."""
        qos = self._qos(a=1.0, b=1.0)
        s = FairShareScheduler(qos)
        claimant = _FakeReq(tenant="a", cost=8, priority=1)
        s.add(claimant)
        assert s.peek() is claimant
        victim = _FakeReq(tenant="a", cost=8, priority=2)
        s.add(victim)                       # re-queued preemption victim
        s.charge("a", 64)                   # claimant pays eviction
        assert s.pop() is claimant          # NOT the higher-prio victim
        assert s.pop() is victim

    def test_remove_and_requests_views(self):
        qos = self._qos(a=1.0, b=1.0)
        s = FairShareScheduler(qos)
        reqs = [_FakeReq(tenant=t, cost=8) for t in ("a", "b", "a")]
        for r in reqs:
            s.add(r)
        assert set(map(id, s.requests())) == set(map(id, reqs))
        assert s.remove([reqs[0], reqs[1]]) == 2
        assert len(s) == 1 and s.pop() is reqs[2]

    def test_add_marks_trace_queued(self):
        s = FairShareScheduler(self._qos(a=1.0))
        r = _FakeReq(tenant="a")
        s.add(r)
        assert r.trace.count("queued") == 1


# ---------------------------------------------------------------------------
# shed planning
# ---------------------------------------------------------------------------
class TestShedPlan:
    def _qos(self):
        return QoSPolicy([
            TenantPolicy("bulk", tier=0, shed_floor=1),
            TenantPolicy("vip", tier=5, shed_floor=2),
        ], clock=_VClock())

    def test_lowest_tier_newest_first(self):
        qos = self._qos()
        bulk = [_FakeReq(tenant="bulk", seq=i) for i in range(4)]
        vip = [_FakeReq(tenant="vip", seq=10 + i) for i in range(3)]
        victims = qos.shed_plan(bulk + vip, target=4)
        # 3 victims: all bulk (tier 0), newest (highest seq) first
        assert [id(v) for v in victims] == [id(bulk[3]), id(bulk[2]),
                                            id(bulk[1])]

    def test_floor_counts_running_rows(self):
        qos = self._qos()
        bulk = [_FakeReq(tenant="bulk", seq=i) for i in range(3)]
        # no running rows: floor 1 keeps one bulk pending
        assert len(qos.shed_plan(bulk, target=0)) == 2
        # a running bulk row already satisfies the floor: shed all 3
        assert len(qos.shed_plan(bulk, {"bulk": 1}, target=0)) == 3

    def test_vip_floor_protects_under_total_shed(self):
        qos = self._qos()
        vip = [_FakeReq(tenant="vip", seq=i) for i in range(4)]
        victims = qos.shed_plan(vip, target=0)
        assert len(victims) == 2            # floor 2 retained

    def test_no_excess_no_victims(self):
        qos = self._qos()
        reqs = [_FakeReq(tenant="bulk", seq=i) for i in range(3)]
        assert qos.shed_plan(reqs, target=3) == []
        assert qos.shed_plan([], target=0) == []


# ---------------------------------------------------------------------------
# submit-path validation (satellite a)
# ---------------------------------------------------------------------------
def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


def _drive(eng, iters=300):
    pending = []
    for _ in range(iters):
        eng.admit(pending)
        eng.decode_once()
        if eng.idle() and not eng.backlog:
            return
    raise AssertionError("engine did not drain")


class TestSubmitValidation:
    def test_request_ctor_validates(self):
        from paddle_tpu.inference.serving import _Request
        with pytest.raises(ValueError, match="empty"):
            _Request(np.array([], np.int32), 4)
        with pytest.raises(ValueError, match="positive"):
            _Request(np.array([1, 2], np.int32), 0)
        with pytest.raises(ValueError, match="positive"):
            _Request(np.array([1, 2], np.int32), -3)

    def test_engine_submit_validates(self):
        from paddle_tpu.inference.serving import DecodeEngine
        eng = DecodeEngine(_model(), capacity=2, s_max=64, chunk=4)
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.array([], np.int32))
        with pytest.raises(ValueError, match="positive"):
            eng.submit(np.array([1, 2], np.int32), max_new_tokens=0)

    def test_batching_server_submit_validates(self):
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        srv = BatchingServer(GenerationPredictor(_model()))
        try:
            with pytest.raises(ValueError, match="empty"):
                srv.submit(np.array([], np.int32))
            with pytest.raises(ValueError, match="positive"):
                # explicit 0 must NOT fall through to the default
                srv.submit(np.array([1, 2], np.int32),
                           max_new_tokens=0)
        finally:
            srv.close()

    def test_fleet_submit_validates(self):
        from paddle_tpu.inference.fleet import ServingFleet
        fleet = ServingFleet(_model(), n_workers=2,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8))
        try:
            with pytest.raises(ValueError, match="empty"):
                fleet.submit(np.array([], np.int32))
            with pytest.raises(ValueError, match="positive"):
                fleet.submit(np.array([1, 2], np.int32),
                             max_new_tokens=0)
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# engine + QoS integration
# ---------------------------------------------------------------------------
class TestEngineQoS:
    def test_qos_requires_paged(self):
        from paddle_tpu.inference.serving import DecodeEngine
        with pytest.raises(ValueError, match="paged"):
            DecodeEngine(_model(), paged=False,
                         qos=QoSPolicy(clock=_VClock()))

    def test_submit_requires_paged(self):
        from paddle_tpu.inference.serving import DecodeEngine
        eng = DecodeEngine(_model(), paged=False)
        with pytest.raises(RuntimeError, match="paged"):
            eng.submit(np.array([1, 2], np.int32))

    def test_outputs_bit_identical_with_unlimited_qos(self):
        """Acceptance (c) flip side: an unlimited single-tenant QoS
        config must not perturb the decode — outputs stay bit-identical
        to the qos-less engine over the same workload."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _model()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (7, 5, 9, 4)]
        plain = DecodeEngine(m, capacity=2, s_max=64, chunk=4)
        pend = [_Request(p, 6) for p in prompts]
        plain_reqs = list(pend)
        pending = list(pend)
        for _ in range(300):
            plain.admit(pending)
            plain.decode_once()
            if plain.idle() and not pending:
                break
        qos = QoSPolicy(clock=_VClock())
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4, qos=qos)
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        _drive(eng)
        for rq, rp in zip(reqs, plain_reqs):
            np.testing.assert_array_equal(rq.wait(timeout=1),
                                          rp.wait(timeout=1))

    def test_submit_reject_fails_fast_with_reason(self):
        from paddle_tpu.inference.serving import DecodeEngine
        qos = QoSPolicy([TenantPolicy("free", weight=0.0)],
                        clock=_VClock())
        eng = DecodeEngine(_model(), capacity=2, s_max=64, chunk=4,
                           qos=qos)
        req = eng.submit(np.arange(1, 6, dtype=np.int32),
                         max_new_tokens=4, tenant="free")
        with pytest.raises(PermissionError, match="zero_weight"):
            req.wait(timeout=1)
        assert req.trace.attrs["reject_reason"] == "zero_weight"
        assert req.trace.terminal == "failed"

    def test_submit_throttle_releases_on_refill(self):
        """Clock-injected end-to-end: the second request sits behind
        the bucket until the virtual clock refills it, then retires
        with solo-parity tokens."""
        from paddle_tpu.inference.serving import DecodeEngine
        m = _model()
        clk = _VClock()
        p = np.arange(1, 7, dtype=np.int32)          # cost 6 + 4 = 10
        qos = QoSPolicy([TenantPolicy("a", rate=10.0, burst=10.0)],
                        clock=clk)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4, qos=qos)
        r1 = eng.submit(p, max_new_tokens=4, tenant="a")
        r2 = eng.submit(p, max_new_tokens=4, tenant="a")
        assert eng._qos_gate.depth() == 1            # r2 held
        _drive(eng)
        assert r1.wait(timeout=1) is not None
        assert not r2.event.is_set()                 # still gated
        clk.t = 1.0                                  # refill 10 tokens
        _drive(eng)
        ref = _solo(m, p, 4)
        np.testing.assert_array_equal(r2.wait(timeout=1), ref)
        assert qos.stats()["a"]["throttled"] == 1
        assert qos.stats()["a"]["admitted"] == 2
        # gate wait is queue wait: the trace saw ONE queued->admitted
        # stint spanning the throttle
        assert r2.trace.queue_wait > 0.0

    def test_two_tenant_engine_drains_with_parity(self):
        """Fair sharing reorders service between tenants but never
        corrupts it — every request still bit-matches solo decode."""
        from paddle_tpu.inference.serving import DecodeEngine
        m = _model()
        rng = np.random.RandomState(7)
        qos = QoSPolicy([TenantPolicy("h", weight=1.0),
                         TenantPolicy("l", weight=10.0)],
                        clock=_VClock())
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4, qos=qos)
        work = []
        for i in range(6):
            p = rng.randint(1, 128, (4 + i,)).astype(np.int32)
            work.append((p, eng.submit(p, max_new_tokens=5,
                                       tenant="h" if i % 3 else "l")))
        _drive(eng)
        for p, r in work:
            np.testing.assert_array_equal(r.wait(timeout=1),
                                          _solo(m, p, 5))
        st = qos.stats()
        assert st["h"]["served_tokens"] == 4 * 5
        assert st["l"]["served_tokens"] == 2 * 5


# ---------------------------------------------------------------------------
# fleet end-to-end: SLO-driven shedding
# ---------------------------------------------------------------------------
class TestFleetShedding:
    def test_shed_requires_qos(self):
        from paddle_tpu.inference.fleet import ServingFleet
        fleet = ServingFleet(_model(), n_workers=1,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8))
        try:
            with pytest.raises(ValueError, match="qos"):
                fleet.enable_slo(shed=True)
        finally:
            fleet.close()

    def test_burn_rate_shed_end_to_end(self):
        """Flood a 1-worker fleet past a backlog SLO on a virtual
        clock: every shed victim fails LOUDLY (RequestShedError,
        ``shed_reason`` on the trace, counter increment), the
        shed-protected vip tenant fully retires, and every survivor
        bit-matches solo decode."""
        from paddle_tpu.inference.fleet import ServingFleet
        from paddle_tpu.observability import SLORule
        m = _model()
        clk = _VClock()
        qos = QoSPolicy([
            TenantPolicy("bulk", tier=0, shed_floor=1),
            TenantPolicy("vip", tier=1, shed_floor=1),
        ], clock=clk)
        fleet = ServingFleet(m, n_workers=1,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8),
                             qos=qos)
        fleet.enable_slo(rules=[
            SLORule("backlog", "engine_backlog", "value",
                    threshold=2.0, window_s=60.0)],
            shed=True, shed_target_backlog=2)
        rng = np.random.RandomState(11)
        work = []
        for i in range(10):
            p = rng.randint(1, 128, (5,)).astype(np.int32)
            work.append((p, fleet.submit(p, max_new_tokens=4,
                                         tenant="bulk")))
        vip_p = rng.randint(1, 128, (6,)).astype(np.int32)
        vip = fleet.submit(vip_p, max_new_tokens=4, tenant="vip")
        work.append((vip_p, vip))
        for _ in range(200):
            fleet.step()
            fleet.check_slo(now=clk.t)
            clk.t += 0.25
            if not fleet.pending_work():
                break
        assert not fleet.pending_work()
        shed, retired = [], []
        for p, r in work:
            if r.trace.terminal == "failed":
                shed.append(r)
                with pytest.raises(RequestShedError,
                                   match="slo_burn_rate:backlog"):
                    r.wait(timeout=1)
                assert r.trace.attrs["shed_reason"].startswith(
                    "slo_burn_rate:")
            else:
                retired.append((p, r))
        assert shed, "overload never triggered shedding"
        st = fleet.stats()
        assert st["shed"] == len(shed)
        assert sum(t["shed"] for t in st["qos"].values()) == len(shed)
        # the shed-protected tier survived
        assert vip.trace.terminal == "retired"
        assert st["qos"]["vip"]["shed"] == 0
        # loud, not lossy: survivors still bit-match solo decode
        for p, r in retired:
            np.testing.assert_array_equal(r.wait(timeout=1),
                                          _solo(m, p, 4))
        fleet.close()

    def test_fleet_reject_tenant(self):
        from paddle_tpu.inference.fleet import ServingFleet
        qos = QoSPolicy([TenantPolicy("m", rate=1.0, burst=1.0,
                                      on_limit="reject")],
                        clock=_VClock())
        fleet = ServingFleet(_model(), n_workers=1,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8),
                             qos=qos)
        try:
            req = fleet.submit(np.arange(1, 6, dtype=np.int32),
                               max_new_tokens=4, tenant="m")
            with pytest.raises(PermissionError, match="rate_limited"):
                req.wait(timeout=1)
            assert req.trace.attrs["reject_reason"] == "rate_limited"
            assert fleet.stats()["qos_rejected"] == 1
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# tenant-labeled telemetry (satellites b, f)
# ---------------------------------------------------------------------------
class TestTenantTelemetry:
    def test_trace_summary_appends_tenant_after_attrs(self):
        tr = RequestTrace(tenant="t3")
        s = tr.summary()
        keys = list(s)
        assert s["tenant"] == "t3"
        assert keys.index("tenant") > keys.index("attrs")
        assert RequestTrace().summary()["tenant"] is None

    def test_chrome_export_carries_tenant(self):
        tr = RequestTrace(tenant="t3")
        tr.mark("queued", t=tr.arrival + 0.1)
        evs = tr.to_events()
        assert all(e["args"]["tenant"] == "t3" for e in evs)
        # no tenant -> byte-identical r10 args (no key at all)
        evs0 = RequestTrace().to_events()
        assert all("tenant" not in e["args"] for e in evs0)

    def test_aggregator_tenant_labels_beside_workers(self):
        from paddle_tpu.inference.fleet_metrics import MetricsAggregator
        from paddle_tpu.observability import MetricsRegistry
        agg = MetricsAggregator()
        wr = MetricsRegistry()
        wr.counter("engine_retired_total", "t").inc(5)
        agg.add("w0", wr)
        tr = MetricsRegistry()
        tr.counter("qos_shed_total", "t").inc(3)
        agg.add_labels({"tenant": "t3"}, tr)
        text = agg.prometheus_text()
        assert 'engine_retired_total{worker="w0"} 5' in text
        assert 'qos_shed_total{tenant="t3"} 3' in text
        snap = agg.snapshot()
        assert snap["workers"]["tenant=t3"]["counters"][
            "qos_shed_total"] == 3
        # tenant entries are EXCLUDED from the fleet merge (they
        # partition the same events the workers already count)
        assert "qos_shed_total" not in snap["fleet"]["counters"]
        assert snap["fleet"]["counters"]["engine_retired_total"] == 5

    def test_aggregator_duplicate_and_empty_labels_raise(self):
        from paddle_tpu.inference.fleet_metrics import MetricsAggregator
        from paddle_tpu.observability import MetricsRegistry
        agg = MetricsAggregator()
        agg.add_labels({"tenant": "a"}, MetricsRegistry())
        with pytest.raises(ValueError, match="duplicate"):
            agg.add_labels({"tenant": "a"}, MetricsRegistry())
        with pytest.raises(ValueError, match="label"):
            agg.add_labels({}, MetricsRegistry())

    def test_aggregator_type_conflict_across_label_sets(self):
        from paddle_tpu.inference.fleet_metrics import MetricsAggregator
        from paddle_tpu.observability import MetricsRegistry
        agg = MetricsAggregator()
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("x_total", "h")
        r2.gauge("x_total", "h")
        agg.add("w0", r1)
        agg.add_labels({"tenant": "t"}, r2)
        with pytest.raises(TypeError, match="conflicting"):
            agg.prometheus_text()

    def test_tenant_label_escaping(self):
        from paddle_tpu.inference.fleet_metrics import MetricsAggregator
        from paddle_tpu.observability import MetricsRegistry
        agg = MetricsAggregator()
        reg = MetricsRegistry()
        reg.counter("qos_shed_total", "t").inc()
        agg.add_labels({"tenant": 'we"ird\\te\nnant'}, reg)
        text = agg.prometheus_text()
        assert 'tenant="we\\"ird\\\\te\\nnant"' in text

    def test_fleet_aggregator_includes_tenant_registries(self):
        from paddle_tpu.inference.fleet import ServingFleet
        qos = QoSPolicy(clock=_VClock())
        fleet = ServingFleet(_model(), n_workers=1,
                             engine_kwargs=dict(capacity=2, s_max=64,
                                                chunk=4, block_size=8),
                             qos=qos)
        try:
            req = fleet.submit(np.arange(1, 6, dtype=np.int32),
                               max_new_tokens=4, tenant="t3")
            while fleet.pending_work():
                fleet.step()
            req.wait(timeout=1)
            agg = fleet.aggregator()
            assert "tenant=t3" in agg.labels()
            text = agg.prometheus_text()
            assert 'qos_admitted_total{tenant="t3"} 1' in text
            assert 'qos_served_tokens_total{tenant="t3"} 4' in text
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# traffic generator
# ---------------------------------------------------------------------------
class TestTraffic:
    _TENANTS = [TenantProfile("h", share=10.0),
                TenantProfile("l", share=1.0)]

    def test_same_seed_same_arrivals(self):
        a = TrafficGenerator(self._TENANTS, rate=5.0,
                             seed=42).arrivals(20.0)
        b = TrafficGenerator(self._TENANTS, rate=5.0,
                             seed=42).arrivals(20.0)
        assert a == b and len(a) > 10
        c = TrafficGenerator(self._TENANTS, rate=5.0,
                             seed=43).arrivals(20.0)
        assert a != c

    def test_validation(self):
        with pytest.raises(ValueError, match="process"):
            TrafficGenerator(self._TENANTS, process="lumpy")
        with pytest.raises(ValueError, match="prompt_dist"):
            TrafficGenerator(self._TENANTS, prompt_dist="zipf")
        with pytest.raises(ValueError, match="rate"):
            TrafficGenerator(self._TENANTS, rate=0.0)
        with pytest.raises(ValueError, match="prompt_min"):
            TrafficGenerator(self._TENANTS, prompt_min=9, prompt_max=4)
        with pytest.raises(ValueError):
            TrafficGenerator([])
        with pytest.raises(ValueError, match="share"):
            TenantProfile("x", share=0.0)

    @pytest.mark.parametrize("process", ["constant", "poisson",
                                         "bursty", "diurnal"])
    def test_processes_sorted_and_bounded(self, process):
        arr = TrafficGenerator(self._TENANTS, rate=8.0, seed=1,
                               process=process).arrivals(10.0)
        ts = [r.t for r in arr]
        assert ts == sorted(ts)
        assert all(0.0 < t < 10.0 for t in ts)
        assert len(arr) > 0

    def test_tenant_skew_follows_shares(self):
        arr = TrafficGenerator(self._TENANTS, rate=50.0, seed=0,
                               process="poisson").arrivals(40.0)
        n_h = sum(r.tenant == "h" for r in arr)
        assert n_h / len(arr) == pytest.approx(10 / 11, abs=0.05)

    def test_prompt_lengths_bounded_heavy_tail(self):
        gen = TrafficGenerator(self._TENANTS, rate=50.0, seed=0,
                               prompt_min=4, prompt_max=32)
        arr = gen.arrivals(30.0)
        lens = [r.prompt_len for r in arr]
        assert all(4 <= n <= 32 for n in lens)
        assert min(lens) < 8 < max(lens)    # short mode, fat tail

    def test_prompt_ids_deterministic_and_in_vocab(self):
        gen = TrafficGenerator(self._TENANTS, rate=5.0, seed=0)
        arr = gen.arrivals(10.0)
        a = gen.prompt_ids(arr[0], 512, index=0)
        b = gen.prompt_ids(arr[0], 512, index=0)
        np.testing.assert_array_equal(a, b)
        assert a.size == arr[0].prompt_len
        assert a.min() >= 1 and a.max() < 512
        c = gen.prompt_ids(arr[0], 512, index=1)
        assert not np.array_equal(a, c)

    def test_jain_index(self):
        assert jain_index([]) == 1.0
        assert jain_index([0, 0]) == 1.0
        assert jain_index([5, 5, 5]) == pytest.approx(1.0)
        assert jain_index([1, 0, 0, 0]) == pytest.approx(0.25)
        assert jain_index([10, 1]) == pytest.approx(121 / 202)
