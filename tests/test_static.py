"""Static Program/Executor tests (reference: test/legacy_test static
executor tests; base/executor.py:1482, program_guard patterns)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static


class TestProgramExecutor:
    def test_record_and_run(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4])
            y = paddle.exp(x) + 1.0
        assert "exp" in prog.op_types
        exe = static.Executor()
        feed = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        out, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
        np.testing.assert_allclose(out, np.exp(feed) + 1, rtol=1e-5)

    def test_feed_shape_polymorphism(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4])
            y = (x * 2).sum()
        exe = static.Executor()
        for n in (2, 7):
            feed = np.ones((n, 4), np.float32)
            out, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
            assert float(out) == 8 * n

    def test_layer_params_are_live_inputs(self):
        """Parameter updates between runs must be visible without
        recompiling (externals are runner inputs, not baked constants)."""
        paddle.seed(0)
        net = nn.Linear(4, 2)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4])
            y = net(x)
        exe = static.Executor()
        feed = np.ones((2, 4), np.float32)
        out1, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
        net.weight._in_place_update(net.weight._value * 2)
        net.bias._in_place_update(net.bias._value * 2)
        out2, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
        np.testing.assert_allclose(out2, out1 * 2, rtol=1e-5)

    def test_multiple_fetches_and_default_program(self):
        prog = static.Program()
        with static.program_guard(prog):
            assert static.default_main_program() is prog
            x = static.data("x", [3])
            a = x + 1
            b = a * a
        exe = static.Executor()
        feed = np.array([1.0, 2.0, 3.0], np.float32)
        ra, rb = exe.run(prog, feed={"x": feed}, fetch_list=[a, b])
        np.testing.assert_allclose(ra, feed + 1)
        np.testing.assert_allclose(rb, (feed + 1) ** 2)

    def test_program_str_and_clone(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2])
            _ = paddle.tanh(x)
        text = str(prog)
        assert "tanh" in text
        c = prog.clone(for_test=True)
        assert c.op_types == prog.op_types

    def test_ops_outside_guard_not_recorded(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2])
            _ = x + 1
        _ = paddle.exp(paddle.to_tensor([1.0]))  # outside: not recorded
        assert "exp" not in prog.op_types


class TestIrAndAsyncCkpt:
    def test_program_to_jaxpr(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4])
            _ = paddle.tanh(x * 2).sum()
        jaxpr = prog.to_jaxpr()
        text = str(jaxpr)
        assert "tanh" in text and "reduce_sum" in text

    def test_async_checkpoint_save(self, tmp_path):
        import paddle_tpu.distributed as dist
        net = nn.Linear(4, 2)
        sd = net.state_dict()
        handle = dist.checkpoint.save_state_dict(
            sd, str(tmp_path / "ck"), async_save=True)
        handle.wait()
        assert handle.done()
        net2 = nn.Linear(4, 2)
        dist.checkpoint.load_state_dict(net2.state_dict(),
                                        str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(net2.weight._value),
                                   np.asarray(net.weight._value))


class TestProgramPasses:
    """The recorded Program is a TRANSFORMABLE IR (SURVEY items 5/6):
    pass manager + DCE / constant folding / CSE / fusion annotation,
    with semantics preserved (reference pir PassManager + fluid passes)."""

    def _build(self):
        import paddle_tpu.static as st
        prog = st.Program()
        with st.program_guard(prog):
            x = st.data("x", [4], "float32")
            a = x * 2.0                 # live chain
            b = a + 1.0
            dead = x - 5.0              # dead: never used
            dead2 = dead * 3.0
            c = paddle.exp(b)
        return prog, c

    def test_executor_runs_pass_pipeline(self):
        """VERDICT r3 #7: the pass pipeline sits IN the execution path —
        Executor.run folds/dedupes/DCEs the recorded program at compile
        time, with a measurable op-count drop and identical semantics."""
        import paddle_tpu.static as st
        prog = st.Program()
        with st.program_guard(prog):
            x = st.data("x", [4], "float32")
            k = paddle.ones([4]) * 3.0        # constant subgraph: folds
            a = x * k
            b = x * k                          # duplicate: CSE
            dead = paddle.exp(b) + 5.0         # unfetched: DCE  # noqa: F841
            y = a + b
        exe = st.Executor()
        r = exe.run(prog, feed={"x": np.full(4, 2.0, np.float32)},
                    fetch_list=[y])
        np.testing.assert_allclose(r[0], np.full(4, 12.0), rtol=1e-6)
        stats = exe.last_pass_stats
        assert [s["pass"] for s in stats] == [
            "constant_folding", "cse", "dead_op_elimination"]
        assert stats[-1]["ops_after"] < stats[0]["ops_before"], stats
        # second run: cache hit, pipeline not re-run, same result
        exe.last_pass_stats = []
        r2 = exe.run(prog, feed={"x": np.full(4, 2.0, np.float32)},
                     fetch_list=[y])
        np.testing.assert_allclose(r2[0], r[0])
        assert exe.last_pass_stats == []

    def test_dead_op_elimination(self):
        import paddle_tpu.static as st
        prog, c = self._build()
        n0 = len(prog.ops)
        out = st.apply_pass(prog, "dead_op_elimination",
                            fetch_ids=[id(c)])
        assert len(out.ops) < n0
        # semantics preserved
        exe = st.Executor()
        r = exe.run(out, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[c])
        np.testing.assert_allclose(r[0], np.exp(np.ones(4) * 2 + 1),
                                   rtol=1e-6)

    def test_constant_folding(self):
        import paddle_tpu.static as st
        prog = st.Program()
        with st.program_guard(prog):
            x = st.data("x", [4], "float32")
            k = paddle.ones([4]) * 3.0      # constant subgraph
            k2 = k + 1.0
            y = x * k2
        n0 = len(prog.ops)
        out = st.apply_pass(prog, "constant_folding", fetch_ids=[id(y)])
        assert len(out.ops) < n0
        exe = st.Executor()
        r = exe.run(out, feed={"x": np.full(4, 2.0, np.float32)},
                    fetch_list=[y])
        np.testing.assert_allclose(r[0], np.full(4, 8.0), rtol=1e-6)

    def test_cse(self):
        import paddle_tpu.static as st
        prog = st.Program()
        with st.program_guard(prog):
            x = st.data("x", [4], "float32")
            a = x * 2.0
            b = x * 2.0                    # duplicate
            y = a + b
        n0 = len(prog.ops)
        p = st.PASS_REGISTRY["cse"]()
        out = p.apply(prog, fetch_ids=[id(y)])
        assert len(out.ops) == n0 - 1
        exe = st.Executor()
        r = exe.run(out, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[y])
        np.testing.assert_allclose(r[0], np.full(4, 4.0), rtol=1e-6)

    def test_fuse_annotation_and_pass_manager(self):
        import paddle_tpu.static as st
        prog, c = self._build()
        pm = st.PassManager(["dead_op_elimination", "fuse_elementwise"])
        out = pm.run(prog, fetch_ids=[id(c)])
        assert pm.stats[0]["ops_after"] < pm.stats[0]["ops_before"]
        assert getattr(out, "fuse_groups", [])  # at least one chain
