"""jit/to_static tests (reference: test/dygraph_to_static — run eager vs
compiled and compare)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 3)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


class TestToStatic:
    def test_function_compiles_and_matches(self):
        net = Net()
        net.eval()
        x = paddle.randn([5, 4])
        eager = _np(net(x))
        static_fn = paddle.jit.to_static(net.forward.__func__.__get__(net))
        compiled = _np(static_fn(x))
        assert np.allclose(eager, compiled, atol=1e-5)

    def test_layer_decoration(self):
        net = Net()
        net.eval()
        x = paddle.randn([2, 4])
        eager = _np(net(x))
        net = paddle.jit.to_static(net)
        out = _np(net(x))
        assert np.allclose(eager, out, atol=1e-5)

    def test_compiled_cache_hit_changes_with_shape(self):
        net = Net()
        sfn = paddle.jit.to_static(net.forward.__func__.__get__(net))
        assert sfn(paddle.randn([2, 4])).shape == [2, 3]
        assert sfn(paddle.randn([7, 4])).shape == [7, 3]

    def test_buffer_update_through_jit(self):
        class BNNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.bn = nn.BatchNorm1D(4)

            def forward(self, x):
                return self.bn(x)

        net = BNNet()
        net.train()
        sfn = paddle.jit.to_static(net.forward.__func__.__get__(net))
        before = _np(net.bn._mean).copy()
        sfn(paddle.randn([8, 4]) + 3)
        after = _np(net.bn._mean)
        assert not np.allclose(before, after), "BN running mean must update"

    def test_control_flow_python_level(self):
        # python-level control flow on shapes works (static unrolling)
        def fn(x):
            if x.shape[0] > 2:
                return paddle.sum(x)
            return paddle.mean(x)
        sfn = paddle.jit.to_static(fn)
        assert np.allclose(float(sfn(paddle.ones([4]))), 4.0)

    def test_graph_break_falls_back_to_eager(self):
        """VERDICT #6: DATA-dependent Python control flow can't trace —
        instead of a hard error, to_static warns once and runs the
        function eagerly (reference SOT's graph-break fallback)."""
        import pytest
        calls = []

        def fn(x):
            calls.append(1)
            if float(paddle.sum(x)) > 0:     # host round trip: untraceable
                return x * 2
            return x - 1

        sfn = paddle.jit.to_static(fn)
        with pytest.warns(RuntimeWarning, match="not fully traceable"):
            out = sfn(paddle.ones([3]))
        assert np.allclose(_np(out), 2.0)
        # negative branch actually executes eagerly now (data-dependent!)
        out2 = sfn(paddle.full([3], -1.0))
        assert np.allclose(_np(out2), -2.0)
        assert sfn._fallback

    def test_mixed_mode_stitches_compiled_subgraphs(self):
        """VERDICT r3 #3 (SOT analogue): after a graph break the function
        is NOT demoted to permanent eager — op chains before and after
        the host-dependent Python run as compiled segments
        (core/lazy.py), cached so repeated calls neither re-trace nor
        re-compile, and the break's branch re-evaluates per call."""
        import pytest
        from paddle_tpu.core import autograd
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 32)
                self.fc2 = nn.Linear(32, 4)

            def forward(self, x):
                h = self.fc1(x)
                # branch on the INPUT sign (not the RNG-dependent
                # weights) so x/xneg deterministically take different
                # paths on any jax PRNG
                if float(paddle.sum(x)) > 0:     # host round trip: break
                    h = h * 2.0
                return self.fc2(h)

        paddle.seed(7)
        net = Net()
        x = paddle.to_tensor(
            np.abs(np.random.RandomState(0).randn(4, 8)).astype(np.float32))
        xneg = paddle.to_tensor(np.full((4, 8), -2.0, np.float32))
        with autograd.no_grad():
            ref = _np(net.forward(x))
            refneg = _np(net.forward(xneg))

        sfn = paddle.jit.to_static(net)
        eng_of = lambda: net._static_function._mixed_engine
        with autograd.no_grad():
            with pytest.warns(RuntimeWarning, match="mixed-mode"):
                out1 = sfn(x)
            eng = eng_of()
            # prefix (fc1+sum) and suffix (mul+fc2) each ran as ONE
            # compiled executable — the matmuls did NOT run eager
            assert eng.compile_count == 2
            assert eng.executable_calls == 2
            np.testing.assert_allclose(_np(out1), ref, rtol=1e-5)

            out2 = sfn(x)                         # cache hit: no re-trace
            assert eng.compile_count == 2
            assert eng.executable_calls == 4
            np.testing.assert_allclose(_np(out2), ref, rtol=1e-5)

            out3 = sfn(xneg)                      # other branch: one new
            assert eng.compile_count == 3         # suffix segment only
            np.testing.assert_allclose(_np(out3), refneg, rtol=1e-5)

            sfn(xneg)                             # and it is cached too
            assert eng.compile_count == 3
        assert not net._static_function._eager    # never demoted

    def test_mixed_mode_getitem_keyed_and_failure_demotes(self):
        """Closure-carrying ops join segments only when identified: two
        different static indices must NOT share a cache entry; and a
        mixed-mode call that raises demotes to plain eager with buffers
        rolled back (no double-applied side effects)."""
        import pytest
        from paddle_tpu.core import autograd

        def fn(x):
            a = x[0] * 2            # getitem closure, lazy_key = repr(0)
            if float(paddle.sum(a)) > -1e9:   # break
                b = x[1] * 2        # different index: different key
            return a + b

        sfn = paddle.jit.to_static(fn)
        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
        with autograd.no_grad():
            with pytest.warns(RuntimeWarning, match="mixed-mode"):
                out = sfn(x)
            np.testing.assert_allclose(
                _np(out), (np.arange(4) * 2 + (np.arange(4) + 4) * 2))
            out2 = sfn(x)
            np.testing.assert_allclose(_np(out), _np(out2))

        def bad(x):
            y = x * 2
            if float(paddle.sum(y)) > 0:
                raise ValueError("host-side failure")
            return y

        sbad = paddle.jit.to_static(bad)
        xp = paddle.ones([3])
        with autograd.no_grad():
            with pytest.warns(RuntimeWarning):
                with pytest.raises(ValueError, match="host-side failure"):
                    sbad(xp)
            assert sbad._eager        # demoted: subsequent calls run eager

    def test_graph_break_full_graph_raises(self):
        import pytest

        def fn(x):
            if float(paddle.sum(x)) > 0:
                return x * 2
            return x - 1

        import jax
        sfn = paddle.jit.to_static(fn, full_graph=True)
        with pytest.raises(jax.errors.ConcretizationTypeError):
            sfn(paddle.ones([3]))

    def test_shape_polymorphic_guard_and_retrace(self):
        """Changed input signature retraces exactly once per new shape
        (jax.jit's cache is the SOT guard table)."""
        def fn(x):
            return paddle.sum(x * 2)

        sfn = paddle.jit.to_static(fn)
        sfn(paddle.ones([2, 4]))
        assert sfn._trace_count == 1
        sfn(paddle.ones([2, 4]) * 3)          # same signature: cache hit
        assert sfn._trace_count == 1
        sfn(paddle.ones([5, 4]))              # new shape: one retrace
        assert sfn._trace_count == 2
        sfn(paddle.ones([5, 4], dtype="float64").astype("int32"))
        assert sfn._trace_count == 3          # new dtype: one retrace


class TestTrainStep:
    def test_compiled_train_step_matches_eager(self):
        paddle.seed(0)
        net1 = Net()
        net2 = Net()
        net2.set_state_dict(net1.state_dict())
        opt1 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net1.parameters())
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        x = paddle.randn([8, 4])
        y = paddle.to_tensor(np.random.randint(0, 3, (8,)))

        def loss_fn(model, xb, yb):
            return F.cross_entropy(model(xb), yb)

        step = paddle.jit.TrainStep(net2, opt2, loss_fn)
        for _ in range(3):
            loss1 = loss_fn(net1, x, y)
            loss1.backward()
            opt1.step()
            opt1.clear_grad()
            loss2 = step(x, y)
        for p1, p2 in zip(net1.parameters(), net2.parameters()):
            assert np.allclose(_np(p1), _np(p2), atol=1e-5)
        assert np.allclose(float(loss1), float(loss2), atol=1e-5)

    def test_train_step_adam_descends(self):
        net = Net()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        x = paddle.randn([16, 4])
        y = paddle.to_tensor(np.random.randint(0, 3, (16,)))
        step = paddle.jit.TrainStep(
            net, opt, lambda m, a, b: F.cross_entropy(m(a), b))
        losses = [float(step(x, y)) for _ in range(20)]
        assert losses[-1] < losses[0] * 0.8


class TestMixedModeTraining:
    """VERDICT r4 #2: mixed-mode capture compiles TRAINING subgraphs —
    grad-requiring ops record into segments, each flushed segment is one
    compiled fwd+vjp pair with one GradNode, and grads bit-match eager."""

    def _branchy_net(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 32)
                self.fc2 = nn.Linear(32, 4)

            def forward(self, x):
                h = self.fc1(x)
                if float(paddle.sum(h)) > 0:   # host round trip: break
                    h = h * 2.0
                return self.fc2(h)
        return Net

    def test_train_step_matmuls_compiled_and_grads_match(self):
        Net = self._branchy_net()
        x_np = np.abs(np.random.RandomState(0).randn(4, 8)).astype(
            np.float32)

        paddle.seed(7)
        ref_net = Net()
        ref_loss = (ref_net.forward(paddle.to_tensor(x_np)) ** 2).mean()
        ref_loss.backward()
        ref_grads = {k: _np(v.grad).copy()
                     for k, v in ref_net.named_parameters()}

        paddle.seed(7)
        net = Net()
        sfn = paddle.jit.to_static(net)
        with pytest.warns(RuntimeWarning, match="mixed-mode"):
            out = sfn(paddle.to_tensor(x_np))
        eng = net._static_function._mixed_engine
        # prefix (fc1+sum) and suffix (mul+fc2) each compiled ONCE and
        # ran as executables — the grad-requiring matmuls did NOT flush
        # to per-op eager
        assert eng.compile_count == 2
        assert eng.executable_calls == 2
        loss = (out ** 2).mean()
        loss.backward()
        assert float(loss) == float(ref_loss)
        for k, p in net.named_parameters():
            np.testing.assert_array_equal(_np(p.grad), ref_grads[k]), k

        # second call: cached executables, fresh GradNodes, same grads
        net.clear_gradients()
        out2 = sfn(paddle.to_tensor(x_np))
        assert eng.compile_count == 2          # no re-compile
        ((out2 ** 2).mean()).backward()
        for k, p in net.named_parameters():
            np.testing.assert_array_equal(_np(p.grad), ref_grads[k])

    def test_optimizer_loop_trains_and_matches_eager(self):
        Net = self._branchy_net()
        xs = [np.random.RandomState(i).randn(4, 8).astype(np.float32)
              for i in range(4)]

        def run(train_net, fn):
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=train_net.parameters())
            losses = []
            for x in xs:
                loss = (fn(paddle.to_tensor(x)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        paddle.seed(3)
        ref_net = Net()
        ref_losses = run(ref_net, ref_net.forward)

        paddle.seed(3)
        net = Net()
        sfn = paddle.jit.to_static(net)
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            losses = run(net, sfn)
        assert losses == ref_losses            # bit-exact through SGD
        for k, p in net.named_parameters():
            np.testing.assert_array_equal(
                _np(p), _np(dict(ref_net.named_parameters())[k]))
        eng = net._static_function._mixed_engine
        assert eng.executable_calls >= 4       # segments ran compiled

    def test_detached_edge_blocks_grad_inside_segment(self):
        def fn(x, w):
            y = x * w
            if float(paddle.sum(y)) > -1e30:   # break: demote to mixed
                pass
            y.stop_gradient = True             # detach mid-graph
            z = (y * w).sum()
            return z

        w_np = np.array([2.0, 3.0], np.float32)
        x_np = np.array([1.0, 4.0], np.float32)

        # eager reference
        w = paddle.to_tensor(w_np, stop_gradient=False)
        fn(paddle.to_tensor(x_np), w).backward()
        ref = _np(w.grad).copy()

        w2 = paddle.to_tensor(w_np, stop_gradient=False)
        sfn = paddle.jit.to_static(fn)
        with pytest.warns(RuntimeWarning, match="mixed-mode"):
            out = sfn(paddle.to_tensor(x_np), w2)
        out.backward()
        np.testing.assert_array_equal(_np(w2.grad), ref)
        # and the detached edge really blocked the x*w path: grad is
        # d/dw [stop(x*w) . w] = x*w elementwise... summed over y*w
        np.testing.assert_allclose(ref, x_np * w_np)

    def test_grad_hook_on_intermediate_fires_with_correct_grads(self):
        """A tensor hook registered on an intra-segment intermediate
        must FIRE (its consumer drops to eager), never be silently
        folded into the compiled backward (review r5 repro: eager grad
        [30,120] vs silently-wrong [15,60])."""
        fired = []

        def fn(x, w):
            y = x * w
            if float(paddle.sum(y)) > -1e30:   # break: demote to mixed
                pass
            h = y * w                          # intermediate in segment
            h.register_hook(lambda g: (fired.append(1), g * 2.0)[1])
            return (h * w).sum()

        w_np = np.array([1.0, 2.0], np.float32)
        x_np = np.array([3.0, 5.0], np.float32)

        w = paddle.to_tensor(w_np, stop_gradient=False)
        fn(paddle.to_tensor(x_np), w).backward()
        ref = _np(w.grad).copy()
        assert fired == [1]

        fired.clear()
        w2 = paddle.to_tensor(w_np, stop_gradient=False)
        sfn = paddle.jit.to_static(fn)
        with pytest.warns(RuntimeWarning, match="mixed-mode"):
            out = sfn(paddle.to_tensor(x_np), w2)
        out.backward()
        assert fired == [1]                    # hook fired
        np.testing.assert_array_equal(_np(w2.grad), ref)

    def test_grad_requiring_segment_failure_raises_loudly(self,
                                                          monkeypatch):
        """A trainable segment whose flush fails must RAISE (the caller
        demotes to eager), never materialize op-by-op without a tape —
        that would mean silent zero grads. A no-grad segment still takes
        the op-by-op safety net."""
        import jax.numpy as jnp
        from paddle_tpu.core.lazy import SegmentEngine
        t = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)

        def boom(nodes):
            raise RuntimeError("segment compile exploded")

        eng = SegmentEngine()
        monkeypatch.setattr(eng, "_flush_compiled", boom)
        eng.record("mul", lambda a, b: a * b, (t._value, 2.0), {},
                   tensor_args=(t, None), wants_grad=True)
        with pytest.raises(RuntimeError, match="segment compile"):
            eng.flush()
        assert eng.failures == 1

        eng2 = SegmentEngine()
        monkeypatch.setattr(eng2, "_flush_compiled", boom)
        lv = eng2.record("mul", lambda a, b: a * b,
                         (jnp.ones(2), 2.0), {})
        eng2.flush()                        # no-grad: eager safety net
        np.testing.assert_allclose(np.asarray(lv.force()), 2.0)
