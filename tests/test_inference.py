"""Inference predictor tests (reference: test/legacy_test inference api
tests — save with jit.save, load via Config/create_predictor, run)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.jit import InputSpec


def _net():
    paddle.seed(5)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestJitSaveLoad:
    def test_save_load_compiled_artifact(self, tmp_path):
        net = _net()
        x = paddle.randn([2, 8])
        want = np.asarray(net(x)._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        loaded = paddle.jit.load(path)
        got = np.asarray(loaded(x)._value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_save_without_spec_keeps_params(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path)
        loaded = paddle.jit.load(path)
        sd = loaded.state_dict()
        assert set(sd) == set(net.state_dict())


class TestPredictor:
    def test_config_create_run(self, tmp_path):
        net = _net()
        x = np.random.RandomState(0).rand(2, 8).astype(np.float32)
        want = np.asarray(net(paddle.to_tensor(x))._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])

        config = inference.Config(path)
        predictor = inference.create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["x0"]
        h = predictor.get_input_handle("x0")
        h.copy_from_cpu(x)
        outs = predictor.run()
        np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
        # output handles
        out_h = predictor.get_output_handle(predictor.get_output_names()[0])
        np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5,
                                   atol=1e-6)

    def test_run_direct_arrays(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        predictor = inference.create_predictor(inference.Config(path))
        x = np.random.rand(2, 8).astype(np.float32)
        outs = predictor.run([x])
        assert outs[0].shape == (2, 4)


class TestServing:
    """Serving path (SURVEY item 14): generation predictor over the
    KV-cache decode + dynamic batching front."""

    def test_generation_predictor_bf16_and_events(self):
        import jax.numpy as jnp
        from paddle_tpu.inference.serving import GenerationPredictor
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.utils.log import default_event_log
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m, bf16=True)
        assert m._parameters["wq"]._value.dtype == jnp.bfloat16
        default_event_log.ring.clear()
        ids = np.random.randint(0, 128, (2, 8)).astype(np.int32)
        out = pred.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 12)
        evs = default_event_log.events("serve_generate")
        assert evs and evs[0]["tokens_per_s"] > 0

    def test_batching_server_coalesces_and_resolves(self):
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m)
        srv = BatchingServer(pred, max_batch=4, max_wait_ms=50,
                             max_new_tokens=4)
        try:
            # same-length prompts coalesce into one batch; a different
            # length runs as its own sub-batch — all resolve correctly
            prompts = [np.random.randint(0, 128, (6,)).astype(np.int32)
                       for _ in range(3)]
            other = np.random.randint(0, 128, (9,)).astype(np.int32)
            reqs = [srv.submit(p) for p in prompts]
            reqs.append(srv.submit(other, max_new_tokens=2))
            outs = [r.wait(timeout=300) for r in reqs]
            for p, o in zip(prompts, outs[:3]):
                assert o.shape == (10,)
                np.testing.assert_array_equal(o[:6], p)
            assert outs[3].shape == (11,)
            np.testing.assert_array_equal(outs[3][:9], other)
            # batched result == solo greedy result (no cross-request
            # contamination)
            solo = pred.generate(prompts[0][None], max_new_tokens=4)[0]
            np.testing.assert_array_equal(outs[0], solo)
        finally:
            srv.close()
