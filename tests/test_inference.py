"""Inference predictor tests (reference: test/legacy_test inference api
tests — save with jit.save, load via Config/create_predictor, run)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.jit import InputSpec


def _net():
    paddle.seed(5)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestJitSaveLoad:
    def test_save_load_compiled_artifact(self, tmp_path):
        net = _net()
        x = paddle.randn([2, 8])
        want = np.asarray(net(x)._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        loaded = paddle.jit.load(path)
        got = np.asarray(loaded(x)._value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_save_without_spec_keeps_params(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path)
        loaded = paddle.jit.load(path)
        sd = loaded.state_dict()
        assert set(sd) == set(net.state_dict())


class TestPredictor:
    def test_config_create_run(self, tmp_path):
        net = _net()
        x = np.random.RandomState(0).rand(2, 8).astype(np.float32)
        want = np.asarray(net(paddle.to_tensor(x))._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])

        config = inference.Config(path)
        predictor = inference.create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["x0"]
        h = predictor.get_input_handle("x0")
        h.copy_from_cpu(x)
        outs = predictor.run()
        np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
        # output handles
        out_h = predictor.get_output_handle(predictor.get_output_names()[0])
        np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5,
                                   atol=1e-6)

    def test_run_direct_arrays(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        predictor = inference.create_predictor(inference.Config(path))
        x = np.random.rand(2, 8).astype(np.float32)
        outs = predictor.run([x])
        assert outs[0].shape == (2, 4)


class TestServing:
    """Serving path (SURVEY item 14): generation predictor over the
    KV-cache decode + dynamic batching front."""

    def test_generation_predictor_bf16_and_events(self):
        import jax.numpy as jnp
        from paddle_tpu.inference.serving import GenerationPredictor
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.utils.log import default_event_log
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m, bf16=True)
        assert m._parameters["wq"]._value.dtype == jnp.bfloat16
        default_event_log.ring.clear()
        ids = np.random.randint(0, 128, (2, 8)).astype(np.int32)
        out = pred.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 12)
        evs = default_event_log.events("serve_generate")
        assert evs and evs[0]["tokens_per_s"] > 0

    def test_mp_sharded_generate_parity(self):
        """Serving a tensor-parallel-sharded model: the cached generate
        program runs with mp-sharded weights (GSPMD inserts the
        collectives) and matches the unsharded decode exactly — the
        multi-chip serving shape an 8B model needs on 16G chips."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        m.eval()
        ids = np.random.RandomState(0).randint(
            1, 128, (2, 10)).astype(np.int32)
        ref = np.asarray(m.generate(ids, max_new_tokens=6,
                                    temperature=0.0)._value)
        mesh = dist.ProcessMesh(shape=[1, 1, 1, 1, 8],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # tiny dims
            dist.shard_model_state(m, mesh)
        out = np.asarray(m.generate(ids, max_new_tokens=6,
                                    temperature=0.0)._value)
        np.testing.assert_array_equal(out, ref)

    def test_masked_generate_matches_per_row(self):
        """attention_mask + left padding: each row of a mixed-length
        masked batch must reproduce its solo unpadded greedy decode
        exactly (positions pad-relative, pad keys excluded)."""
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        m.eval()
        rng = np.random.RandomState(0)
        p1 = rng.randint(1, 128, (1, 5)).astype(np.int32)
        p2 = rng.randint(1, 128, (1, 9)).astype(np.int32)
        r1 = np.asarray(m.generate(p1, max_new_tokens=6,
                                   temperature=0.0)._value)
        r2 = np.asarray(m.generate(p2, max_new_tokens=6,
                                   temperature=0.0)._value)
        s0 = 9
        batch = np.zeros((2, s0), np.int32)
        mask = np.zeros((2, s0), np.int32)
        batch[0, s0 - 5:] = p1[0]
        mask[0, s0 - 5:] = 1
        batch[1] = p2[0]
        mask[1] = 1
        out = np.asarray(m.generate(batch, max_new_tokens=6,
                                    temperature=0.0,
                                    attention_mask=mask)._value)
        np.testing.assert_array_equal(out[0, s0 - 5:], r1[0])
        np.testing.assert_array_equal(out[1], r2[0])

    def test_chunked_decode_attention_parity(self):
        """VERDICT r3 #4b: the chunked (online-softmax) decode path is
        bit-identical to the single-pass full-cache softmax."""
        from paddle_tpu.models import llama
        paddle.seed(0)
        m = llama.LlamaForCausalLM("debug")
        m.eval()
        ids = np.random.RandomState(0).randint(
            1, 128, (2, 12)).astype(np.int32)
        ref = np.asarray(m.generate(ids, max_new_tokens=8,
                                    temperature=0.0)._value)
        old = llama._DECODE_CHUNK
        llama._GEN_CACHE.clear()
        llama._DECODE_CHUNK = 8      # force chunking on the tiny cache
        try:
            got = np.asarray(m.generate(ids, max_new_tokens=8,
                                        temperature=0.0)._value)
        finally:
            llama._DECODE_CHUNK = old
            llama._GEN_CACHE.clear()
        np.testing.assert_array_equal(ref, got)

    def test_int8_weight_only_parity(self):
        """VERDICT r3 #4c: int8 PTQ weights wired into the predictor —
        generation with in-program dequant matches a float model carrying
        the same quantization error exactly; weights live as int8."""
        import jax.numpy as jnp
        from paddle_tpu.inference.serving import GenerationPredictor
        from paddle_tpu.models.llama import LlamaForCausalLM
        rng = np.random.RandomState(0)
        ids = rng.randint(1, 128, (2, 10)).astype(np.int32)

        paddle.seed(4)
        m_ref = LlamaForCausalLM("debug")
        names = [x for x in m_ref._stacked_names()
                 if not x.endswith(("_ln", "bq", "bk", "bv", "router"))]
        for n in names + ["lm_head"]:
            p = m_ref._parameters[n]
            w = p._value.astype(jnp.float32)
            amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
            scale = jnp.maximum(amax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(w / scale), -127, 127)
            p._in_place_update((q * scale).astype(jnp.float32))
        ref = np.asarray(m_ref.generate(ids, max_new_tokens=6,
                                        temperature=0.0)._value)

        paddle.seed(4)
        m_q = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m_q, int8=True)
        assert m_q._parameters["wq"]._value.dtype == jnp.int8
        out = pred.generate(ids, max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(out, ref)

    def test_mixed_lengths_share_one_program(self):
        """VERDICT r3 #4a: unequal-length prompts merge into ONE
        masked generate call (previously one sub-batch per distinct
        length), with per-row greedy parity against solo generation."""
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m)
        calls = []
        orig = pred.generate
        pred.generate = lambda *a, **k: calls.append(1) or orig(*a, **k)
        srv = BatchingServer(pred, max_batch=4, max_wait_ms=200,
                             max_new_tokens=4)
        try:
            rng = np.random.RandomState(1)
            prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                       for n in (4, 7, 11)]
            reqs = [srv.submit(p) for p in prompts]
            outs = [r.wait(timeout=300) for r in reqs]
            assert len(calls) == 1, f"expected ONE merged call, got {calls}"
            for p, o in zip(prompts, outs):
                assert o.shape == (p.size + 4,)
                np.testing.assert_array_equal(o[:p.size], p)
                solo = orig(p[None], max_new_tokens=4)[0]
                np.testing.assert_array_equal(o, solo)
        finally:
            srv.close()

    def test_batching_server_coalesces_and_resolves(self):
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m)
        srv = BatchingServer(pred, max_batch=4, max_wait_ms=50,
                             max_new_tokens=4)
        try:
            # same-length prompts coalesce into one batch; a different
            # length runs as its own sub-batch — all resolve correctly
            prompts = [np.random.randint(0, 128, (6,)).astype(np.int32)
                       for _ in range(3)]
            other = np.random.randint(0, 128, (9,)).astype(np.int32)
            reqs = [srv.submit(p) for p in prompts]
            reqs.append(srv.submit(other, max_new_tokens=2))
            outs = [r.wait(timeout=300) for r in reqs]
            for p, o in zip(prompts, outs[:3]):
                assert o.shape == (10,)
                np.testing.assert_array_equal(o[:6], p)
            assert outs[3].shape == (11,)
            np.testing.assert_array_equal(outs[3][:9], other)
            # batched result == solo greedy result (no cross-request
            # contamination)
            solo = pred.generate(prompts[0][None], max_new_tokens=4)[0]
            np.testing.assert_array_equal(outs[0], solo)
        finally:
            srv.close()

    def test_close_is_idempotent_and_submit_after_close_raises(self):
        """Regression (ISSUE 2 satellite): a second close() must be a
        no-op, and submit() on a closed server must raise immediately
        instead of parking a request no worker will ever serve."""
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        pred = GenerationPredictor(m)
        srv = BatchingServer(pred, max_batch=2, max_wait_ms=50,
                             max_new_tokens=2)
        p = np.random.randint(1, 128, (5,)).astype(np.int32)
        srv.submit(p).wait(timeout=300)    # server demonstrably works
        srv.close()
        srv.close()                        # second close: no-op, no error
        with pytest.raises(RuntimeError, match="closed BatchingServer"):
            srv.submit(p)


class TestOnnxBridge:
    """VERDICT r4 missing #3: onnx.export is no longer a silent stub —
    without paddle2onnx it writes the documented StableHLO bridge
    artifact (SURVEY §7.4)."""

    def test_export_writes_bridge_artifact(self, tmp_path):
        import json
        import pickle

        import paddle_tpu.nn as nn
        from paddle_tpu.jit.api import InputSpec

        net = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
        path = str(tmp_path / "model")
        mpath = paddle.onnx.export(net, path,
                                   input_spec=[InputSpec([2, 8])],
                                   opset_version=13)
        manifest = json.load(open(mpath))
        assert manifest["format"] == "paddle_tpu-onnx-bridge/1"
        assert manifest["opset_version_requested"] == 13
        assert manifest["inputs"][0]["shape"] == [2, 8]
        with open(path + ".pdmodel", "rb") as f:
            payload = pickle.load(f)
        assert payload["stablehlo"] is not None
        # the bridged program is directly servable via jit.load
        loaded = paddle.jit.load(path)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8).astype(np.float32))
        ref = np.asarray(net(x)._value)
        got = np.asarray(loaded(x)._value)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_export_requires_input_spec(self, tmp_path):
        import pytest
        with pytest.raises(ValueError, match="input_spec"):
            paddle.onnx.export(nn.Linear(4, 2), str(tmp_path / "m"))


class TestContinuousBatching:
    """VERDICT r4 #5: continuous batching — carried-KV DecodeEngine with
    chunk-boundary admit/retire — and the masked path under pp>1."""

    def _model(self):
        paddle.seed(0)
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        m.eval()
        return m

    @staticmethod
    def _drive(eng, pending, iters=200):
        """Run the engine loop until every pending request is served."""
        for _ in range(iters):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                return
        raise AssertionError("engine did not drain the workload")

    def _workload(self, rng):
        # 2 long generations + 6 shorts: batch-at-a-time rides every
        # tick to its max(max_new); the engine retires shorts early and
        # admits the next ones into the freed slots
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 10, 5, 6, 7, 5, 6, 4)]
        max_news = [16, 16, 4, 4, 4, 4, 4, 4]
        return prompts, max_news

    def test_engine_parity_with_solo_generation(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(1)
        prompts, max_news = self._workload(rng)
        refs = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=mn,
            temperature=0.0)._value)[0]
            for p, mn in zip(prompts, max_news)]
        eng = DecodeEngine(m, capacity=4, s_max=96, chunk=4)
        reqs = [_Request(p, mn) for p, mn in zip(prompts, max_news)]
        pending = list(reqs)
        self._drive(eng, pending)
        for req, ref in zip(reqs, refs):
            np.testing.assert_array_equal(req.wait(timeout=1), ref)

    def test_engine_beats_batch_at_a_time_on_decode_steps(self):
        """Same workload, same FIFO order: the engine executes fewer
        decode program-steps than batch-at-a-time, because shorts retire
        at chunk boundaries and later shorts reuse their slots while the
        longs are still running (deterministic device-work comparison,
        not wall-clock)."""
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  DecodeEngine,
                                                  GenerationPredictor,
                                                  _Request)
        m = self._model()
        rng = np.random.RandomState(1)
        prompts, max_news = self._workload(rng)

        # batch-at-a-time baseline: count decode steps = max_new per tick
        pred = GenerationPredictor(m)
        steps = []
        orig = pred.generate

        def counting(ids, max_new_tokens=32, **kw):
            steps.append(int(max_new_tokens))
            return orig(ids, max_new_tokens=max_new_tokens, **kw)

        pred.generate = counting
        srv = BatchingServer(pred, max_batch=4, max_wait_ms=200.0)
        reqs = [srv.submit(p, mn) for p, mn in zip(prompts, max_news)]
        outs = [r.wait(timeout=300) for r in reqs]
        srv.close()
        baseline_steps = sum(steps)
        assert baseline_steps >= 20     # tick1 rides the longs' 16

        eng = DecodeEngine(m, capacity=4, s_max=96, chunk=4)
        pend = [_Request(p, mn) for p, mn in zip(prompts, max_news)]
        pending = list(pend)
        self._drive(eng, pending)
        for r in pend:
            r.wait(timeout=1)
        assert eng.device_steps < baseline_steps, (
            eng.device_steps, baseline_steps)
        # and the engine's outputs match the batch path's
        for r, out in zip(pend, outs):
            np.testing.assert_array_equal(
                r.result[-r.max_new:], out[-r.max_new:])

    def test_continuous_server_staggered_arrivals(self):
        """Threaded server: late arrivals join mid-generation at chunk
        boundaries and every future resolves with solo-parity tokens."""
        import time as _time
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        m = self._model()
        rng = np.random.RandomState(2)
        prompts, max_news = self._workload(rng)
        refs = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=mn,
            temperature=0.0)._value)[0]
            for p, mn in zip(prompts, max_news)]
        pred = GenerationPredictor(m)
        srv = BatchingServer(pred, max_batch=4, continuous=True,
                             engine_kwargs={"s_max": 96, "chunk": 4})
        try:
            first = [srv.submit(p, mn)
                     for p, mn in zip(prompts[:2], max_news[:2])]
            _time.sleep(0.3)            # longs are mid-generation
            rest = [srv.submit(p, mn)
                    for p, mn in zip(prompts[2:], max_news[2:])]
            for req, ref in zip(first + rest, refs):
                np.testing.assert_array_equal(req.wait(timeout=300), ref)
        finally:
            srv.close()

    def test_engine_on_mp_sharded_mesh(self):
        """Continuous batching on a tensor-parallel serving mesh: the
        engine's prefill/decode programs consume mp-sharded weights
        (GSPMD inserts the collectives) with solo-parity tokens — the
        multi-chip serving shape an 8B model needs on 16G chips."""
        import warnings

        import paddle_tpu.distributed as dist
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 5)]
        refs = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=5,
            temperature=0.0)._value)[0] for p in prompts]
        mesh = dist.ProcessMesh(shape=[1, 1, 1, 1, 8],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # tiny dims
            dist.shard_model_state(m, mesh)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4)
        reqs = [_Request(p, 5) for p in prompts]
        pending = list(reqs)
        self._drive(eng, pending)
        for req, ref in zip(reqs, refs):
            np.testing.assert_array_equal(req.wait(timeout=1), ref)

    def test_engine_int8_dequantizes_in_program(self):
        """An int8 weight-only model serves through the engine: the
        dequant runs inside the compiled prefill/decode programs and
        tokens match the cached generate path exactly."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        from paddle_tpu.models.llama import quantize_weights_int8
        m = self._model()
        quantize_weights_int8(m)
        rng = np.random.RandomState(4)
        p = rng.randint(1, 128, (7,)).astype(np.int32)
        ref = np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=5,
            temperature=0.0)._value)[0]
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4)
        req = _Request(p, 5)
        pending = [req]
        self._drive(eng, pending)
        np.testing.assert_array_equal(req.wait(timeout=1), ref)

    def test_continuous_falls_back_on_pp_mesh(self):
        """continuous=True on a pipeline mesh degrades loudly to the
        masked batch loop instead of crashing at construction."""
        import warnings

        import jax as _jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        m = self._model()
        p = np.random.RandomState(6).randint(1, 128, (7,)).astype(
            np.int32)
        ref = np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=3,
            temperature=0.0)._value)[0]
        mesh = Mesh(np.array(_jax.devices()[:2]).reshape(2, 1),
                    ("pp", "mp"))
        with sharding_ctx(mesh):
            pred = GenerationPredictor(m)
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                srv = BatchingServer(pred, continuous=True,
                                     max_wait_ms=50.0)
            try:
                assert any("falling back" in str(x.message)
                           for x in rec)
                assert srv.engine is None
                np.testing.assert_array_equal(
                    srv.submit(p, 3).wait(timeout=300), ref)
            finally:
                srv.close()

    def test_pp2_masked_batching(self):
        """supports_mask() is True on a pp=2 mesh (r5): mixed-length
        prompts share ONE masked program through the pipeline prefill,
        with per-row solo parity."""
        import jax as _jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        m = self._model()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (9, 5, 12)]
        refs = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=4,
            temperature=0.0)._value)[0] for p in prompts]
        mesh = Mesh(np.array(_jax.devices()[:4]).reshape(2, 2),
                    ("pp", "mp"))
        with sharding_ctx(mesh):
            pred = GenerationPredictor(m)
            assert pred.supports_mask()          # pp>1 no longer opts out
            calls = []
            orig = pred.generate

            def counting(ids, **kw):
                calls.append(np.asarray(ids).shape)
                return orig(ids, **kw)

            pred.generate = counting
            srv = BatchingServer(pred, max_batch=4, max_wait_ms=300.0,
                                 max_new_tokens=4)
            try:
                reqs = [srv.submit(p, 4) for p in prompts]
                for req, ref in zip(reqs, refs):
                    np.testing.assert_array_equal(req.wait(timeout=600),
                                                  ref)
            finally:
                srv.close()
            assert len(calls) == 1               # ONE masked program
            assert calls[0][0] == 3              # all rows together
