"""Inference predictor tests (reference: test/legacy_test inference api
tests — save with jit.save, load via Config/create_predictor, run)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.jit import InputSpec


def _net():
    paddle.seed(5)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestJitSaveLoad:
    def test_save_load_compiled_artifact(self, tmp_path):
        net = _net()
        x = paddle.randn([2, 8])
        want = np.asarray(net(x)._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        loaded = paddle.jit.load(path)
        got = np.asarray(loaded(x)._value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_save_without_spec_keeps_params(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path)
        loaded = paddle.jit.load(path)
        sd = loaded.state_dict()
        assert set(sd) == set(net.state_dict())


class TestPredictor:
    def test_config_create_run(self, tmp_path):
        net = _net()
        x = np.random.RandomState(0).rand(2, 8).astype(np.float32)
        want = np.asarray(net(paddle.to_tensor(x))._value)
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])

        config = inference.Config(path)
        predictor = inference.create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["x0"]
        h = predictor.get_input_handle("x0")
        h.copy_from_cpu(x)
        outs = predictor.run()
        np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
        # output handles
        out_h = predictor.get_output_handle(predictor.get_output_names()[0])
        np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5,
                                   atol=1e-6)

    def test_run_direct_arrays(self, tmp_path):
        net = _net()
        path = str(tmp_path / "m")
        paddle.jit.save(net, path, input_spec=[InputSpec([2, 8])])
        predictor = inference.create_predictor(inference.Config(path))
        x = np.random.rand(2, 8).astype(np.float32)
        outs = predictor.run([x])
        assert outs[0].shape == (2, 4)
