"""Sequence-parallel paged attention over the 2-D (seq, tp) mesh
(ISSUE 16): the block-pool PAGE axis shards over ``seq``, each shard
runs the online-softmax over only the pages it owns, and one
partial-accumulator merge (pmax + two psums — ring-attention math on a
flat topology) finishes attention. The correctness contract is strict
BIT-parity of greedy tokens:

- tp x seq SHARDED engines (including tp*seq > n_kv_heads, the
  configuration a kv-head-only mesh cannot legally build) vs the
  unsharded engine on the same seeded arrivals, with prefix cache +
  chunked prefill + spec decode + int8 KV exercised;
- ``seq_degree=1`` must reproduce the 1-D tp engine (and the unsharded
  engine) byte-exactly — the second axis is pure wiring until used.

Kernel-level edge rows (satellite): q_len=0 padding rows stay EXACT
zero through the partial merge, and a final partial page landing on a
shard boundary matches a float64 oracle. Host-side: the striped
allocator keeps table column j in stripe j % seq across every
allocation path, and mesh validation reports ALL violated constraints
at once, naming ``seq`` as the escape hatch past the kv-head cap."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.paged_cache import BlockAllocator
from paddle_tpu.inference.serving import DecodeEngine
from paddle_tpu.inference.sharding import (make_mesh, make_tp_mesh,
                                           validate_mesh_config)


def _model(preset="debug"):
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM(preset)
    m.eval()
    return m


def _drain(eng, reqs):
    eng.admit([])
    for _ in range(10000):
        eng.decode_once()
        eng.admit([])
        if eng.idle():
            break
    return [np.asarray(r.wait(timeout=120)) for r in reqs]


def _run(m, prompts, max_new=8, mesh=None, **kw):
    eng = DecodeEngine(m, capacity=4, s_max=64, chunk=4, block_size=8,
                       mesh=mesh, **kw)
    reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    outs = _drain(eng, reqs)
    return outs, eng


def _prompts(rng, vocab, sizes):
    return [rng.randint(1, vocab, (n,)).astype(np.int32)
            for n in sizes]


class TestSeqParallelParity:
    def test_2x4_beyond_kv_heads_all_features_parity(self):
        """The acceptance oracle: tp=2 x seq=4 = 8 devices on a
        2-kv-head model — four times past the kv-head cap — with
        prefix cache + chunked prefill + spec decode ON, bit-identical
        to the unsharded engine across a cache-seeding wave and a
        hit + COW wave."""
        m = _model()                       # debug: 4 heads / 2 kv heads
        rng = np.random.RandomState(0)
        shared = rng.randint(1, 128, (10,)).astype(np.int32)
        wave1 = [np.tile(rng.randint(1, 128, (5,)).astype(np.int32), 4),
                 shared]
        wave2 = [np.concatenate([shared, rng.randint(
                     1, 128, (7,)).astype(np.int32)]),
                 rng.randint(1, 128, (19,)).astype(np.int32)]
        kw = dict(prefix_cache=True, chunked_prefill=True,
                  spec_decode=True)

        def run(mesh):
            eng = DecodeEngine(m, capacity=4, s_max=64, chunk=4,
                               block_size=8, mesh=mesh, **kw)
            outs = []
            for wave in (wave1, wave2):
                reqs = [eng.submit(p, max_new_tokens=10) for p in wave]
                outs += _drain(eng, reqs)
            return outs, eng

        base, _ = run(None)
        outs, eng = run(make_mesh(2, 4))
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        s = eng.stats()
        assert s["tp_degree"] == 2
        assert s["seq_degree"] == 4
        assert s["mesh_shape"] == {"seq": 4, "tp": 2}
        assert s["prefix_hit_tokens"] > 0
        assert s["spec"]["proposed"] > 0
        assert s["prefill_chunks"] > 0
        assert s["pool"]["stripes"] == 4

    def test_int8_kv_2d_parity(self):
        """int8 paged KV under page sharding: quantized insert/scatter
        route writes through the owned-page drop path and reads clamp,
        bit-matching the unsharded int8 engine."""
        m = _model()
        rng = np.random.RandomState(1)
        prompts = _prompts(rng, 128, (5, 19, 11))
        base, _ = _run(m, prompts, kv_dtype="int8", prefix_cache=True)
        outs, eng = _run(m, prompts, mesh=make_mesh(2, 2),
                         kv_dtype="int8", prefix_cache=True)
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        assert eng.stats()["seq_degree"] == 2

    def test_seq_only_mesh_parity(self):
        """tp=1, seq=4: page parallelism alone (no kv-head split at
        all) still bit-matches — the two axes are independent."""
        m = _model()
        rng = np.random.RandomState(2)
        prompts = _prompts(rng, 128, (7, 33, 12))
        base, _ = _run(m, prompts, chunked_prefill=True,
                       spec_decode=True)
        outs, eng = _run(m, prompts, mesh=make_mesh(1, 4),
                         chunked_prefill=True, spec_decode=True)
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        assert eng.stats()["mesh_shape"] == {"seq": 4, "tp": 1}

    def test_seq1_reproduces_1d_engine(self):
        """seq_degree=1 is the regression satellite: a (1, tp) 2-D mesh
        must produce exactly the 1-D tp engine's outputs (and the
        unsharded engine's), with the unstriped allocator snapshot."""
        m = _model()
        rng = np.random.RandomState(3)
        prompts = _prompts(rng, 128, (9, 17))
        base, _ = _run(m, prompts)
        out1d, e1 = _run(m, prompts, mesh=make_tp_mesh(2))
        out2d, e2 = _run(m, prompts, mesh=make_mesh(2, 1))
        for a, b, c in zip(base, out1d, out2d):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        assert e2.stats()["seq_degree"] == 1
        # stripes=1 keeps the r6 pool-stats shape: no "stripes" key
        assert "stripes" not in e2.stats()["pool"]
        assert e1.stats()["pool"] == e2.stats()["pool"]

    def test_pool_arrays_actually_sharded_2d(self):
        """The tentpole's point: per-device KV footprint is
        1/(tp*seq) of the pool — page axis split over seq, kv-head
        axis split over tp."""
        m = _model()
        eng = DecodeEngine(m, capacity=2, s_max=64, block_size=8,
                           mesh=make_mesh(2, 2), kv_dtype="int8")
        for arr in (eng._kp, eng._vp):
            shard = arr.addressable_shards[0]
            assert shard.data.shape[1] == arr.shape[1] // 2
            assert shard.data.shape[3] == arr.shape[3] // 2
        for arr in (eng._kscale, eng._vscale):
            shard = arr.addressable_shards[0]
            assert shard.data.shape[1] == arr.shape[1] // 2
            assert shard.data.shape[2] == arr.shape[2] // 2


class TestSeqKernelEdgeRows:
    """Satellite: mixed-kernel edge rows under page sharding, against
    a float64 oracle built from the same global pools."""

    def _setup(self, rng, n_seq=4, n_blocks=8, bs=4, kvh=2, G=2, hd=8,
               B=2, mb=4):
        kp = rng.standard_normal((n_blocks, bs, kvh, hd)) \
            .astype(np.float32)
        vp = rng.standard_normal((n_blocks, bs, kvh, hd)) \
            .astype(np.float32)
        # striping invariant by construction: column j holds a page
        # from stripe j % n_seq (stripe s owns [2s, 2s+2))
        table = np.zeros((B, mb), np.int32)
        table[0] = [1, 3, 5, 7]
        return kp, vp, table

    def _sharded(self, fn_name, q, kp, vp, table, *lens, n_seq=4):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        import paddle_tpu.kernels.paged_attention as pa
        from paddle_tpu.utils.compat import shard_map
        mesh = Mesh(np.asarray(jax.devices()[:n_seq]), ("seq",))
        kern = getattr(pa, fn_name)

        def prog(q, kp, vp, table, *lens):
            return kern(q, kp, vp, table, *lens, seq_axis="seq",
                        n_seq=n_seq)

        sharded = shard_map(
            prog, mesh=mesh,
            in_specs=(P(), P("seq"), P("seq"), P(),
                      *([P()] * len(lens))),
            out_specs=P())
        return np.asarray(sharded(q, kp, vp, table, *lens))

    def _oracle_row(self, q_row, keys, vals, n_keys):
        """float64 causal-free softmax over the first n_keys keys for
        one [G, hd] query (decode: attends everything resident)."""
        qf = q_row.astype(np.float64)
        k = keys[:n_keys].astype(np.float64)
        v = vals[:n_keys].astype(np.float64)
        s = qf @ k.T / np.sqrt(q_row.shape[-1])
        s -= s.max(axis=-1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=-1, keepdims=True)
        return p @ v

    def test_partial_page_on_shard_boundary_matches_f64(self):
        """seq_len=13 with bs=4: three full pages on shards 0-2 and a
        final 1-token partial page alone on shard 3 — the merge must
        weight that shard's single key exactly like the dense f64
        softmax does."""
        rng = np.random.default_rng(0)
        kp, vp, table = self._setup(rng)
        q = rng.standard_normal((2, 2, 2, 8)).astype(np.float32)
        seq_lens = np.array([13, 0], np.int32)
        out = self._sharded("paged_decode_attention", q, kp, vp,
                            table, seq_lens)
        keys = kp[table[0]].reshape(-1, 2, 8)       # [16, kvh, hd]
        vals = vp[table[0]].reshape(-1, 2, 8)
        for n in range(2):                           # kv head
            ref = self._oracle_row(q[0, n], keys[:, n], vals[:, n], 13)
            np.testing.assert_allclose(out[0, n], ref, rtol=2e-5,
                                       atol=2e-6)

    def test_zero_len_rows_stay_exact_zero(self):
        """q_len=0 / kv_len=0 padding rows: every shard's l is 0, so
        the merged accumulator floors at eps over a zero numerator —
        EXACT zeros, not NaN, not denormal noise."""
        rng = np.random.default_rng(1)
        kp, vp, table = self._setup(rng)
        B, T = 2, 4
        q = rng.standard_normal((B, T, 2, 2, 8)).astype(np.float32)
        kv_lens = np.array([13, 0], np.int32)
        q_lens = np.array([4, 0], np.int32)
        out = self._sharded("mixed_paged_attention", q, kp, vp, table,
                            kv_lens, q_lens)
        assert np.all(out[1] == 0.0)
        assert np.all(np.isfinite(out))

    def test_mixed_causal_tail_matches_f64(self):
        """The mixed launch's causal window across the shard-strided
        keys: query t attends keys <= kv_len - q_len + t, including the
        boundary partial page."""
        rng = np.random.default_rng(2)
        kp, vp, table = self._setup(rng)
        q = rng.standard_normal((2, 4, 2, 2, 8)).astype(np.float32)
        kv_lens = np.array([13, 0], np.int32)
        q_lens = np.array([4, 0], np.int32)
        out = self._sharded("mixed_paged_attention", q, kp, vp, table,
                            kv_lens, q_lens)
        keys = kp[table[0]].reshape(-1, 2, 8)
        vals = vp[table[0]].reshape(-1, 2, 8)
        for t in range(4):
            n_vis = 13 - 4 + t + 1
            for n in range(2):
                ref = self._oracle_row(q[0, t, n], keys[:, n],
                                       vals[:, n], n_vis)
                np.testing.assert_allclose(out[0, t, n], ref,
                                           rtol=2e-5, atol=2e-6)


class TestStripedAllocator:
    def test_column_residency_invariant(self):
        """allocate(n, start_col) must hand page i from stripe
        (start_col + i) % stripes — the invariant every strided
        per-shard gather depends on."""
        a = BlockAllocator(16, stripes=4)           # stripe size 4
        for start in (0, 1, 3, 6):
            pages = a.allocate(5, start_col=start)
            assert pages is not None
            for i, p in enumerate(pages):
                assert a.stripe_of(p) == (start + i) % 4
            a.free(pages)
        assert a.conservation_ok

    def test_all_or_nothing_per_stripe(self):
        """A request fails when ITS stripes can't cover it, even with
        free pages elsewhere — exactly what a physically sharded pool
        enforces."""
        a = BlockAllocator(8, stripes=4)    # stripe 0 has 1 page (NULL)
        first = a.allocate(4, start_col=0)  # one page from each stripe
        assert first is not None
        assert a.allocate(1, start_col=0) is None   # stripe 0 empty
        assert a.num_free == 3                      # others untouched
        assert a.shortfall(1, start_col=0) == 1
        assert a.shortfall(1, start_col=1) == 0
        assert a.allocate(1, start_col=1) is not None

    def test_free_returns_to_owning_stripe(self):
        a = BlockAllocator(12, stripes=3)
        pages = a.allocate(6, start_col=2)
        a.free(pages)
        again = a.allocate(6, start_col=2)
        for i, p in enumerate(again):
            assert a.stripe_of(p) == (2 + i) % 3
        # decref path too (prefix sharing)
        a.incref(again[0])
        a.decref(again[0])
        a.decref(again[0])
        assert a.stripe_of(a.allocate(1, start_col=2)[0]) == 2

    def test_stats_and_validation(self):
        assert "stripes" not in BlockAllocator(8).stats()
        assert BlockAllocator(8, stripes=2).stats()["stripes"] == 2
        with pytest.raises(ValueError, match="divisible"):
            BlockAllocator(9, stripes=2)
        with pytest.raises(ValueError, match="NULL"):
            BlockAllocator(8, stripes=8)    # stripe 0 would be empty
        # stripes=1 keeps the full r6 free list (capacity unchanged)
        assert BlockAllocator(8, stripes=1).num_free == 7

    def test_shortfall_unstriped_matches_global(self):
        a = BlockAllocator(8)
        a.allocate(4)
        assert a.shortfall(5) == 2
        assert a.shortfall(3) == 0


class TestValidationAggregate:
    def test_reports_all_violations_in_one_message(self):
        """Satellite: a bad degree lists EVERY violated divisibility
        constraint, not just the first."""
        m = _model()                        # 4 heads / 2 kv heads
        with pytest.raises(ValueError) as e:
            validate_mesh_config(m.config, 3)
        msg = str(e.value)
        assert "num_key_value_heads" in msg
        assert "num_attention_heads" in msg
        assert "intermediate_size" in msg

    def test_kv_head_cap_names_seq_escape_hatch(self):
        """tp past the kv-head count points at the 2-D mesh instead of
        dead-ending."""
        m = _model()
        with pytest.raises(ValueError, match="seq_degree>1"):
            validate_mesh_config(m.config, 4)
        with pytest.raises(ValueError, match="seq_degree>1"):
            DecodeEngine(m, capacity=2, s_max=64, block_size=8,
                         mesh=make_tp_mesh(4))

    def test_n_blocks_must_divide_over_seq(self):
        m = _model()
        with pytest.raises(ValueError, match="n_blocks"):
            validate_mesh_config(m.config, 2, seq=2, n_blocks=7)
        with pytest.raises(ValueError, match="n_blocks"):
            DecodeEngine(m, capacity=2, s_max=64, block_size=8,
                         n_blocks=7, mesh=make_mesh(2, 2))

    def test_mesh_needs_enough_devices(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh(4, 4)                 # 16 > the 8 virtual devices
        with pytest.raises(ValueError):
            make_mesh(0, 2)


class TestObservability:
    def test_engine_seq_degree_gauge_and_stats(self):
        """Satellite: stats()/statusz report the full mesh shape per
        engine and the engine_seq_degree gauge reads it live."""
        m = _model()
        rng = np.random.RandomState(5)
        outs, eng = _run(m, _prompts(rng, 128, (9,)),
                         mesh=make_mesh(2, 2))
        snap = eng.metrics.snapshot()
        assert snap["gauges"]["engine_tp_degree"] == 2
        assert snap["gauges"]["engine_seq_degree"] == 2
        s = eng.stats()
        assert s["seq_degree"] == 2
        assert s["mesh_shape"] == {"seq": 2, "tp": 2}
        # unsharded engines still report degree 1 (gauge always there)
        _, e0 = _run(m, _prompts(rng, 128, (5,)))
        assert e0.metrics.snapshot()["gauges"]["engine_seq_degree"] == 1


class TestSeqParallelFleet:
    def test_fleet_2d_submesh_parity_and_stats(self):
        """ServingFleet(tp_degree=2, seq_degree=4): the worker builds
        a (4, 2) submesh past the kv-head cap and routed traffic
        bit-matches the solo unsharded engine; fleet stats carry
        seq_degree beside tp_degree."""
        from paddle_tpu.inference.fleet import ServingFleet
        m = _model()
        rng = np.random.RandomState(6)
        prompts = _prompts(rng, 128, (9, 21))
        base, _ = _run(m, prompts)
        fl = ServingFleet(m, n_workers=1, tp_degree=2, seq_degree=4,
                          engine_kwargs=dict(capacity=4, s_max=64,
                                             chunk=4, block_size=8))
        try:
            reqs = [fl.submit(p, max_new_tokens=8) for p in prompts]
            for _ in range(3000):
                if fl.step() == 0 and all(not w.pending
                                          for w in fl.workers):
                    break
            outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
            for a, b in zip(base, outs):
                np.testing.assert_array_equal(a, b)
            s = fl.stats()
            assert s["tp_degree"] == 2
            assert s["seq_degree"] == 4
            ws = list(s["workers"].values())[0]
            assert ws["mesh_shape"] == {"seq": 4, "tp": 2}
        finally:
            fl.close()

    def test_fleet_rejects_oversubscribed_2d_submeshes(self):
        from paddle_tpu.inference.fleet import ServingFleet
        m = _model()
        with pytest.raises(ValueError, match="seq_degree"):
            ServingFleet(m, n_workers=2, tp_degree=2, seq_degree=4,
                         engine_kwargs=dict(capacity=2, s_max=64))
