"""Test config: force CPU with 8 virtual devices so sharding/collective
tests run without TPU hardware (SURVEY §4: the reference tests multi-device
via multi-process on localhost; the JAX analogue is a virtual device mesh).

Note: the axon TPU plugin ignores JAX_PLATFORMS, so we must use jax.config
before any backend initialization."""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices; the pre-backend-init XLA
    # flag is the same knob under its old spelling (safe here: conftest
    # runs before any test touches a device)
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)  # staticcheck: disable=SC04 — the fixture that seeds replay
    yield
