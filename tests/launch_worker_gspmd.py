"""Multi-controller compiled-collective proof worker (VERDICT r3 #2).

Run two ways with IDENTICAL seeds/data so losses must match:
- single process, 8 local CPU devices (GSPMD_LOCAL_DEVICES=8, no launch)
- 2 processes × 4 CPU devices under ``python -m
  paddle_tpu.distributed.launch --nproc_per_node 2`` — ONE shared
  8-device mesh, jax.distributed rendezvous, GSPMD collectives compiled
  ACROSS the process boundary (gloo CPU data plane).

This is the JAX analogue of the reference's multi-process-on-localhost
harness (test/legacy_test/test_parallel_dygraph_dataparallel.py:157) and
the shape that matches a v5p pod's one-process-per-host reality.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices",
                  int(os.environ.get("GSPMD_LOCAL_DEVICES", "4")))
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import json  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402  (import-time hook connects ranks)
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402


class TPNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.col = dist.fleet.ColumnParallelLinear(
            16, 32, has_bias=True, gather_output=False)
        self.row = dist.fleet.RowParallelLinear(
            32, 4, input_is_parallel=True)

    def forward(self, x):
        return self.row(F.relu(self.col(x)))


def loss_fn(model, x, y):
    return F.cross_entropy(model(x), y)


def main():
    dist.init_parallel_env()
    assert len(jax.devices()) == 8, len(jax.devices())

    paddle.seed(11)
    net = TPNet()
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    # ZeRO-2 over dp composed with Megatron TP over mp — the compiled
    # program contains dp grad-reduce, mp allreduce and the ZeRO
    # reduce-scatter, all riding the cross-process mesh
    from paddle_tpu.distributed.fleet.sharding import apply_sharding_specs
    apply_sharding_specs(net, stage=2, axis="dp", min_size_to_shard=0)
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    dist.shard_model_state(net, mesh)
    step = dist.DistTrainStep(net, opt, loss_fn, mesh, donate=False)

    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 4, (8,))
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for _ in range(3)]
    assert losses[-1] < losses[0], losses
    print("GSPMD_LOSSES", json.dumps(losses), flush=True)

    # second run: per-process LOCAL batch shards (DistributedBatchSampler
    # semantics) assembled into the global batch via local_batch=True —
    # must reproduce the same losses as the replicated-loader run
    paddle.seed(11)
    net2 = TPNet()
    opt2 = paddle.optimizer.AdamW(learning_rate=0.05,
                                  parameters=net2.parameters())
    apply_sharding_specs(net2, stage=2, axis="dp", min_size_to_shard=0)
    dist.shard_model_state(net2, mesh)
    step2 = dist.DistTrainStep(net2, opt2, loss_fn, mesh, donate=False,
                               local_batch=True)
    nproc = jax.process_count()
    rows = x.shape[0] // nproc
    lo = jax.process_index() * rows
    xl, yl = x[lo:lo + rows], y[lo:lo + rows]
    losses_l = [float(step2(paddle.to_tensor(xl), paddle.to_tensor(yl)))
                for _ in range(3)]
    print("GSPMD_LOSSES_LOCAL", json.dumps(losses_l), flush=True)


if __name__ == "__main__":
    main()
