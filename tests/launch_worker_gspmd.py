"""Multi-controller compiled-collective proof worker (VERDICT r3 #2).

Run two ways with IDENTICAL seeds/data so losses must match:
- single process, 8 local CPU devices (GSPMD_LOCAL_DEVICES=8, no launch)
- 2 processes × 4 CPU devices under ``python -m
  paddle_tpu.distributed.launch --nproc_per_node 2`` — ONE shared
  8-device mesh, jax.distributed rendezvous, GSPMD collectives compiled
  ACROSS the process boundary (gloo CPU data plane).

This is the JAX analogue of the reference's multi-process-on-localhost
harness (test/legacy_test/test_parallel_dygraph_dataparallel.py:157) and
the shape that matches a v5p pod's one-process-per-host reality.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ.get("GSPMD_LOCAL_DEVICES", "4")))
except AttributeError:  # jax < 0.5: pre-init XLA flag spelling
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ.get("GSPMD_LOCAL_DEVICES", "4")).strip()
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import json  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402  (import-time hook connects ranks)
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402


class TPNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.col = dist.fleet.ColumnParallelLinear(
            16, 32, has_bias=True, gather_output=False)
        self.row = dist.fleet.RowParallelLinear(
            32, 4, input_is_parallel=True)

    def forward(self, x):
        return self.row(F.relu(self.col(x)))


def loss_fn(model, x, y):
    return F.cross_entropy(model(x), y)


def main():
    dist.init_parallel_env()
    assert len(jax.devices()) == 8, len(jax.devices())

    paddle.seed(11)
    net = TPNet()
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    # ZeRO-2 over dp composed with Megatron TP over mp — the compiled
    # program contains dp grad-reduce, mp allreduce and the ZeRO
    # reduce-scatter, all riding the cross-process mesh
    from paddle_tpu.distributed.fleet.sharding import apply_sharding_specs
    apply_sharding_specs(net, stage=2, axis="dp", min_size_to_shard=0)
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    dist.shard_model_state(net, mesh)
    step = dist.DistTrainStep(net, opt, loss_fn, mesh, donate=False)

    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 4, (8,))
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for _ in range(3)]
    assert losses[-1] < losses[0], losses
    print("GSPMD_LOSSES", json.dumps(losses), flush=True)

    # second run: per-process LOCAL batch shards (DistributedBatchSampler
    # semantics) assembled into the global batch via local_batch=True —
    # must reproduce the same losses as the replicated-loader run
    paddle.seed(11)
    net2 = TPNet()
    opt2 = paddle.optimizer.AdamW(learning_rate=0.05,
                                  parameters=net2.parameters())
    apply_sharding_specs(net2, stage=2, axis="dp", min_size_to_shard=0)
    dist.shard_model_state(net2, mesh)
    step2 = dist.DistTrainStep(net2, opt2, loss_fn, mesh, donate=False,
                               local_batch=True)
    nproc = jax.process_count()
    rows = x.shape[0] // nproc
    lo = jax.process_index() * rows
    xl, yl = x[lo:lo + rows], y[lo:lo + rows]
    losses_l = [float(step2(paddle.to_tensor(xl), paddle.to_tensor(yl)))
                for _ in range(3)]
    print("GSPMD_LOSSES_LOCAL", json.dumps(losses_l), flush=True)

    ck = os.environ.get("GSPMD_CKPT_DIR")
    if ck:
        _checkpoint_phase(net, opt, step, x, y, ck)


def _opt_state_tensors(opt):
    """Optimizer slots as checkpoint entries via the public
    state_dict(); returns (tensors, writeback) where writeback() hands
    the (restored-in-place) wrappers back through set_state_dict."""
    from paddle_tpu.core.tensor import Tensor
    sd = opt.state_dict()
    tensors = {f"__opt__/{k}": v for k, v in sd.items()
               if isinstance(v, Tensor)}

    def writeback(gstep):
        full = {k.split("/", 1)[1]: v for k, v in tensors.items()}
        full["global_step"] = gstep
        opt.set_state_dict(full)

    return tensors, writeback


def _checkpoint_phase(net, opt, step, x, y, ck):
    """VERDICT r4 #4: orbax save/load ACROSS the multi-controller
    process boundary. Save (collective), train 2 more steps, reload the
    snapshot, replay the same 2 steps — losses must match bit-exactly.
    The snapshot carries params AND optimizer moments + global step."""
    snap = os.path.join(ck, "snap")
    state = dict(net.state_dict())
    opt_ts, _ = _opt_state_tensors(opt)
    state.update(opt_ts)
    gstep = opt._global_step
    dist.save_state_dict(state, snap)
    post = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
            for _ in range(2)]
    print("GSPMD_CKPT_POST", json.dumps(post), flush=True)

    targets = dict(net.state_dict())
    opt_ts2, writeback = _opt_state_tensors(opt)
    targets.update(opt_ts2)
    dist.load_state_dict(targets, snap)
    writeback(gstep)
    replay = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
              for _ in range(2)]
    print("GSPMD_CKPT_REPLAY", json.dumps(replay), flush=True)


def crosstopo_load():
    """Cross-topology load (VERDICT r4 #4): a checkpoint written by the
    2-proc [dp=2, mp=4] run restores into a single-process model on a
    [dp=1, mp=8] mesh; two further train steps must track the 2-proc
    run's post-save losses (collective order may differ → fp tolerance
    checked host-side)."""
    dist.init_parallel_env()
    snap = os.path.join(os.environ["GSPMD_LOAD_DIR"], "snap")
    paddle.seed(11)
    net = TPNet()
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    from paddle_tpu.distributed.fleet.sharding import apply_sharding_specs
    apply_sharding_specs(net, stage=2, axis="dp", min_size_to_shard=0)
    mesh = dist.ProcessMesh(shape=[1, 8], dim_names=["dp", "mp"])
    dist.shard_model_state(net, mesh)
    step = dist.DistTrainStep(net, opt, loss_fn, mesh, donate=False)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 4, (8,))
    # build the jitted step + optimizer accumulators, then restore the
    # snapshot over them (3 throwaway steps mirror the saver's history)
    for _ in range(3):
        step(paddle.to_tensor(x), paddle.to_tensor(y))
    targets = dict(net.state_dict())
    opt_ts, writeback = _opt_state_tensors(opt)
    targets.update(opt_ts)
    dist.load_state_dict(targets, snap)
    writeback(3)
    post = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
            for _ in range(2)]
    print("GSPMD_CROSSTOPO_POST", json.dumps(post), flush=True)


if __name__ == "__main__":
    if os.environ.get("GSPMD_LOAD_DIR"):
        crosstopo_load()
    else:
        main()
