"""Custom C++ op extension tests (reference: test/custom_op/ — builds a
real shared library with the system toolchain and runs it as an op;
VERDICT item 22)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

SRC = r"""
#include <cstdint>

// PD_OP: square_plus_one 1
extern "C" void square_plus_one(const float* x, float* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) out[i] = x[i] * x[i] + 1.0f;
}

// backward: d/dx (x^2+1) * cot = 2x * cot
extern "C" void square_plus_one_grad(const float* x, const float* cot,
                                     float* out, int64_t n) {
    for (int64_t i = 0; i < n; ++i) out[i] = 2.0f * x[i] * cot[i];
}

// PD_OP: pair_max 2
extern "C" void pair_max(const float* a, const float* b, float* out,
                         int64_t n) {
    for (int64_t i = 0; i < n; ++i) out[i] = a[i] > b[i] ? a[i] : b[i];
}
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "custom_ops.cc"
    src.write_text(SRC)
    return cpp_extension.load("custom_ops", [str(src)],
                              build_directory=str(d))


class TestCppExtension:
    def test_forward(self, ext):
        x = np.linspace(-2, 2, 7).astype(np.float32)
        out = ext.square_plus_one(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out._value), x * x + 1,
                                   rtol=1e-6)

    def test_binary_op(self, ext):
        a = np.array([1.0, 5.0, -2.0], np.float32)
        b = np.array([3.0, 2.0, -1.0], np.float32)
        out = ext.pair_max(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(np.asarray(out._value), np.maximum(a, b))

    def test_backward_through_custom_op(self, ext):
        x = paddle.to_tensor(np.array([1.0, -3.0, 0.5], np.float32))
        x.stop_gradient = False
        y = ext.square_plus_one(x)
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad._value),
                                   2 * np.array([1.0, -3.0, 0.5]),
                                   rtol=1e-6)

    def test_works_under_jit(self, ext):
        import jax
        from paddle_tpu.core.tensor import Tensor

        def f(arr):
            return ext.square_plus_one(Tensor(arr))._value

        x = np.linspace(0, 1, 8).astype(np.float32)
        out = jax.jit(f)(x)
        np.testing.assert_allclose(np.asarray(out), x * x + 1, rtol=1e-6)

    def test_build_cache_reuses_so(self, ext, tmp_path):
        src = tmp_path / "again.cc"
        src.write_text(SRC)
        e2 = cpp_extension.load("custom_ops", [str(src)],
                                build_directory=str(tmp_path))
        out = e2.square_plus_one(paddle.to_tensor([2.0]))
        np.testing.assert_allclose(np.asarray(out._value), [5.0])

    def test_setup_api(self, tmp_path):
        src = tmp_path / "s.cc"
        src.write_text(SRC)
        ext = cpp_extension.setup(
            name="s", ext_modules=cpp_extension.CppExtension(
                sources=[str(src)]))
        assert hasattr(ext, "square_plus_one")
