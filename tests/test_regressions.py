"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor


class TestAutogradMultiOutputRoots:
    def test_qr_both_outputs_backward(self):
        # ADVICE #1: backward over two outputs of one multi-output op must
        # not double-count producer in-degrees
        x = paddle.to_tensor(np.random.randn(4, 4).astype("float32"),
                             stop_gradient=False)
        y = x * 2.0  # producer node upstream of qr
        q, r = paddle.qr(y)
        loss = (q.sum() + r.sum())
        loss.backward()
        assert x.grad is not None
        assert np.isfinite(np.asarray(x.grad._value)).all()

    def test_grad_two_outputs(self):
        x = paddle.to_tensor(np.random.randn(3, 3).astype("float32"),
                             stop_gradient=False)
        y = x + 1.0
        q, r = paddle.qr(y)
        ones_q = paddle.to_tensor(np.ones(q.shape, "float32"))
        ones_r = paddle.to_tensor(np.ones(r.shape, "float32"))
        gs = paddle.grad([q, r], [x], grad_outputs=[ones_q, ones_r],
                         allow_unused=False)
        assert gs[0] is not None
        assert np.isfinite(np.asarray(gs[0]._value)).all()

    def test_same_tensor_twice_as_root(self):
        x = paddle.to_tensor(np.array([2.0], "float32"), stop_gradient=False)
        y = x * 3.0
        z = y.sum()
        z2 = (y * 1.0).sum()
        paddle.autograd.backward([z, z2])
        np.testing.assert_allclose(np.asarray(x.grad._value), [6.0])


class TestGradScalerUnscaleOnce:
    def test_unscale_then_step_no_double_divide(self):
        # ADVICE #2: scaler.unscale_(opt); clip; scaler.step(opt) must
        # divide gradients by the scale exactly once
        p = paddle.to_tensor(np.zeros(3, "float32"), stop_gradient=False)
        from paddle_tpu.core.tensor import Parameter
        param = Parameter(p._value)
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[param])
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)

        loss = (param * 3.0).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.unscale_(opt)
        g_after_unscale = np.asarray(param.grad._value).copy()
        np.testing.assert_allclose(g_after_unscale, [3.0, 3.0, 3.0])
        scaler.step(opt)  # must NOT unscale again
        scaler.update()
        # sgd with lr=1: p = 0 - 3
        np.testing.assert_allclose(np.asarray(param._value), [-3.0] * 3)

    def test_step_without_unscale_still_unscales(self):
        from paddle_tpu.core.tensor import Parameter
        param = Parameter(np.zeros(2, "float32"))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[param])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        loss = (param * 2.0).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(np.asarray(param._value), [-2.0] * 2)

    def test_two_cycles_state_resets(self):
        from paddle_tpu.core.tensor import Parameter
        param = Parameter(np.zeros(2, "float32"))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[param])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        for i in range(2):
            opt.clear_grad()
            loss = (param * 1.0).sum()
            scaler.scale(loss).backward()
            scaler.unscale_(opt)
            scaler.step(opt)
            scaler.update()
        np.testing.assert_allclose(np.asarray(param._value), [-2.0] * 2)


class TestSplitRemainder:
    def test_non_divisible_split_raises(self):
        # ADVICE #3: split(5, 2) must raise, not silently drop the tail
        x = paddle.to_tensor(np.arange(5, dtype="float32"))
        with pytest.raises(ValueError):
            paddle.split(x, 2)

    def test_divisible_split_ok(self):
        x = paddle.to_tensor(np.arange(6, dtype="float32"))
        a, b = paddle.split(x, 2)
        np.testing.assert_allclose(np.asarray(a._value), [0, 1, 2])


class TestAttentionDropout:
    def test_dropout_applied_in_training(self):
        # ADVICE #4: dropout_p must actually change the output
        q = paddle.to_tensor(np.random.randn(2, 8, 4, 16).astype("float32"))
        out0 = F.scaled_dot_product_attention(q, q, q, dropout_p=0.0)
        out9 = F.scaled_dot_product_attention(q, q, q, dropout_p=0.9,
                                              training=True)
        assert not np.allclose(np.asarray(out0._value),
                               np.asarray(out9._value))

    def test_dropout_off_in_eval(self):
        q = paddle.to_tensor(np.random.randn(2, 8, 4, 16).astype("float32"))
        out0 = F.scaled_dot_product_attention(q, q, q, dropout_p=0.0)
        oute = F.scaled_dot_product_attention(q, q, q, dropout_p=0.9,
                                              training=False)
        np.testing.assert_allclose(np.asarray(out0._value),
                                   np.asarray(oute._value), rtol=1e-6)


class TestAdamWDecayMaskCache:
    def test_changed_grad_subset_same_shapes(self):
        # ADVICE #5: two same-shape params, alternate which one has a grad;
        # decay must follow the active subset, not a stale trace
        from paddle_tpu.core.tensor import Parameter
        a = Parameter(np.ones(4, "float32"))
        b = Parameter(np.ones(4, "float32"))
        a.name, b.name = "w_decay", "b_nodecay"
        # decay is lr-scaled, so use lr>0 with zero grads to isolate it
        opt2 = paddle.optimizer.AdamW(
            learning_rate=0.1, parameters=[a, b], weight_decay=0.5,
            apply_decay_param_fun=lambda n: n == "w_decay")
        # step 1: only `a` has a grad (zero grad → pure decay effect)
        a.grad = Tensor(np.zeros(4, "float32"))
        b.grad = None
        opt2.step()
        va1 = np.asarray(a._value).copy()
        assert va1[0] < 1.0  # decayed
        # step 2: only `b` has a grad — same shapes, different subset;
        # b must NOT be decayed
        a.grad = None
        b.grad = Tensor(np.zeros(4, "float32"))
        opt2.step()
        vb = np.asarray(b._value)
        np.testing.assert_allclose(vb, np.ones(4), rtol=1e-6)

    def test_callable_weight_decay_schedule_not_stale(self):
        # callable weight_decay must be re-evaluated each step, not baked
        # into the first trace (and must not retrace per step)
        from paddle_tpu.core.tensor import Parameter
        coeffs = [0.5, 0.25]
        it = {"i": 0}
        param = Parameter(np.ones(4, "float32"))
        opt = paddle.optimizer.Momentum(
            learning_rate=1.0, momentum=0.0, parameters=[param],
            weight_decay=lambda: coeffs[it["i"]])
        param.grad = Tensor(np.zeros(4, "float32"))
        opt.step()  # g + 0.5*p = 0.5 -> p = 1 - 0.5 = 0.5
        np.testing.assert_allclose(np.asarray(param._value), [0.5] * 4)
        it["i"] = 1
        param.grad = Tensor(np.zeros(4, "float32"))
        opt.step()  # g + 0.25*0.5 = 0.125 -> p = 0.5 - 0.125 = 0.375
        np.testing.assert_allclose(np.asarray(param._value), [0.375] * 4)
        assert len(opt._update_fns) == 1  # one trace for both coeffs

    def test_adamw_scheduled_decay_single_trace(self):
        from paddle_tpu.core.tensor import Parameter
        vals = iter([0.5, 0.25, 0.125])
        param = Parameter(np.ones(4, "float32"))
        opt = paddle.optimizer.AdamW(
            learning_rate=0.1, parameters=[param],
            weight_decay=lambda: next(vals))
        for _ in range(3):
            param.grad = Tensor(np.zeros(4, "float32"))
            opt.step()
        assert len(opt._update_fns) == 1


class TestGradScalerMultiOptimizer:
    def test_two_optimizers_one_scaler(self):
        # step(opt1) must not clear opt2's unscaled state mid-iteration
        from paddle_tpu.core.tensor import Parameter
        p1 = Parameter(np.zeros(2, "float32"))
        p2 = Parameter(np.zeros(2, "float32"))
        o1 = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p1])
        o2 = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p2])
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        loss = (p1 * 3.0).sum() + (p2 * 5.0).sum()
        scaler.scale(loss).backward()
        scaler.unscale_(o1)
        scaler.unscale_(o2)
        scaler.step(o1)
        scaler.step(o2)
        scaler.update()
        np.testing.assert_allclose(np.asarray(p1._value), [-3.0] * 2)
        np.testing.assert_allclose(np.asarray(p2._value), [-5.0] * 2)

    def test_scale_update_bookkeeping_once_per_iteration(self):
        from paddle_tpu.core.tensor import Parameter
        param = Parameter(np.zeros(2, "float32"))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[param])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0,
                                       incr_every_n_steps=2)
        for _ in range(2):
            opt.clear_grad()
            scaler.scale((param * 1.0).sum()).backward()
            scaler.step(opt)
            scaler.update()
        # exactly 2 good steps -> one doubling
        assert scaler._scale == 8.0


class TestHigherOrderGrad:
    """create_graph=True (reference prim/composite higher-order autodiff;
    VERDICT item 23 — previously raised NotImplementedError)."""

    def test_triple_backward_scalar(self):
        x = paddle.to_tensor(np.float32(2.0))
        x.stop_gradient = False
        y = x * x * x
        g1, = paddle.grad(y, x, create_graph=True)
        g2, = paddle.grad(g1, x, create_graph=True)
        g3, = paddle.grad(g2, x)
        assert abs(float(g1) - 12) < 1e-5
        assert abs(float(g2) - 12) < 1e-5
        assert abs(float(g3) - 6) < 1e-5

    def test_grad_penalty_into_weights(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        net = nn.Linear(4, 1)
        xb = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        xb.stop_gradient = False
        out = net(xb).sum()
        gx, = paddle.grad(out, xb, create_graph=True)
        ((gx ** 2).sum()).backward()
        assert net.weight.grad is not None
        # d/dw sum((dout/dx)^2) = d/dw sum(w^2 broadcast) = 2*w*batch
        want = 2 * np.asarray(net.weight._value) * 8
        np.testing.assert_allclose(np.asarray(net.weight.grad._value),
                                   want, rtol=1e-4)

    def test_mixed_ops_second_derivative(self):
        x = paddle.to_tensor(np.linspace(0.2, 1.0, 5).astype(np.float32))
        x.stop_gradient = False
        y = (paddle.sin(x) * paddle.exp(x)).sum()
        g1, = paddle.grad(y, x, create_graph=True)
        g2, = paddle.grad(g1.sum(), x)
        # d2/dx2 sin(x)e^x = 2cos(x)e^x
        want = 2 * np.cos(np.linspace(0.2, 1.0, 5)) * np.exp(
            np.linspace(0.2, 1.0, 5))
        np.testing.assert_allclose(np.asarray(g2._value), want, rtol=1e-4)


def test_multi_precision_master_does_not_alias_fp32_param():
    """multi_precision with fp32 params must COPY the master weight —
    astype(fp32) on fp32 is a no-op returning the same buffer, and an
    aliased master breaks donation in compiled train steps."""
    import numpy as np
    import paddle_tpu as paddle
    w = paddle.to_tensor(np.ones(4, "float32"), stop_gradient=False)
    opt = paddle.optimizer.AdamW(1e-3, parameters=[w], multi_precision=True)
    loss = (w * w).sum()
    loss.backward()
    opt.step()
    master = opt._accumulators["master_weight"][0]
    assert master.unsafe_buffer_pointer() != \
        w._value.unsafe_buffer_pointer()


class TestMemoryStatsAndOom:
    """Allocator-facade stats + OOM diagnostics (SURVEY item 1 depth)."""

    def test_memory_stats_accounts_live_arrays(self):
        import paddle_tpu.device as D
        st0 = D.memory_stats()
        big = paddle.ones([256, 1024])          # 1 MiB
        st1 = D.memory_stats()
        assert st1["bytes_in_use"] >= st0["bytes_in_use"] + 1_000_000
        assert st1["num_live_arrays"] > 0
        assert D.max_memory_allocated() >= st1["bytes_in_use"]
        assert any(a["nbytes"] >= 1_000_000
                   for a in st1["largest_arrays"])
        del big
        D.reset_max_memory_allocated()
        assert D.max_memory_allocated() <= st1["bytes_in_use"]
        # cuda shim delegates
        assert D.cuda.memory_allocated() == D.memory_allocated()

    def test_oom_diagnostic_message(self):
        import paddle_tpu.device as D
        m = paddle.nn.Linear(8, 8)
        o = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=m.parameters())
        x = paddle.randn([2, 8])
        (m(x) ** 2).mean().backward()
        o.step()
        fake = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                            "123 bytes")
        msg = D.explain_oom(fake, model=m, optimizer=o)
        assert "RESOURCE_EXHAUSTED" in msg
        assert "model parameters" in msg
        assert "optimizer state" in msg
        assert "remedies" in msg
        # non-OOM errors pass through _wrap_oom untouched
        assert D._wrap_oom(ValueError("boom")) is False
        import pytest
        with pytest.raises(RuntimeError, match="remedies"):
            D._wrap_oom(fake, m, o)


class TestAdvisorRound4:
    """Regression tests for the round-4 advisor findings (ADVICE.md r4)."""

    def test_int8_model_refuses_scaleless_paths(self):
        # ADVICE r4 #1: a quantized model on any path without in-program
        # dequant must raise, not emit garbage
        import pytest
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             quantize_weights_int8)
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        quantize_weights_int8(m)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(1, 128, (2, 8)).astype(
                np.int32))
        with pytest.raises(RuntimeError, match="serving-only"):
            m.forward(ids)
        with pytest.raises(RuntimeError, match="KV-cache generate"):
            m.generate(ids, max_new_tokens=4, use_cache=False)
        # the cached path still works
        out = m.generate(ids, max_new_tokens=4, temperature=0.0)
        assert out.shape == [2, 12]

    def test_int8_generate_raises_on_pp_mesh(self):
        import jax
        import pytest
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             quantize_weights_int8)
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        quantize_weights_int8(m)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(1, 128, (2, 8)).astype(
                np.int32))
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))
        with sharding_ctx(mesh):
            with pytest.raises(RuntimeError, match="KV-cache generate"):
                m.generate(ids, max_new_tokens=4)

    def test_int8_predictor_refuses_pp_mesh_before_quantizing(self):
        import jax
        import jax.numpy as jnp
        import pytest
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.inference.serving import GenerationPredictor
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))
        with sharding_ctx(mesh):
            with pytest.raises(RuntimeError, match="pp=1"):
                GenerationPredictor(m, int8=True)
        # refusal happened BEFORE the float weights were destroyed
        assert m._parameters["wq"]._value.dtype == jnp.float32

    def test_p2p_expiry_only_under_cap_pressure(self):
        # ADVICE r4 #2: parked messages outlive the TTL when their
        # source is under the cap; over-cap stale entries expire loudly
        # and a later take() of an expired seq raises instead of
        # desynchronizing the stream
        import time as _time
        import pytest
        from paddle_tpu import flags
        from paddle_tpu.distributed.p2p_transport import P2PTransport

        class _KV:  # transport only registers its address at init
            def key_value_set(self, k, v):
                pass

        t = P2PTransport(rank=0, kv_client=_KV())
        try:
            old = {"cap": flags.flag("p2p_inbox_max_mb"),
                   "to": flags.flag("comm_timeout_seconds")}
            flags.set_flags({"p2p_inbox_max_mb": 1,
                             "comm_timeout_seconds": 0.01})
            stale = _time.monotonic() - 10.0
            with t._cv:
                # src 1: stale but NOT wedging its reader — must survive
                t._inbox[(1, 0)] = b"x" * 64
                t._inbox_when[(1, 0)] = stale
                t._inbox_bytes[1] = 64
                # src 2: its (simulated) reader is blocked on the cap —
                # expiry is scoped to exactly this source
                t._inbox[(2, 0)] = b"y" * 512
                t._inbox_when[(2, 0)] = stale
                t._inbox_bytes[2] = 512
                t._expire_locked(2)
                assert (1, 0) in t._inbox          # other source intact
                assert (2, 0) not in t._inbox
                assert (2, 0) in t._dropped
                assert t._inbox_bytes[2] == 0      # backlog accounting
            assert bytes(t.take(1, 0, timeout=1.0)) == b"x" * 64
            with pytest.raises(RuntimeError, match="expired"):
                t.take(2, 0, timeout=1.0)

            # a take() already parked wakes promptly via the expiry
            # notify (not after its full timeout): insert + expire under
            # ONE lock hold so the tombstone notify is the only wake-up
            import threading
            err = []

            def waiter():
                try:
                    t.take(3, 7, timeout=30.0)
                except RuntimeError as e:
                    err.append(e)

            th = threading.Thread(target=waiter)
            th.start()
            _time.sleep(0.2)                        # waiter parks
            with t._cv:
                t._inbox[(3, 7)] = b"z" * 128
                t._inbox_when[(3, 7)] = _time.monotonic() - 10.0
                t._inbox_bytes[3] = 128
                t._expire_locked(3)
            th.join(timeout=5.0)
            assert not th.is_alive() and err        # woke early, loudly
        finally:
            flags.set_flags({"p2p_inbox_max_mb": old["cap"],
                             "comm_timeout_seconds": old["to"]})
            t.close() if hasattr(t, "close") else t._srv.close()

    def test_hdfs_mv_defaults_and_exists_check(self, tmp_path):
        # ADVICE r4 #3: mv defaults test_exists=True (reference parity)
        # and pre-checks the destination in the no-overwrite case
        import stat
        import pytest
        from paddle_tpu.distributed.fleet.fs import (FSFileExistsError,
                                                     FSFileNotExistsError,
                                                     HDFSClient)
        home = tmp_path / "hadoop_home"
        (home / "bin").mkdir(parents=True)
        log = tmp_path / "argv.log"
        stub = home / "bin" / "hadoop"
        # -test -e <p> succeeds iff <p> is listed in exists.txt
        stub.write_text(f"""#!/bin/sh
echo "$@" >> {log}
prev=""; target=""
for a in "$@"; do
  if [ "$prev" = "-e" ]; then target="$a"; fi
  prev="$a"
done
case " $@ " in
  *" -test -e "*) grep -qx "$target" {tmp_path}/exists.txt && exit 0 || exit 1 ;;
esac
exit 0
""")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        (tmp_path / "exists.txt").write_text("/data/src\n/data/dst\n")
        c = HDFSClient(hadoop_home=str(home))
        with pytest.raises(FSFileNotExistsError):
            c.mv("/data/missing", "/data/other")   # default test_exists
        with pytest.raises(FSFileExistsError):
            c.mv("/data/src", "/data/dst")         # dst pre-check, no -mv
        assert not any("-mv" in ln for ln in log.read_text().splitlines())
        c.mv("/data/src", "/data/fresh")           # happy path runs -mv
        assert any("-mv /data/src /data/fresh" in ln
                   for ln in log.read_text().splitlines())
        c.mv("/data/src", "/data/dst", overwrite=True)  # rm then mv
        lines = log.read_text().splitlines()
        assert any("-rm -r -f /data/dst" in ln for ln in lines)
        # test_exists=False opts out of ALL existence round-trips
        n_tests = sum("-test" in ln for ln in lines)
        c.mv("/data/whatever", "/data/other", test_exists=False)
        lines = log.read_text().splitlines()
        assert sum("-test" in ln for ln in lines) == n_tests
        assert any("-mv /data/whatever /data/other" in ln for ln in lines)

    def test_lazy_refuses_unreprable_static_args(self):
        # ADVICE r4 #4: no id()-keyed cache entries — record() refuses,
        # dispatch flushes to eager, results stay correct
        import pytest
        from paddle_tpu.core.lazy import SegmentEngine, UncapturableArg

        class NoRepr:
            def __repr__(self):
                raise TypeError("not representable")

        eng = SegmentEngine()
        with pytest.raises(UncapturableArg):
            eng.record("fake_op", lambda x, s: x, (np.ones((2,)),
                                                   NoRepr()), {})
        assert eng.recorded_ops == 0 and not eng._nodes  # state unmutated
        with pytest.raises(UncapturableArg):
            eng.record("fake_op", lambda x, **kw: x, (np.ones((2,)),),
                       {"cfg": NoRepr()})
        assert eng.recorded_ops == 0 and not eng._nodes
