"""Round-5 op-surface gap closures (VERDICT r4 missing #2): the last
NotImplementedError stubs become real kernels, each checked against a
torch (CPU) or numpy oracle.

- nn.SpectralNorm layer (module twin of the nn.utils.spectral_norm hook;
  reference python/paddle/nn/layer/norm.py SpectralNorm)
- F.fold (inverse unfold; reference nn/functional/common.py fold)
- put_along_axis reduce modes add/mul/amin/amax (+ include_self=False)
- adaptive_max_pool{1,2,3}d with non-divisible sizes
- cumulative_trapezoid(x=...) sample points
"""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class TestFold:
    @pytest.mark.parametrize("ks,st,pd,dl", [
        ((2, 2), (2, 2), 0, 1),
        ((3, 3), (1, 1), 1, 1),          # overlapping windows: sums
        ((3, 2), (2, 1), (1, 2), (1, 1)),
        ((2, 2), (1, 1), 0, 2),          # dilation
    ])
    def test_fold_matches_torch(self, ks, st, pd, dl):
        x = np.random.RandomState(0).randn(2, 3, 10, 12).astype(np.float32)
        cols = F.unfold(paddle.to_tensor(x), list(ks), strides=list(st),
                        paddings=pd if isinstance(pd, int) else list(pd),
                        dilations=dl if isinstance(dl, int) else list(dl))
        out = F.fold(cols, output_sizes=[10, 12], kernel_sizes=list(ks),
                     strides=list(st),
                     paddings=pd if isinstance(pd, int) else list(pd),
                     dilations=dl if isinstance(dl, int) else list(dl))
        tc = torch.nn.functional.unfold(
            torch.from_numpy(x), ks, dilation=dl,
            padding=pd if isinstance(pd, int) else tuple(pd), stride=st)
        tf = torch.nn.functional.fold(
            tc, (10, 12), ks, dilation=dl,
            padding=pd if isinstance(pd, int) else tuple(pd), stride=st)
        np.testing.assert_allclose(_np(out), tf.numpy(), rtol=1e-5,
                                   atol=1e-5)

    def test_fold_grad(self):
        cols = paddle.to_tensor(
            np.random.RandomState(1).randn(1, 4, 9).astype(np.float32),
            stop_gradient=False)
        out = F.fold(cols, output_sizes=[4, 4], kernel_sizes=[2, 2],
                     strides=1)
        out.sum().backward()
        # fold's adjoint is unfold of ones: every column element maps to
        # exactly one image position, so the grad is all-ones
        np.testing.assert_allclose(_np(cols.grad), 1.0)

    def test_fold_column_mismatch_raises(self):
        cols = paddle.to_tensor(np.zeros((1, 4, 5), np.float32))
        with pytest.raises(ValueError, match="columns"):
            F.fold(cols, output_sizes=[4, 4], kernel_sizes=[2, 2],
                   strides=1)


class TestPutAlongAxisReduce:
    def _oracle(self, reduce, include_self):
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        idx = np.random.RandomState(1).randint(0, 6, (4, 3))
        val = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        tred = {"add": "sum", "mul": "prod", "multiply": "prod",
                "amin": "amin", "amax": "amax"}[reduce]
        want = torch.from_numpy(x.copy()).scatter_reduce(
            1, torch.from_numpy(idx), torch.from_numpy(val), tred,
            include_self=include_self).numpy()
        got = paddle.put_along_axis(
            paddle.to_tensor(x), paddle.to_tensor(idx),
            paddle.to_tensor(val), axis=1, reduce=reduce,
            include_self=include_self)
        np.testing.assert_allclose(_np(got), want, rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("reduce", ["add", "mul", "amin", "amax"])
    def test_reduce_include_self(self, reduce):
        self._oracle(reduce, True)

    @pytest.mark.parametrize("reduce", ["add", "mul", "amin", "amax"])
    def test_reduce_exclude_self(self, reduce):
        self._oracle(reduce, False)

    def test_broadcast_indices(self):
        # reference infer_broadcast_shape: indices broadcast against arr
        # on non-axis dims ([[0]] writes the whole row 0)
        x = paddle.to_tensor(np.array([[10., 30., 20.],
                                       [60., 40., 50.]], np.float32))
        out = paddle.put_along_axis(x, paddle.to_tensor([[0]]), 99.0,
                                    axis=0)
        np.testing.assert_allclose(
            _np(out), [[99., 99., 99.], [60., 40., 50.]])

    def test_add_keeps_working_for_complex(self):
        # identities are computed lazily: iinfo (integer-only) must not
        # run for dtypes that only use add/mul (bool is rejected by jax
        # scatter-add and absent from the reference dtype list too)
        xc = (np.random.RandomState(0).randn(3, 4)
              + 1j * np.random.RandomState(1).randn(3, 4)).astype(
                  np.complex64)
        idx = np.array([[0, 1], [2, 3], [1, 0]])
        out = paddle.put_along_axis(
            paddle.to_tensor(xc), paddle.to_tensor(idx),
            paddle.to_tensor(np.ones((3, 2), np.complex64)), axis=1,
            reduce="add")
        want = xc.copy()
        np.add.at(want, (np.arange(3)[:, None], idx), 1.0)
        np.testing.assert_allclose(_np(out), want, rtol=1e-6)

    def test_unknown_reduce_raises(self):
        x = paddle.to_tensor(np.zeros((2, 3), np.float32))
        with pytest.raises(ValueError, match="unsupported reduce"):
            paddle.put_along_axis(x, paddle.to_tensor([[0]]), 1.0,
                                  axis=0, reduce="mean")


class TestAdaptiveMaxPoolNonDivisible:
    def test_2d_matches_torch(self):
        x = np.random.RandomState(0).randn(2, 3, 7, 11).astype(np.float32)
        for osize in [(3, 5), (2, 4), (5, 3), (7, 11), (1, 1)]:
            got = F.adaptive_max_pool2d(paddle.to_tensor(x), list(osize))
            want = torch.nn.functional.adaptive_max_pool2d(
                torch.from_numpy(x), osize).numpy()
            np.testing.assert_allclose(_np(got), want, rtol=1e-6)

    def test_1d_and_3d(self):
        x1 = np.random.RandomState(1).randn(2, 3, 10).astype(np.float32)
        got = F.adaptive_max_pool1d(paddle.to_tensor(x1), 4)
        want = torch.nn.functional.adaptive_max_pool1d(
            torch.from_numpy(x1), 4).numpy()
        np.testing.assert_allclose(_np(got), want, rtol=1e-6)
        x3 = np.random.RandomState(2).randn(1, 2, 5, 6, 7).astype(
            np.float32)
        got = F.adaptive_max_pool3d(paddle.to_tensor(x3), [2, 4, 3])
        want = torch.nn.functional.adaptive_max_pool3d(
            torch.from_numpy(x3), (2, 4, 3)).numpy()
        np.testing.assert_allclose(_np(got), want, rtol=1e-6)


class TestCumulativeTrapezoidX:
    def test_x_1d(self):
        y = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        x = np.sort(np.random.RandomState(1).rand(8)).astype(np.float32)
        got = paddle.cumulative_trapezoid(paddle.to_tensor(y),
                                          x=paddle.to_tensor(x))
        want = torch.cumulative_trapezoid(torch.from_numpy(y),
                                          x=torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-6)

    def test_x_full_shape_and_axis(self):
        y = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        x = np.cumsum(np.random.RandomState(1).rand(4, 5), axis=0).astype(
            np.float32)
        got = paddle.cumulative_trapezoid(paddle.to_tensor(y),
                                          x=paddle.to_tensor(x), axis=0)
        want = torch.cumulative_trapezoid(torch.from_numpy(y),
                                          x=torch.from_numpy(x),
                                          dim=0).numpy()
        np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-6)

    def test_x_and_dx_conflict(self):
        y = paddle.to_tensor(np.zeros((3,), np.float32))
        with pytest.raises(ValueError, match="not both"):
            paddle.cumulative_trapezoid(y, x=y, dx=0.5)


class TestSpectralNormLayer:
    def test_normalizes_largest_singular_value(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        w = np.random.RandomState(0).randn(6, 4).astype(np.float32) * 3.0
        sn = nn.SpectralNorm(w.shape, dim=0, power_iters=30)
        out = sn(paddle.to_tensor(w))
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(
            np.linalg.svd(_np(out), compute_uv=False)[0], 1.0, rtol=1e-4)
        np.testing.assert_allclose(_np(out), w / sigma, rtol=1e-3,
                                   atol=1e-4)

    def test_conv_weight_dim1_and_state_advances(self):
        import paddle_tpu.nn as nn
        paddle.seed(1)
        w = np.random.RandomState(1).randn(4, 8, 3, 3).astype(np.float32)
        sn = nn.SpectralNorm(w.shape, dim=1, power_iters=1)
        u0 = _np(sn.weight_u).copy()
        out1 = sn(paddle.to_tensor(w))
        u1 = _np(sn.weight_u).copy()
        assert not np.allclose(u0, u1)          # persistent u advanced
        assert out1.shape == list(w.shape)
        # repeated application converges to sigma-normalized weight
        for _ in range(30):
            sn(paddle.to_tensor(w))
        out = sn(paddle.to_tensor(w))
        mat = np.moveaxis(w, 1, 0).reshape(8, -1)
        sigma = np.linalg.svd(mat, compute_uv=False)[0]
        np.testing.assert_allclose(_np(out), w / sigma, rtol=1e-3,
                                   atol=1e-4)

    def test_gradient_flows_through_sigma(self):
        import paddle_tpu.nn as nn
        paddle.seed(2)
        w = paddle.to_tensor(
            np.random.RandomState(2).randn(5, 3).astype(np.float32),
            stop_gradient=False)
        sn = nn.SpectralNorm([5, 3], dim=0, power_iters=2)
        # converge u/v first: the tape treats them as constants (same
        # rule as the reference), which only matches finite differences
        # at the power-iteration fixed point where dsigma/du = 0
        from paddle_tpu.core import autograd
        with autograd.no_grad():
            for _ in range(60):
                sn(paddle.to_tensor(_np(w)))
        sn(w).sum().backward()
        g = _np(w.grad)
        assert np.isfinite(g).all() and (g != 0).any()
        # finite-difference check through the FROZEN u/v (power iteration
        # uses stop_gradient'd values, so freeze state for the oracle)
        import copy
        eps = 1e-3
        w0 = _np(w).copy()

        def f(arr):
            sn2 = copy.deepcopy(sn)
            return float(sn2(paddle.to_tensor(arr)).sum()._value)

        i, j = 2, 1
        wp, wm = w0.copy(), w0.copy()
        wp[i, j] += eps
        wm[i, j] -= eps
        fd = (f(wp) - f(wm)) / (2 * eps)
        np.testing.assert_allclose(g[i, j], fd, rtol=5e-2, atol=1e-3)

    def test_shape_mismatch_and_bad_power_iters(self):
        import paddle_tpu.nn as nn
        sn = nn.SpectralNorm([4, 4], dim=0, power_iters=1)
        with pytest.raises(ValueError, match="shape"):
            sn(paddle.to_tensor(np.zeros((3, 3), np.float32)))
        with pytest.raises(ValueError, match="power_iters"):
            nn.SpectralNorm([4, 4], power_iters=0)
