"""Multi-controller kill-and-relaunch worker (VERDICT r4 #4): the
elastic crash-resume contract exercised ACROSS the 2-process GSPMD
boundary — one rank dies hard mid-run, the launcher kills the pod
(rc=101), a relaunch resumes BOTH ranks from the last advertised orbax
snapshot and training continues with bit-exact loss parity against an
uninterrupted run.

Usage (under ``python -m paddle_tpu.distributed.launch
--nproc_per_node 2``): argv = <workdir> <crash_at_step|-1>.
Trains 10 steps of a dp×mp DistTrainStep; AutoCheckpoint every 2 steps
(synchronously joined — a background orbax collective must not
interleave with the train step's); rank 1 os._exit(101)s at the crash
step. Prints RESUMED_AT <n> and LOSSES <json of (step, loss)>.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # jax < 0.5: pre-init XLA flag spelling
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import json  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu.distributed.checkpoint import AutoCheckpoint  # noqa: E402
from paddle_tpu.distributed.fleet.elastic import FileKVStore  # noqa: E402


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.col = dist.fleet.ColumnParallelLinear(
            16, 32, has_bias=True, gather_output=False)
        self.row = dist.fleet.RowParallelLinear(
            32, 4, input_is_parallel=True)

    def forward(self, x):
        return self.row(F.relu(self.col(x)))


def loss_fn(model, x, y):
    return F.cross_entropy(model(x), y)


def main():
    workdir, crash_at = sys.argv[1], int(sys.argv[2])
    dist.init_parallel_env()
    assert len(jax.devices()) == 8, len(jax.devices())

    paddle.seed(3)
    net = Net()
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    dist.shard_model_state(net, mesh)
    step_fn = dist.DistTrainStep(net, opt, loss_fn, mesh, donate=False)

    auto = AutoCheckpoint("gspmd", net, optimizer=opt,
                          save_dir=f"{workdir}/ckpt",
                          store=FileKVStore(f"{workdir}/store"),
                          every_n_steps=2)
    start = auto.resume()
    print(f"RESUMED_AT {start}", flush=True)

    rng = np.random.RandomState(5)
    xs = rng.randn(10, 8, 16).astype(np.float32)
    ys = rng.randint(0, 4, (10, 8))

    losses = []
    for step in range(start + 1, 11):
        loss = float(step_fn(paddle.to_tensor(xs[step - 1]),
                             paddle.to_tensor(ys[step - 1])))
        losses.append((step, loss))
        h = auto.step(step)
        if h is not None:
            auto.wait()        # join before the next step's collectives
        if step == crash_at and jax.process_index() == 1:
            os._exit(101)      # rank 1 dies hard; launcher reaps rank 0
    print("LOSSES", json.dumps(losses), flush=True)


if __name__ == "__main__":
    main()
