"""nn.Layer / functional tests (reference test/legacy_test nn coverage)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class TestLayerBase:
    def test_parameters_registration(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert "fc1.weight" in names and "fc2.bias" in names
        assert len(net.parameters()) == 4
        out = net(paddle.randn([3, 4]))
        assert out.shape == [3, 2]

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        sd = net.state_dict()
        net2 = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        net2.set_state_dict(sd)
        x = paddle.randn([2, 4])
        assert np.allclose(_np(net(x)), _np(net2(x)))

    def test_train_eval_mode(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([100])
        d.eval()
        assert np.allclose(_np(d(x)), 1.0)
        d.train()
        assert not np.allclose(_np(d(x)), 1.0)

    def test_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(lambda l, i, o: calls.append(1))
        net(paddle.ones([1, 2]))
        assert calls
        h.remove()
        net(paddle.ones([1, 2]))
        assert len(calls) == 1

    def test_sublayers_apply(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(net.sublayers()) == 3  # linear, seq, inner linear


class TestLayers:
    def test_conv2d_shape_and_value(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = paddle.randn([2, 3, 16, 16])
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]

    def test_conv2d_matches_manual(self):
        import jax
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = np.random.randn(1, 1, 3, 3).astype(np.float32)
        w = _np(conv.weight)
        out = _np(conv(paddle.to_tensor(x)))
        ref = np.zeros((1, 1, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                ref[0, 0, i, j] = (x[0, 0, i:i + 2, j:j + 2] * w[0, 0]).sum()
        assert np.allclose(out, ref, atol=1e-5)

    def test_conv_groups_dilation(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, dilation=2, padding=2)
        out = conv(paddle.randn([1, 4, 8, 8]))
        assert out.shape == [1, 8, 8, 8]

    def test_conv_transpose(self):
        deconv = nn.Conv2DTranspose(4, 2, 2, stride=2)
        out = deconv(paddle.randn([1, 4, 5, 5]))
        assert out.shape == [1, 2, 10, 10]

    def test_pools(self):
        x = paddle.randn([2, 3, 8, 8])
        assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
        arr = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mp = _np(nn.MaxPool2D(2, 2)(paddle.to_tensor(arr)))
        assert np.allclose(mp[0, 0], [[5, 7], [13, 15]])

    def test_batchnorm_stats(self):
        bn = nn.BatchNorm2D(3)
        x = paddle.randn([4, 3, 5, 5]) * 2 + 1
        bn.train()
        out = bn(x)
        m = _np(out).mean(axis=(0, 2, 3))
        assert np.allclose(m, 0, atol=1e-5)
        assert not np.allclose(_np(bn._mean), 0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([2, 4, 8]) * 3 + 5
        out = _np(ln(x))
        assert np.allclose(out.mean(-1), 0, atol=1e-5)
        assert np.allclose(out.std(-1), 1, atol=1e-2)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([2, 8])
        out = _np(rn(x))
        ref = _np(x) / np.sqrt((np.asarray(_np(x)) ** 2).mean(-1, keepdims=True) + 1e-6)
        assert np.allclose(out, ref, atol=1e-5)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        out = gn(paddle.randn([2, 4, 3, 3]))
        assert out.shape == [2, 4, 3, 3]

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        assert np.allclose(_np(out)[0, 0], _np(emb.weight)[1])

    def test_lstm(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([3, 5, 4])  # [batch, time, feat]
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 8]
        assert h.shape == [2, 3, 8]

    def test_bilstm(self):
        lstm = nn.LSTM(4, 8, direction="bidirect")
        out, (h, c) = lstm(paddle.randn([3, 5, 4]))
        assert out.shape == [3, 5, 16]

    def test_gru(self):
        gru = nn.GRU(4, 8)
        out, h = gru(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 8]
        assert h.shape == [1, 2, 8]

    def test_multihead_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 6, 16])
        out = mha(x)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(paddle.randn([2, 6, 16]))
        assert out.shape == [2, 6, 16]

    def test_transformer_full(self):
        t = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32)
        out = t(paddle.randn([2, 5, 16]), paddle.randn([2, 3, 16]))
        assert out.shape == [2, 3, 16]


class TestFunctional:
    def test_softmax_crossentropy_agreement(self):
        logits = paddle.randn([4, 7])
        labels = paddle.to_tensor(np.random.randint(0, 7, (4,)))
        ce = F.cross_entropy(logits, labels)
        logp = F.log_softmax(logits, axis=-1)
        ref = -np.take_along_axis(_np(logp), _np(labels)[:, None], 1).mean()
        assert np.allclose(float(ce), ref, atol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 5])
        labels = paddle.to_tensor(np.array([0, -100, 2, -100]))
        ce = F.cross_entropy(logits, labels, ignore_index=-100)
        logp = _np(F.log_softmax(logits, axis=-1))
        ref = -(logp[0, 0] + logp[2, 2]) / 2
        assert np.allclose(float(ce), ref, atol=1e-5)

    def test_label_smoothing(self):
        logits = paddle.randn([3, 4])
        labels = paddle.to_tensor(np.array([1, 2, 0]))
        ce = F.cross_entropy(logits, labels, label_smoothing=0.1)
        assert np.isfinite(float(ce))

    def test_activations_values(self):
        x = paddle.to_tensor([-1.0, 0.0, 2.0])
        assert np.allclose(_np(F.relu(x)), [0, 0, 2])
        assert np.allclose(_np(F.relu6(x * 4)), [0, 0, 6])
        assert np.allclose(_np(F.leaky_relu(x)), [-0.01, 0, 2])
        assert np.allclose(_np(F.hardtanh(x)), [-1, 0, 1])
        sig = 1 / (1 + np.exp(-np.array([-1, 0, 2.0])))
        assert np.allclose(_np(F.sigmoid(x)), sig, atol=1e-5)
        assert np.allclose(_np(F.silu(x)), np.array([-1, 0, 2.0]) * sig, atol=1e-5)

    def test_losses(self):
        a = paddle.randn([4, 3])
        b = paddle.randn([4, 3])
        assert np.allclose(float(F.mse_loss(a, b)),
                           ((_np(a) - _np(b)) ** 2).mean(), atol=1e-5)
        assert np.allclose(float(F.l1_loss(a, b)),
                           np.abs(_np(a) - _np(b)).mean(), atol=1e-5)
        p = F.sigmoid(a)
        bce = F.binary_cross_entropy(p, F.sigmoid(b))
        assert np.isfinite(float(bce))

    def test_sdpa_matches_reference(self):
        q = paddle.randn([2, 5, 2, 4])
        k = paddle.randn([2, 5, 2, 4])
        v = paddle.randn([2, 5, 2, 4])
        out = F.scaled_dot_product_attention(q, k, v)
        qn, kn, vn = _np(q), _np(k), _np(v)
        # manual reference
        qh = np.moveaxis(qn, 2, 1)
        kh = np.moveaxis(kn, 2, 1)
        vh = np.moveaxis(vn, 2, 1)
        s = np.einsum("bhsd,bhtd->bhst", qh, kh) / 2.0
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.moveaxis(np.einsum("bhst,bhtd->bhsd", p, vh), 1, 2)
        assert np.allclose(_np(out), ref, atol=1e-4)

    def test_sdpa_causal(self):
        q = paddle.randn([1, 4, 1, 4])
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert np.isfinite(_np(out)).all()

    def test_pad(self):
        x = paddle.ones([1, 1, 2, 2])
        out = F.pad(x, [1, 1, 0, 0])  # pad W by 1 both sides
        assert out.shape == [1, 1, 2, 4]

    def test_interpolate(self):
        x = paddle.randn([1, 2, 4, 4])
        out = F.interpolate(x, scale_factor=2, mode="nearest")
        assert out.shape == [1, 2, 8, 8]
        out = F.interpolate(x, size=[2, 2], mode="bilinear")
        assert out.shape == [1, 2, 2, 2]

    def test_one_hot(self):
        out = F.one_hot(paddle.to_tensor([0, 2]), 3)
        assert np.allclose(_np(out), [[1, 0, 0], [0, 0, 1]])

    def test_linear_layout(self):
        # paddle weight layout [in, out]
        w = paddle.to_tensor(np.random.randn(3, 2).astype(np.float32))
        x = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32))
        assert np.allclose(_np(F.linear(x, w)), _np(x) @ _np(w), atol=1e-5)


class TestClip:
    def test_clip_by_global_norm(self):
        p1 = paddle.Parameter(np.ones(4, np.float32))
        p2 = paddle.Parameter(np.ones(4, np.float32))
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        p1.grad = Tensor(jnp.full((4,), 3.0))
        p2.grad = Tensor(jnp.full((4,), 4.0))
        clip = nn.ClipGradByGlobalNorm(1.0)
        clip([p1, p2])
        total = np.sqrt((_np(p1.grad) ** 2).sum() + (_np(p2.grad) ** 2).sum())
        assert np.allclose(total, 1.0, atol=1e-5)
