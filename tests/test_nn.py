"""nn.Layer / functional tests (reference test/legacy_test nn coverage)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class TestLayerBase:
    def test_parameters_registration(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert "fc1.weight" in names and "fc2.bias" in names
        assert len(net.parameters()) == 4
        out = net(paddle.randn([3, 4]))
        assert out.shape == [3, 2]

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        sd = net.state_dict()
        net2 = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        net2.set_state_dict(sd)
        x = paddle.randn([2, 4])
        assert np.allclose(_np(net(x)), _np(net2(x)))

    def test_train_eval_mode(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([100])
        d.eval()
        assert np.allclose(_np(d(x)), 1.0)
        d.train()
        assert not np.allclose(_np(d(x)), 1.0)

    def test_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(lambda l, i, o: calls.append(1))
        net(paddle.ones([1, 2]))
        assert calls
        h.remove()
        net(paddle.ones([1, 2]))
        assert len(calls) == 1

    def test_sublayers_apply(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(net.sublayers()) == 3  # linear, seq, inner linear


class TestLayers:
    def test_conv2d_shape_and_value(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = paddle.randn([2, 3, 16, 16])
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]

    def test_conv2d_matches_manual(self):
        import jax
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = np.random.randn(1, 1, 3, 3).astype(np.float32)
        w = _np(conv.weight)
        out = _np(conv(paddle.to_tensor(x)))
        ref = np.zeros((1, 1, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                ref[0, 0, i, j] = (x[0, 0, i:i + 2, j:j + 2] * w[0, 0]).sum()
        assert np.allclose(out, ref, atol=1e-5)

    def test_conv_groups_dilation(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, dilation=2, padding=2)
        out = conv(paddle.randn([1, 4, 8, 8]))
        assert out.shape == [1, 8, 8, 8]

    def test_conv_transpose(self):
        deconv = nn.Conv2DTranspose(4, 2, 2, stride=2)
        out = deconv(paddle.randn([1, 4, 5, 5]))
        assert out.shape == [1, 2, 10, 10]

    def test_pools(self):
        x = paddle.randn([2, 3, 8, 8])
        assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
        arr = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mp = _np(nn.MaxPool2D(2, 2)(paddle.to_tensor(arr)))
        assert np.allclose(mp[0, 0], [[5, 7], [13, 15]])

    def test_batchnorm_stats(self):
        bn = nn.BatchNorm2D(3)
        x = paddle.randn([4, 3, 5, 5]) * 2 + 1
        bn.train()
        out = bn(x)
        m = _np(out).mean(axis=(0, 2, 3))
        assert np.allclose(m, 0, atol=1e-5)
        assert not np.allclose(_np(bn._mean), 0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([2, 4, 8]) * 3 + 5
        out = _np(ln(x))
        assert np.allclose(out.mean(-1), 0, atol=1e-5)
        assert np.allclose(out.std(-1), 1, atol=1e-2)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([2, 8])
        out = _np(rn(x))
        ref = _np(x) / np.sqrt((np.asarray(_np(x)) ** 2).mean(-1, keepdims=True) + 1e-6)
        assert np.allclose(out, ref, atol=1e-5)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        out = gn(paddle.randn([2, 4, 3, 3]))
        assert out.shape == [2, 4, 3, 3]

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        assert np.allclose(_np(out)[0, 0], _np(emb.weight)[1])

    def test_lstm(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([3, 5, 4])  # [batch, time, feat]
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 8]
        assert h.shape == [2, 3, 8]

    def test_bilstm(self):
        lstm = nn.LSTM(4, 8, direction="bidirect")
        out, (h, c) = lstm(paddle.randn([3, 5, 4]))
        assert out.shape == [3, 5, 16]

    def test_gru(self):
        gru = nn.GRU(4, 8)
        out, h = gru(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 8]
        assert h.shape == [1, 2, 8]

    def test_multihead_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 6, 16])
        out = mha(x)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(paddle.randn([2, 6, 16]))
        assert out.shape == [2, 6, 16]

    def test_transformer_full(self):
        t = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32)
        out = t(paddle.randn([2, 5, 16]), paddle.randn([2, 3, 16]))
        assert out.shape == [2, 3, 16]


class TestFunctional:
    def test_softmax_crossentropy_agreement(self):
        logits = paddle.randn([4, 7])
        labels = paddle.to_tensor(np.random.randint(0, 7, (4,)))
        ce = F.cross_entropy(logits, labels)
        logp = F.log_softmax(logits, axis=-1)
        ref = -np.take_along_axis(_np(logp), _np(labels)[:, None], 1).mean()
        assert np.allclose(float(ce), ref, atol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 5])
        labels = paddle.to_tensor(np.array([0, -100, 2, -100]))
        ce = F.cross_entropy(logits, labels, ignore_index=-100)
        logp = _np(F.log_softmax(logits, axis=-1))
        ref = -(logp[0, 0] + logp[2, 2]) / 2
        assert np.allclose(float(ce), ref, atol=1e-5)

    def test_label_smoothing(self):
        logits = paddle.randn([3, 4])
        labels = paddle.to_tensor(np.array([1, 2, 0]))
        ce = F.cross_entropy(logits, labels, label_smoothing=0.1)
        assert np.isfinite(float(ce))

    def test_activations_values(self):
        x = paddle.to_tensor([-1.0, 0.0, 2.0])
        assert np.allclose(_np(F.relu(x)), [0, 0, 2])
        assert np.allclose(_np(F.relu6(x * 4)), [0, 0, 6])
        assert np.allclose(_np(F.leaky_relu(x)), [-0.01, 0, 2])
        assert np.allclose(_np(F.hardtanh(x)), [-1, 0, 1])
        sig = 1 / (1 + np.exp(-np.array([-1, 0, 2.0])))
        assert np.allclose(_np(F.sigmoid(x)), sig, atol=1e-5)
        assert np.allclose(_np(F.silu(x)), np.array([-1, 0, 2.0]) * sig, atol=1e-5)

    def test_losses(self):
        a = paddle.randn([4, 3])
        b = paddle.randn([4, 3])
        assert np.allclose(float(F.mse_loss(a, b)),
                           ((_np(a) - _np(b)) ** 2).mean(), atol=1e-5)
        assert np.allclose(float(F.l1_loss(a, b)),
                           np.abs(_np(a) - _np(b)).mean(), atol=1e-5)
        p = F.sigmoid(a)
        bce = F.binary_cross_entropy(p, F.sigmoid(b))
        assert np.isfinite(float(bce))

    def test_sdpa_matches_reference(self):
        q = paddle.randn([2, 5, 2, 4])
        k = paddle.randn([2, 5, 2, 4])
        v = paddle.randn([2, 5, 2, 4])
        out = F.scaled_dot_product_attention(q, k, v)
        qn, kn, vn = _np(q), _np(k), _np(v)
        # manual reference
        qh = np.moveaxis(qn, 2, 1)
        kh = np.moveaxis(kn, 2, 1)
        vh = np.moveaxis(vn, 2, 1)
        s = np.einsum("bhsd,bhtd->bhst", qh, kh) / 2.0
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.moveaxis(np.einsum("bhst,bhtd->bhsd", p, vh), 1, 2)
        assert np.allclose(_np(out), ref, atol=1e-4)

    def test_sdpa_causal(self):
        q = paddle.randn([1, 4, 1, 4])
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert np.isfinite(_np(out)).all()

    def test_pad(self):
        x = paddle.ones([1, 1, 2, 2])
        out = F.pad(x, [1, 1, 0, 0])  # pad W by 1 both sides
        assert out.shape == [1, 1, 2, 4]

    def test_interpolate(self):
        x = paddle.randn([1, 2, 4, 4])
        out = F.interpolate(x, scale_factor=2, mode="nearest")
        assert out.shape == [1, 2, 8, 8]
        out = F.interpolate(x, size=[2, 2], mode="bilinear")
        assert out.shape == [1, 2, 2, 2]

    def test_one_hot(self):
        out = F.one_hot(paddle.to_tensor([0, 2]), 3)
        assert np.allclose(_np(out), [[1, 0, 0], [0, 0, 1]])

    def test_linear_layout(self):
        # paddle weight layout [in, out]
        w = paddle.to_tensor(np.random.randn(3, 2).astype(np.float32))
        x = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32))
        assert np.allclose(_np(F.linear(x, w)), _np(x) @ _np(w), atol=1e-5)


class TestClip:
    def test_clip_by_global_norm(self):
        p1 = paddle.Parameter(np.ones(4, np.float32))
        p2 = paddle.Parameter(np.ones(4, np.float32))
        from paddle_tpu.core.tensor import Tensor
        import jax.numpy as jnp
        p1.grad = Tensor(jnp.full((4,), 3.0))
        p2.grad = Tensor(jnp.full((4,), 4.0))
        clip = nn.ClipGradByGlobalNorm(1.0)
        clip([p1, p2])
        total = np.sqrt((_np(p1.grad) ** 2).sum() + (_np(p2.grad) ** 2).sum())
        assert np.allclose(total, 1.0, atol=1e-5)


class TestExtraFunctionals:
    """Long-tail functionals (nn/functional/extra.py)."""

    def test_sequence_mask_temporal_shift(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([2, 4])), maxlen=5)
        np.testing.assert_allclose(
            _np(m), [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])
        x = paddle.to_tensor(np.random.randn(4, 8, 5, 5).astype("float32"))
        ts = F.temporal_shift(x, seg_num=2)
        assert ts.shape == [4, 8, 5, 5]
        # shifted channels: first quarter comes from t+1
        x5 = _np(x).reshape(2, 2, 8, 5, 5)
        t5 = _np(ts).reshape(2, 2, 8, 5, 5)
        np.testing.assert_allclose(t5[:, 0, :2], x5[:, 1, :2])
        np.testing.assert_allclose(t5[:, 1, :2], 0.0)

    def test_rrelu(self):
        r = F.rrelu(paddle.to_tensor(np.array([-1., 1.], "float32")),
                    training=False)
        np.testing.assert_allclose(_np(r), [-(1 / 8 + 1 / 3) / 2, 1.0],
                                   atol=1e-6)
        r2 = F.rrelu(paddle.to_tensor(np.array([-1., 1.], "float32")),
                     training=True)
        assert -1 / 3 <= float(_np(r2)[0]) <= -1 / 8

    def test_max_pool_mask_unpool_roundtrip(self):
        x = paddle.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        out, mask = F.max_pool2d(x, 2, stride=2, return_mask=True)
        assert out.shape == [2, 3, 4, 4] and mask.shape == [2, 3, 4, 4]
        un = F.max_unpool2d(out, mask, 2, stride=2)
        assert un.shape == [2, 3, 8, 8]
        unn, xn = _np(un), _np(x)
        nz = unn != 0
        np.testing.assert_allclose(unn[nz], xn[nz])
        # unpool preserves every pooled max
        np.testing.assert_allclose(np.sort(unn[nz]).ravel(),
                                   np.sort(_np(out).ravel()))

    def test_margin_and_hinge_losses(self):
        logits = paddle.to_tensor(
            (np.random.rand(4, 10) * 2 - 1).astype("float32"),
            stop_gradient=False)
        lbl = paddle.to_tensor(np.array([1, 2, 3, 4]))
        loss = F.margin_cross_entropy(logits, lbl)
        loss.backward()
        assert logits.grad is not None and np.isfinite(loss.item())
        mm = F.multi_margin_loss(
            paddle.to_tensor(np.random.randn(4, 5).astype("float32")), lbl[:4])
        assert np.isfinite(mm.item())
        a, b, c = [paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
                   for _ in range(3)]
        tl = F.triplet_margin_with_distance_loss(a, b, c)
        assert np.isfinite(tl.item())

    def test_hsigmoid_loss(self):
        x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"),
                             stop_gradient=False)
        w = paddle.to_tensor(np.random.randn(9, 16).astype("float32"),
                             stop_gradient=False)
        b = paddle.to_tensor(np.zeros(9, "float32"))
        loss = F.hsigmoid_loss(x, paddle.to_tensor(np.array([0, 3, 7, 9])),
                               10, w, b)
        assert loss.shape == [4, 1]
        loss.sum().backward()
        assert x.grad is not None and w.grad is not None

    def test_rnnt_loss_vs_bruteforce(self):
        """Forward-algorithm loss equals brute-force enumeration of every
        monotone lattice path (T blanks + U labels, last symbol the final
        blank at (T-1, U))."""
        from itertools import combinations
        T, U, V = 3, 2, 4
        rng = np.random.default_rng(0)
        logits = rng.standard_normal((1, T, U + 1, V)).astype("float32")
        labels = np.array([[1, 2]])
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        total = -np.inf
        # last slot is the forced final blank; choose label slots among the
        # first T+U-1 positions
        for lab_pos in combinations(range(T + U - 1), U):
            t = u = 0
            s = 0.0
            valid = True
            for i in range(T + U - 1):
                if i in lab_pos:
                    s += lp[0, t, u, labels[0, u]]
                    u += 1
                else:
                    if t >= T - 1:  # final blank is reserved for the end
                        valid = False
                        break
                    s += lp[0, t, u, 0]
                    t += 1
            if valid:
                s += lp[0, T - 1, U, 0]  # final blank
                total = np.logaddexp(total, s)
        got = F.rnnt_loss(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(np.array([T])),
                          paddle.to_tensor(np.array([U])),
                          blank=0, reduction="none")
        np.testing.assert_allclose(_np(got)[0], -total, atol=1e-4)

    def test_class_center_sample_gather_tree(self):
        lab = paddle.to_tensor(np.array([1, 5, 1, 9]))
        rl, sc = F.class_center_sample(lab, 20, 6)
        assert len(_np(sc)) == 6 and _np(rl).max() < 6
        pos = set(np.asarray([1, 5, 9]))
        assert pos.issubset(set(_np(sc).tolist()))
        ids = paddle.to_tensor(np.array(
            [[[2, 2]], [[3, 9]], [[0, 1]]]))
        par = paddle.to_tensor(np.array(
            [[[0, 0]], [[1, 0]], [[0, 0]]]))
        gt = F.gather_tree(ids, par)
        assert gt.shape == [3, 1, 2]

    def test_sparse_attention_matches_dense_on_full_pattern(self):
        S, D = 4, 8
        q = paddle.to_tensor(np.random.randn(1, 1, S, D).astype("float32"))
        k = paddle.to_tensor(np.random.randn(1, 1, S, D).astype("float32"))
        v = paddle.to_tensor(np.random.randn(1, 1, S, D).astype("float32"))
        # full CSR pattern == dense softmax attention
        offs = paddle.to_tensor(
            (np.arange(S + 1) * S)[None, None].astype("int32"))
        cols = paddle.to_tensor(
            np.tile(np.arange(S), S)[None, None].astype("int32"))
        out = F.sparse_attention(q, k, v, offs, cols)
        qn, kn, vn = _np(q), _np(k), _np(v)
        sc = qn[0, 0] @ kn[0, 0].T / np.sqrt(D)
        pr = np.exp(sc) / np.exp(sc).sum(-1, keepdims=True)
        np.testing.assert_allclose(_np(out)[0, 0], pr @ vn[0, 0], atol=1e-3)

    def test_inplace_activations(self):
        t = paddle.to_tensor(np.array([-1., 2.], "float32"))
        F.tanh_(t)
        assert abs(_np(t)[1] - np.tanh(2)) < 1e-6
        F.softmax_(t)
        assert abs(_np(t).sum() - 1) < 1e-5
        F.leaky_relu_(t)


class TestExtraLayers:
    """Long-tail layers (nn/layer/extra.py)."""

    def test_simple_layers(self):
        assert nn.ChannelShuffle(2)(paddle.to_tensor(
            np.random.randn(1, 4, 3, 3).astype("float32"))).shape == [1, 4, 3, 3]
        d = nn.PairwiseDistance()(
            paddle.to_tensor(np.ones((2, 3), "float32")),
            paddle.to_tensor(np.zeros((2, 3), "float32")))
        np.testing.assert_allclose(_np(d), np.sqrt(3) * np.ones(2), atol=1e-4)
        s = nn.Softmax2D()(paddle.to_tensor(
            np.random.randn(1, 3, 2, 2).astype("float32")))
        assert abs(_np(s)[0, :, 0, 0].sum() - 1) < 1e-5
        assert nn.Unflatten(1, [2, 3])(paddle.to_tensor(
            np.zeros((4, 6), "float32"))).shape == [4, 2, 3]

    def test_loss_layers(self):
        hs = nn.HSigmoidLoss(16, 10)
        loss = hs(paddle.to_tensor(np.random.randn(4, 16).astype("float32")),
                  paddle.to_tensor(np.array([0, 1, 2, 3])))
        assert loss.shape == [4, 1]
        lbl = paddle.to_tensor(np.array([0, 1, 2, 3]))
        mm = nn.MultiMarginLoss()(paddle.to_tensor(
            np.random.randn(4, 5).astype("float32")), lbl)
        assert np.isfinite(mm.item())
        rt = nn.RNNTLoss()(
            paddle.to_tensor(np.random.randn(2, 4, 4, 5).astype("float32")),
            paddle.to_tensor(np.array([[1, 2, 3], [2, 4, 0]])),
            paddle.to_tensor(np.array([4, 3])),
            paddle.to_tensor(np.array([3, 2])))
        assert np.isfinite(rt.item())

    def test_beam_search_decoder(self):
        class ToyCell(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, inputs, states):
                h = states[0] if isinstance(states, (list, tuple)) else states
                nh = paddle.tanh(self.fc(h))
                return nh, nh

        emb = nn.Embedding(8, 8)
        dec = nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=1,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=nn.Linear(8, 8))
        h0 = paddle.to_tensor(np.zeros((2, 8), "float32"))
        out, lp = nn.dynamic_decode(dec, inits=h0, max_step_num=6)
        assert out.shape[0] == 2 and out.shape[2] == 3
        # scores sorted descending per batch
        lpn = _np(lp)
        assert (np.diff(lpn, axis=1) <= 1e-5).all()

    def test_nn_parity_vs_reference(self):
        import re, pathlib
        if not pathlib.Path("/root/reference").exists():
            pytest.skip("reference Paddle checkout not present")
        for mod, path in [(nn, "nn/__init__.py"),
                          (F, "nn/functional/__init__.py")]:
            ref = pathlib.Path(
                f"/root/reference/python/paddle/{path}").read_text()
            names = set(re.findall(r"^\s+'([A-Za-z_][A-Za-z0-9_]*)',\s*$",
                                   ref, re.M))
            missing = [x for x in sorted(names) if not hasattr(mod, x)]
            assert missing == [], (path, missing)
