"""Profiler tests (reference: test/legacy_test/test_profiler.py /
test_newprofiler.py — scheduler states, span capture, chrome export,
stats; VERDICT #9 done criterion: capture a train step and assert
span/export structure)."""

import json
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import profiler


class TestRecordEventAndProfiler:
    def _train_steps(self, prof, n=3):
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.randn([4, 8])
        y = paddle.to_tensor(np.random.randint(0, 4, (4,)))
        for _ in range(n):
            with profiler.RecordEvent("train_step"):
                loss = nn.functional.cross_entropy(net(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
            prof.step(num_samples=4)

    def test_capture_train_step(self, tmp_path):
        prof = profiler.Profiler()
        prof.start()
        self._train_steps(prof)
        prof.stop()
        stats = prof.summary()
        # user span captured with right call count
        assert stats["events"]["train_step"]["calls"] == 3
        assert stats["events"]["train_step"]["total_ms"] > 0
        # ops auto-annotated at dispatch (matmul from Linear, sgd update)
        assert stats["op_counts"].get("linear", 0) >= 3
        # chrome export structure
        path = str(tmp_path / "trace.json")
        prof.export_chrome_tracing(path)
        data = json.load(open(path))
        names = {e["name"] for e in data["traceEvents"]}
        assert "train_step" in names and "linear" in names
        kinds = {e["ph"] for e in data["traceEvents"]}
        assert "X" in kinds and "i" in kinds

    def test_scheduler_states(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                        repeat=1)
        S = profiler.ProfilerState
        assert [sched(i) for i in range(5)] == [
            S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN, S.CLOSED]

    def test_scheduler_gates_recording(self, tmp_path):
        windows = []
        prof = profiler.Profiler(
            scheduler=profiler.make_scheduler(closed=1, ready=0, record=1,
                                              repeat=1),
            on_trace_ready=lambda p: windows.append(
                {s.name for s in p._spans}))
        prof.start()
        # step 0 closed: span must NOT be recorded
        with profiler.RecordEvent("skipped"):
            pass
        prof.step()
        # step 1 is RECORD_AND_RETURN: recorded then exported; the window's
        # spans are cleared after export (each window exports only itself)
        with profiler.RecordEvent("kept"):
            pass
        prof.step()
        prof.stop()
        assert windows and "kept" in windows[0]
        assert "skipped" not in windows[0]

    def test_export_chrome_tracing_handler(self, tmp_path):
        prof = profiler.Profiler(
            on_trace_ready=profiler.export_chrome_tracing(str(tmp_path)))
        with prof:
            with profiler.RecordEvent("w"):
                pass
        files = os.listdir(tmp_path)
        assert any(f.endswith(".paddle_trace.json") for f in files)

    def test_record_event_outside_profiler_is_noop(self):
        with profiler.RecordEvent("orphan"):
            pass
        prof = profiler.Profiler()
        prof.start()
        prof.stop()
        assert "orphan" not in prof.summary()["events"]


class TestBenchmarkTimer:
    def test_ips(self):
        import time
        b = profiler.Benchmark()
        b.begin()
        for _ in range(5):
            time.sleep(0.01)
            b.step(num_samples=32)
        b.end()
        rep = b.report()
        assert rep["steps"] == 5
        assert 0 < rep["batch_cost_avg"] < 1
        assert rep["ips"] > 100
        assert "ips" in b.step_info()

    def test_global_singleton(self):
        assert profiler.benchmark() is profiler.benchmark()


class TestStructuredLogging:
    """SURVEY §5 item 57: one structured JSON-lines event stream for the
    runtime (comm timeouts, checkpoint lifecycle, custom events)."""

    def test_event_log_ring_file_and_sinks(self, tmp_path):
        import json
        from paddle_tpu.utils.log import EventLog
        p = str(tmp_path / "ev.jsonl")
        log = EventLog(path=p)
        seen = []
        log.add_sink(seen.append)
        log.emit("train_step", step=1, loss=2.5)
        log.emit("train_step", step=2, loss=2.1)
        log.emit("other", x=1)
        assert len(log.events("train_step")) == 2
        assert seen[0]["loss"] == 2.5 and "ts" in seen[0]
        lines = [json.loads(l) for l in open(p)]
        assert [l["event"] for l in lines] == ["train_step", "train_step",
                                               "other"]

    def test_checkpoint_events_emitted(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint
        from paddle_tpu.distributed.fleet.elastic import FileKVStore
        from paddle_tpu.utils.log import default_event_log
        default_event_log.ring.clear()
        m = paddle.nn.Linear(4, 2)
        auto = AutoCheckpoint("ev", m, save_dir=str(tmp_path / "ck"),
                              store=FileKVStore(str(tmp_path / "st")),
                              every_n_steps=1)
        auto.step(1)
        auto.wait()
        auto.resume()
        evs = [r["event"] for r in default_event_log.ring]
        assert "checkpoint_saved" in evs
        assert "checkpoint_resume" in evs

    def test_glog_level_logger(self, monkeypatch):
        import logging
        from paddle_tpu.utils import log as L
        monkeypatch.setenv("GLOG_v", "2")
        lg = L.get_logger("ptpu_test_logger")
        assert lg.level == logging.DEBUG
        assert lg.propagate is False
