"""Kill-and-relaunch worker for the auto-checkpoint test (reference:
base/incubate/checkpoint/auto_checkpoint.py — training resumes from the
last etcd-recorded snapshot after a crash).

Usage: python autockpt_worker.py <workdir> <crash_at_step|-1>
Trains 10 steps of a tiny regression; checkpoints every 2 steps; exits
hard (os._exit(101), the elastic relaunch code) at the crash step. On
relaunch, resume() must land on a recorded step > 0 and finish.
Prints: RESUMED_AT <n> and DONE <final_step> <loss>.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.distributed.checkpoint import AutoCheckpoint  # noqa: E402


def main():
    workdir, crash_at = sys.argv[1], int(sys.argv[2])
    paddle.seed(0)
    model = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    from paddle_tpu.distributed.fleet.elastic import FileKVStore
    auto = AutoCheckpoint("reg", model, optimizer=opt,
                          save_dir=f"{workdir}/ckpt",
                          store=FileKVStore(f"{workdir}/store"),
                          every_n_steps=2)
    start = auto.resume()
    print(f"RESUMED_AT {start}", flush=True)

    rng = np.random.RandomState(7)
    X = rng.randn(64, 8).astype(np.float32)
    w_true = rng.randn(8, 1).astype(np.float32)
    Y = X @ w_true

    loss = None
    for step in range(start + 1, 11):
        x = paddle.to_tensor(X)
        y = paddle.to_tensor(Y)
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        h = auto.step(step)
        if h is not None:
            auto.wait()               # deterministic test: join the record
        if step == crash_at:
            import os
            os._exit(101)             # elastic relaunch contract
    print(f"DONE {10} {float(loss):.6f} gstep {opt._global_step}",
          flush=True)


if __name__ == "__main__":
    main()
