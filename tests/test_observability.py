"""Serving telemetry (ISSUE 3): metrics primitives (thread safety,
bucket edges, Prometheus exposition), request lifecycle traces with
injected clocks, the engine's end-to-end trace/registry wiring over the
debug llama, unified chrome-trace engine spans, the allocator
conservation invariant under preemption stress, and the engine stall
watchdog driven deterministically."""

import json
import logging
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability import (Counter, Gauge, Histogram,
                                      MetricsRegistry, RequestTrace,
                                      DEFAULT_LATENCY_BUCKETS)


class TestCounter:
    def test_inc_and_value(self):
        c = Counter("c_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_negative_inc_raises(self):
        c = Counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_concurrent_incs_lose_nothing(self):
        c = Counter("c_total")

        def worker():
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000


class TestGauge:
    def test_set_inc_dec(self):
        g = Gauge("g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value == 6.0

    def test_fn_reads_at_collection_time(self):
        """The one-source-of-truth contract: the gauge re-reads the
        callback on every .value, never caching a stale mirror."""
        box = {"v": 1}
        g = Gauge("g", fn=lambda: box["v"])
        assert g.value == 1.0
        box["v"] = 7
        assert g.value == 7.0

    def test_fn_exception_reads_nan(self):
        g = Gauge("g", fn=lambda: 1 / 0)
        assert g.value != g.value          # NaN, not a raised scrape


class TestHistogram:
    def test_default_buckets_cover_latency_range(self):
        assert DEFAULT_LATENCY_BUCKETS[0] == pytest.approx(1e-4)
        assert DEFAULT_LATENCY_BUCKETS[-1] > 100.0
        assert list(DEFAULT_LATENCY_BUCKETS) == \
            sorted(DEFAULT_LATENCY_BUCKETS)

    def test_le_edge_is_inclusive(self):
        h = Histogram("h", buckets=(1.0, 2.0))
        h.observe(1.0)                     # == edge: counts in le=1.0
        cum = dict(h.cumulative())
        assert cum[1.0] == 1

    def test_overflow_lands_in_inf_only(self):
        h = Histogram("h", buckets=(1.0, 2.0))
        h.observe(99.0)
        cum = h.cumulative()
        assert cum[-1] == (float("inf"), 1)
        assert all(c == 0 for _, c in cum[:-1])

    def test_cumulative_monotone_and_inf_equals_count(self):
        h = Histogram("h")
        rng = np.random.default_rng(0)
        for v in rng.uniform(1e-5, 200.0, 500):
            h.observe(float(v))
        cum = h.cumulative()
        counts = [c for _, c in cum]
        assert counts == sorted(counts)
        assert cum[-1][1] == h.count == 500

    def test_sum_min_max_quantiles(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0, 8.0))
        for v in (0.5, 1.5, 3.0, 7.0):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 4 and s["sum"] == pytest.approx(12.0)
        assert s["min"] == 0.5 and s["max"] == 7.0
        assert h.quantile(0.5) == 2.0      # upper edge of holding bucket
        assert h.quantile(1.0) == 8.0

    def test_quantile_inf_bucket_caps_at_observed_max(self):
        h = Histogram("h", buckets=(1.0,))
        h.observe(42.0)
        assert h.quantile(0.99) == 42.0

    def test_timer_observes_elapsed(self):
        h = Histogram("h")
        with h.time():
            pass
        assert h.count == 1 and h.sum >= 0.0

    def test_non_increasing_edges_raise(self):
        with pytest.raises(ValueError):
            Histogram("h", buckets=(2.0, 1.0))
        with pytest.raises(ValueError):
            Histogram("h", buckets=(1.0, 1.0))


class TestMetricsRegistry:
    def test_get_or_create_returns_same_object(self):
        r = MetricsRegistry()
        assert r.counter("a_total") is r.counter("a_total")
        assert "a_total" in r and r.get("a_total") is not None

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x")
        with pytest.raises(TypeError, match="Counter"):
            r.gauge("x")

    def test_snapshot_is_json_able(self):
        r = MetricsRegistry()
        r.counter("c_total").inc(3)
        r.gauge("g").set(2.5)
        h = r.histogram("lat_seconds")
        h.observe(0.01)
        snap = json.loads(json.dumps(r.snapshot()))
        assert snap["counters"]["c_total"] == 3
        assert snap["gauges"]["g"] == 2.5
        hs = snap["histograms"]["lat_seconds"]
        assert hs["count"] == 1 and hs["buckets"]["+Inf"] == 1

    def test_prometheus_text_format(self):
        r = MetricsRegistry()
        r.counter("req_total", "requests").inc(2)
        r.gauge("depth", "queue depth").set(4)
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = r.prometheus_text()
        assert "# HELP req_total requests" in text
        assert "# TYPE req_total counter" in text
        assert "req_total 2" in text
        assert "# TYPE depth gauge" in text and "depth 4" in text
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 2' in text
        assert "lat_seconds_sum 5.05" in text
        assert "lat_seconds_count 2" in text


class TestRequestTrace:
    def test_derived_metrics_from_injected_clock(self):
        tr = RequestTrace(t=0.0)
        tr.mark("queued", t=1.0)
        tr.mark("admitted", t=3.0)
        tr.mark("first_token", t=4.0)
        tr.mark("retired", t=10.0)
        assert tr.ttft == 4.0
        assert tr.queue_wait == 2.0        # queued->admitted only
        assert tr.tpot(4) == pytest.approx(2.0)  # (10-4)/3
        assert tr.terminal == "retired"
        assert tr.is_monotone() and tr.is_complete()

    def test_queue_wait_sums_preemption_stints(self):
        tr = RequestTrace(t=0.0)
        tr.mark("queued", t=0.0)
        tr.mark("admitted", t=1.0)
        tr.mark("first_token", t=1.5)
        tr.mark("preempted", t=2.0)
        tr.mark("queued", t=2.0)
        tr.mark("admitted", t=5.0)
        tr.mark("retired", t=6.0)
        assert tr.queue_wait == pytest.approx(4.0)   # 1.0 + 3.0
        assert tr.preemptions == 1
        assert tr.is_complete()

    def test_no_queued_mark_charges_arrival_to_admitted(self):
        tr = RequestTrace(t=2.0)           # contiguous-mode direct admit
        tr.mark("admitted", t=5.0)
        assert tr.queue_wait == pytest.approx(3.0)

    def test_mark_once_skips_duplicates(self):
        tr = RequestTrace(t=0.0)
        assert tr.mark_once("first_token", t=1.0) == 1.0
        assert tr.mark_once("first_token", t=2.0) is None
        assert tr.times("first_token") == [1.0]

    def test_incomplete_without_first_token(self):
        tr = RequestTrace(t=0.0)
        tr.mark("admitted", t=1.0)
        tr.mark("retired", t=2.0)
        assert not tr.is_complete()

    def test_failed_is_terminal_and_complete(self):
        tr = RequestTrace(t=0.0)
        tr.mark("failed", t=1.0)
        assert tr.terminal == "failed" and tr.is_complete()

    def test_summary_json_able_and_ids_unique(self):
        a, b = RequestTrace(), RequestTrace()
        assert a.request_id != b.request_id
        json.dumps(a.summary())


def _debug_model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _drive(eng, pending, iters=500):
    for _ in range(iters):
        eng.admit(pending)
        eng.decode_once()
        if eng.idle() and not pending:
            return
    raise AssertionError("engine did not drain the workload")


class TestEngineLifecycleTelemetry:
    def test_every_retired_request_has_complete_trace(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(7)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8)
        reqs = [_Request(rng.randint(1, 128,
                                     (int(rng.randint(3, 12)),))
                         .astype(np.int32), int(rng.choice([3, 6])))
                for _ in range(5)]
        _drive(eng, list(reqs))
        for r in reqs:
            r.wait(timeout=5)
            tr = r.trace
            assert tr.terminal == "retired"
            assert tr.is_monotone() and tr.is_complete()
            states = {s for s, _ in tr.events}
            assert {"arrival", "queued", "admitted", "first_token",
                    "decode_chunk", "retired"} <= states
            assert tr.ttft is not None and tr.ttft >= 0.0

    def test_registry_histograms_match_lifecycle_counts(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(9)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8)
        reqs = [_Request(rng.randint(1, 128, (6,)).astype(np.int32), 6)
                for _ in range(4)]
        _drive(eng, list(reqs))
        snap = eng.metrics.snapshot()
        assert snap["counters"]["engine_admitted_total"] == 4
        assert snap["counters"]["engine_retired_total"] == 4
        assert snap["counters"]["engine_failed_total"] == 0
        # one TTFT / queue-wait observation per admission, one TPOT per
        # multi-token retire — the histograms ARE the lifecycle record
        assert snap["histograms"]["engine_ttft_seconds"]["count"] == 4
        assert snap["histograms"]["engine_queue_wait_seconds"][
            "count"] == 4
        assert snap["histograms"]["engine_tpot_seconds"]["count"] == 4
        assert snap["histograms"]["engine_chunk_seconds"]["count"] >= 1
        g = snap["gauges"]
        for name in ("engine_backlog", "engine_pool_free",
                     "allocator_in_use", "engine_pool_high_watermark",
                     "engine_batch_occupancy", "engine_prefix_hit_rate"):
            assert name in g, name
        assert g["engine_backlog"] == 0
        # stats() is a THIN view over the same registry
        st = eng.stats()
        # rows are gone but their published prefix pages stay cached —
        # the gauge reads the allocator, not a drifting mirror
        assert g["allocator_in_use"] == st["pool"]["used"]
        assert st["admitted"] == 4 and st["retired"] == 4
        assert st["pool"]["high_watermark"] == \
            g["engine_pool_high_watermark"]
        json.dumps(snap)
        assert "engine_ttft_seconds_bucket" in \
            eng.metrics.prometheus_text()

    def test_private_registries_do_not_cross_pollute(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(3)
        p = rng.randint(1, 128, (6,)).astype(np.int32)
        e1 = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                          block_size=8)
        e2 = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                          block_size=8)
        _drive(e1, [_Request(p, 4)])
        assert e1.stats()["retired"] == 1
        assert e2.stats()["retired"] == 0

    def test_ttft_observed_once_across_preemption(self):
        """A preempted-and-resumed request keeps ONE first_token mark:
        the TTFT histogram must not double-count the resume."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(18)
        eng = DecodeEngine(m, capacity=3, s_max=64, chunk=4,
                           block_size=8, n_blocks=6)
        reqs = [_Request(rng.randint(1, 128,
                                     (int(rng.randint(3, 14)),))
                         .astype(np.int32),
                         int(rng.choice([3, 6, 10])),
                         priority=int(rng.randint(0, 3)))
                for _ in range(8)]
        queue, pending = list(reqs), []
        for _ in range(2000):
            while queue and len(pending) < 2:
                pending.append(queue.pop(0))
            eng.admit(pending)
            eng.decode_once()
            if not queue and not pending and eng.idle():
                break
        else:
            raise AssertionError("stress workload did not drain")
        preempted = sum(r.trace.preemptions for r in reqs)
        assert preempted >= 1              # the tiny pool forced some
        for r in reqs:
            assert r.trace.count("first_token") <= 1
            if r.trace.terminal == "retired":
                assert r.trace.is_complete()
        snap = eng.metrics.snapshot()
        assert snap["counters"]["engine_preempted_total"] == preempted
        assert snap["histograms"]["engine_ttft_seconds"]["count"] == \
            snap["counters"]["engine_admitted_total"] - preempted


class TestAllocatorConservation:
    def test_invariant_across_preemption_stress(self):
        """total_allocated - total_freed == in_use at EVERY engine step
        of a pool-starved preempting workload, and the pool drains to
        zero — the counter-drift class the satellite closes."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(18)
        eng = DecodeEngine(m, capacity=3, s_max=64, chunk=4,
                           block_size=8, n_blocks=6)
        reqs = [_Request(rng.randint(1, 128,
                                     (int(rng.randint(3, 14)),))
                         .astype(np.int32),
                         int(rng.choice([3, 6, 10])),
                         priority=int(rng.randint(0, 3)))
                for _ in range(8)]
        queue, pending = list(reqs), []
        a = eng._alloc
        for _ in range(2000):
            while queue and len(pending) < 2:
                pending.append(queue.pop(0))
            eng.admit(pending)
            eng.decode_once()
            assert a.total_allocated - a.total_freed == a.in_use
            if not queue and not pending and eng.idle():
                break
        else:
            raise AssertionError("stress workload did not drain")
        # cached prefix pages may legitimately stay resident; evicting
        # everything must take the pool back to exactly zero in use
        if eng._cache is not None:
            eng._cache.evict(eng.n_blocks)
        assert a.in_use == 0
        assert a.total_allocated == a.total_freed
        # the gauge reads the same source of truth
        assert eng.metrics.get("allocator_in_use").value == 0

    def test_gauge_tracks_live_allocator(self):
        from paddle_tpu.inference.paged_cache import BlockAllocator
        r = MetricsRegistry()
        a = BlockAllocator(8)
        r.gauge("allocator_in_use", fn=lambda: a.in_use)
        pages = a.allocate(3)
        assert r.get("allocator_in_use").value == 3
        a.free(pages)
        assert r.get("allocator_in_use").value == 0
        assert a.total_allocated - a.total_freed == a.in_use == 0


class TestChromeTraceUnifiedTimeline:
    def test_engine_spans_and_op_events_share_one_export(self, tmp_path):
        """The unified timeline: engine lifecycle spans (cat=engine)
        and op-dispatch instants land in ONE chrome trace."""
        from paddle_tpu import profiler
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = _debug_model()
        rng = np.random.RandomState(5)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8)
        prof = profiler.Profiler()
        prof.start()
        reqs = [_Request(rng.randint(1, 128, (6,)).astype(np.int32), 4)
                for _ in range(2)]
        pending = list(reqs)
        for _ in range(200):
            eng.admit(pending)
            eng.decode_once()
            # a host-side paddle op inside the window: the op instant
            # must interleave with the engine spans in the same export
            (paddle.to_tensor(np.ones((2, 2), np.float32)) * 2.0)
            if eng.idle() and not pending:
                break
        prof.stop()
        path = str(tmp_path / "trace.json")
        prof.export_chrome_tracing(path)
        data = json.load(open(path))
        by_cat = {}
        for e in data["traceEvents"]:
            by_cat.setdefault(e.get("cat"), set()).add(e["name"])
        assert "engine.prefill" in by_cat.get("engine", set())
        assert "engine.decode_chunk" in by_cat.get("engine", set())
        assert any(c != "engine" and c is not None for c in by_cat)

    def test_record_event_is_cheap_when_disabled(self):
        """Engine spans ride RecordEvent unconditionally — with no
        profiler enabled they must not emit anything."""
        from paddle_tpu import profiler
        from paddle_tpu.profiler import RecordEvent
        with RecordEvent("engine.decode_chunk", "engine"):
            pass
        prof = profiler.Profiler()
        prof.start()
        prof.stop()
        assert "engine.decode_chunk" not in prof.summary()["events"]


class TestEngineStallWatchdog:
    def _registry(self, steps=0, occupancy=1, backlog=0):
        r = MetricsRegistry()
        r.counter("engine_device_steps_total").inc(steps)
        r.gauge("engine_batch_occupancy").set(occupancy)
        r.gauge("engine_backlog").set(backlog)
        return r

    def _wd(self, registry, **kw):
        from paddle_tpu.distributed.watchdog import EngineStallWatchdog
        kw.setdefault("stall_s", 10.0)
        return EngineStallWatchdog(registry, **kw)

    def test_fires_once_per_stall_episode(self):
        r = self._registry(steps=5)
        events = []
        wd = self._wd(r, on_stall=events.append)
        assert wd.check(now=0.0) is None       # baseline
        assert wd.check(now=5.0) is None       # under threshold
        info = wd.check(now=15.0)              # static 15s while busy
        assert info is not None
        assert info["counter"] == "engine_device_steps_total"
        assert info["stalled_s"] == pytest.approx(15.0)
        assert info["snapshot"]["gauges"]["engine_batch_occupancy"] == 1
        assert wd.check(now=30.0) is None      # same episode: no re-fire
        assert events == [info] and wd.stalls == [info]

    def test_advancing_heartbeat_rearms(self):
        r = self._registry(steps=0)
        wd = self._wd(r)
        assert wd.check(now=0.0) is None
        assert wd.check(now=15.0) is not None  # first stall
        r.counter("engine_device_steps_total").inc(4)
        assert wd.check(now=20.0) is None      # moved: re-armed
        assert wd.check(now=35.0) is not None  # second distinct episode
        assert len(wd.stalls) == 2

    def test_idle_engine_never_stalls(self):
        r = self._registry(steps=3, occupancy=0, backlog=0)
        wd = self._wd(r)
        assert wd.check(now=0.0) is None
        assert wd.check(now=100.0) is None     # quiet != stalled
        # backlog alone (requests waiting, no rows) still counts as busy
        r.gauge("engine_backlog").set(2)
        assert wd.check(now=101.0) is None     # busy clock starts here
        assert wd.check(now=120.0) is not None

    def test_stall_dump_hits_event_log(self):
        from paddle_tpu.utils.log import default_event_log
        r = self._registry(steps=1)
        wd = self._wd(r)
        wd.check(now=0.0)
        mark = len(default_event_log.events("engine_stall"))
        assert wd.check(now=60.0) is not None
        evts = default_event_log.events("engine_stall")[mark:]
        assert len(evts) == 1
        assert evts[0]["snapshot"]["counters"][
            "engine_device_steps_total"] == 1

    def test_missing_counter_is_not_a_stall(self):
        wd = self._wd(MetricsRegistry())
        assert wd.check(now=0.0) is None
        assert wd.check(now=100.0) is None


class TestStructuredLogging:
    def test_kv_line_format(self):
        from paddle_tpu.utils.log import kv_line
        assert kv_line("admitted", req=3, slot=0) == \
            "admitted req=3 slot=0"
        assert kv_line("tick") == "tick"

    def test_log_kv_respects_logger_level(self, caplog):
        from paddle_tpu.utils.log import log_kv
        logger = logging.getLogger("pt.test.obs")
        logger.setLevel(logging.INFO)
        logger.propagate = True
        with caplog.at_level(logging.INFO, logger="pt.test.obs"):
            log_kv(logger, "retired", req=1, ttft_s=0.5)
            log_kv(logger, "chatter", level=logging.DEBUG, x=1)
        assert "retired req=1 ttft_s=0.5" in caplog.text
        assert "chatter" not in caplog.text

    def test_pt_log_level_env_knob(self, monkeypatch):
        from paddle_tpu.utils import log as ptlog
        monkeypatch.setenv("PT_LOG_LEVEL", "debug")
        assert ptlog._glog_level() == logging.DEBUG
        monkeypatch.setenv("PT_LOG_LEVEL", "40")
        assert ptlog._glog_level() == logging.ERROR
        monkeypatch.delenv("PT_LOG_LEVEL")
        monkeypatch.setenv("GLOG_v", "0")
        assert ptlog._glog_level() == logging.WARNING

    def test_server_stats_is_registry_view(self):
        """BatchingServer counts submissions through the registry and
        exposes a thin stats() view (engine stats ride along in
        continuous mode)."""
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        m = _debug_model()
        srv = BatchingServer(GenerationPredictor(m), max_batch=2,
                             max_new_tokens=4, continuous=True,
                             engine_kwargs={"s_max": 64, "chunk": 4,
                                            "block_size": 8})
        try:
            assert srv.metrics is srv.engine.metrics
            r = srv.submit(np.array([1, 5, 9], np.int32))
            r.wait(timeout=120)
            st = srv.stats()
            assert st["submitted"] == 1
            assert st["engine"]["retired"] == 1
            snap = srv.metrics.snapshot()
            assert snap["counters"]["server_submitted_total"] == 1
            assert snap["counters"]["engine_retired_total"] == 1
        finally:
            srv.close()


class TestMergeSnapshots:
    """ISSUE 4: snapshot merging must behave like observing the UNION
    of samples into one histogram — checked property-style (random
    sample sets, associativity, commutativity) over the fixed
    log-spaced edges that make the merge well-defined."""

    @staticmethod
    def _registry_with(samples, counter=0.0, gauge=0.0):
        from paddle_tpu.observability import MetricsRegistry
        r = MetricsRegistry()
        r.counter("reqs_total").inc(counter)
        r.gauge("occupancy").set(gauge)
        h = r.histogram("lat_seconds")
        for v in samples:
            h.observe(v)
        return r

    @staticmethod
    def _sample_sets(seed, k=3):
        rng = np.random.RandomState(seed)
        out = []
        for _ in range(k):
            n = int(rng.randint(0, 40))
            # span the full bucket range incl. sub-min and overflow
            out.append(list(10 ** rng.uniform(-4.5, 2.5, size=n)))
        return out

    def _assert_hist_equal(self, a, b):
        assert a["count"] == b["count"]
        assert a["buckets"] == b["buckets"]
        assert a["sum"] == pytest.approx(b["sum"])
        for k in ("min", "max"):
            if a[k] is None:
                assert b[k] is None
            else:
                assert a[k] == pytest.approx(b[k])
        # snapshot bucket keys are 'g'-formatted (6 sig figs), so a
        # merged quantile can differ from the live histogram's exact
        # edge only by that serialization rounding
        assert a["p50"] == pytest.approx(b["p50"], rel=1e-5)
        assert a["p99"] == pytest.approx(b["p99"], rel=1e-5)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_merge_equals_union_observation(self, seed):
        from paddle_tpu.observability import merge_snapshots
        sets = self._sample_sets(seed)
        snaps = [self._registry_with(s, counter=i + 1, gauge=i).snapshot()
                 for i, s in enumerate(sets)]
        merged = merge_snapshots(snaps)
        union = self._registry_with(
            [v for s in sets for v in s],
            counter=sum(range(1, len(sets) + 1)),
            gauge=sum(range(len(sets)))).snapshot()
        assert merged["counters"] == pytest.approx(union["counters"])
        assert merged["gauges"] == pytest.approx(union["gauges"])
        self._assert_hist_equal(merged["histograms"]["lat_seconds"],
                                union["histograms"]["lat_seconds"])

    @pytest.mark.parametrize("seed", [7, 8])
    def test_merge_is_commutative(self, seed):
        from paddle_tpu.observability import merge_snapshots
        snaps = [self._registry_with(s, counter=i).snapshot()
                 for i, s in enumerate(self._sample_sets(seed))]
        fwd = merge_snapshots(snaps)
        rev = merge_snapshots(list(reversed(snaps)))
        assert fwd["counters"] == pytest.approx(rev["counters"])
        self._assert_hist_equal(fwd["histograms"]["lat_seconds"],
                                rev["histograms"]["lat_seconds"])

    @pytest.mark.parametrize("seed", [11, 12])
    def test_merge_is_associative(self, seed):
        from paddle_tpu.observability import merge_snapshots
        a, b, c = [self._registry_with(s).snapshot()
                   for s in self._sample_sets(seed, k=3)]
        left = merge_snapshots([merge_snapshots([a, b]), c])
        right = merge_snapshots([a, merge_snapshots([b, c])])
        assert left["counters"] == pytest.approx(right["counters"])
        self._assert_hist_equal(left["histograms"]["lat_seconds"],
                                right["histograms"]["lat_seconds"])

    def test_empty_and_single_inputs(self):
        from paddle_tpu.observability import merge_snapshots
        assert merge_snapshots([]) == {"counters": {}, "gauges": {},
                                       "histograms": {}}
        snap = self._registry_with([0.01], counter=2).snapshot()
        one = merge_snapshots([snap])
        assert one["counters"] == snap["counters"]
        self._assert_hist_equal(one["histograms"]["lat_seconds"],
                                snap["histograms"]["lat_seconds"])

    def test_nan_gauges_are_skipped(self):
        from paddle_tpu.observability import MetricsRegistry, merge_snapshots
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.gauge("g", fn=lambda: (_ for _ in ()).throw(RuntimeError()))
        r2.gauge("g").set(3.0)
        m = merge_snapshots([r1.snapshot(), r2.snapshot()])
        assert m["gauges"]["g"] == 3.0

    def test_mismatched_bucket_edges_raise(self):
        from paddle_tpu.observability import MetricsRegistry, merge_snapshots
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.histogram("h", buckets=(1.0, 2.0))
        r2.histogram("h", buckets=(1.0, 4.0))
        with pytest.raises(ValueError, match="bucket edges"):
            merge_snapshots([r1.snapshot(), r2.snapshot()])


class TestPrometheusLabels:
    def test_no_labels_is_byte_identical(self):
        r = MetricsRegistry()
        r.counter("c_total", "help").inc(2)
        r.histogram("h_seconds").observe(0.01)
        base = r.prometheus_text()
        assert r.prometheus_text(labels=None) == base
        assert r.prometheus_text(labels={}) == base

    def test_labels_on_every_sample_sorted_le_last(self):
        r = MetricsRegistry()
        r.counter("c_total").inc(2)
        r.gauge("g").set(1.5)
        r.histogram("h_seconds").observe(0.01)
        text = r.prometheus_text(labels={"worker": "w3", "host": "a"})
        assert 'c_total{host="a",worker="w3"} 2' in text
        assert 'g{host="a",worker="w3"} 1.5' in text
        assert 'h_seconds_bucket{host="a",worker="w3",le="+Inf"} 1' \
            in text
        assert 'h_seconds_sum{host="a",worker="w3"}' in text
        assert 'h_seconds_count{host="a",worker="w3"} 1' in text
        # HELP/TYPE headers stay unlabeled
        assert "# TYPE c_total counter" in text


class TestQuantileFromBuckets:
    """ISSUE 13 satellite: the shared cumulative-bucket quantile rule,
    exercised on the edge buckets (the dedup target for
    merge_snapshots / SLO windows / StepProfiler.summary)."""

    def test_empty_returns_empty_default(self):
        from paddle_tpu.observability import quantile_from_buckets
        assert quantile_from_buckets(0.5, {}, 0) == 0.0
        assert quantile_from_buckets(
            0.99, {"+Inf": 0}, 0, empty=None) is None

    def test_median_lands_on_covering_edge(self):
        from paddle_tpu.observability import quantile_from_buckets
        # 3 of 4 samples at/below 2e-4 (second edge): p50 -> 0.0002
        buckets = {"0.0001": 1, "0.0002": 3, "+Inf": 4}
        assert quantile_from_buckets(0.5, buckets, 4) == \
            pytest.approx(2e-4)

    def test_p99_clamps_to_observed_max(self):
        from paddle_tpu.observability import quantile_from_buckets
        # all mass in +Inf: without a max the edge would be inf; the
        # observed max is the honest clamp
        buckets = {"0.0001": 0, "+Inf": 10}
        assert quantile_from_buckets(0.99, buckets, 10, 7.5) == 7.5

    def test_float_and_string_keys_agree(self):
        from paddle_tpu.observability import quantile_from_buckets
        total = 8
        s = {"0.0001": 2, "0.0004": 6, "+Inf": 8}
        f = {1e-4: 2, 4e-4: 6, float("inf"): 8}
        for q in (0.25, 0.5, 0.9, 0.99):
            assert quantile_from_buckets(q, s, total) == \
                pytest.approx(quantile_from_buckets(q, f, total))

    def test_matches_registry_snapshot_quantiles(self):
        from paddle_tpu.observability import quantile_from_buckets
        r = MetricsRegistry()
        h = r.histogram("h_seconds")
        rng = np.random.RandomState(3)
        for v in 10 ** rng.uniform(-4, 1, size=64):
            h.observe(float(v))
        snap = r.snapshot()["histograms"]["h_seconds"]
        for q, key in ((0.5, "p50"), (0.99, "p99")):
            assert quantile_from_buckets(
                q, snap["buckets"], snap["count"],
                snap["max"]) == pytest.approx(snap[key], rel=1e-5)


class TestFlightRecorder:
    def _rec(self, **kw):
        from paddle_tpu.observability import FlightRecorder
        t = [0.0]

        def clock():
            t[0] += 0.25
            return t[0]

        return FlightRecorder(clock=clock, **kw)

    def test_ring_bound_and_drop_accounting(self):
        rec = self._rec(capacity=4, name="w0")
        for i in range(10):
            rec.record("tick", i=i)
        evts = rec.events()
        assert len(rec) == 4 and len(evts) == 4
        assert [e["i"] for e in evts] == [6, 7, 8, 9]
        snap = rec.snapshot()
        assert snap["seq"] == 10 and snap["dropped"] == 6
        assert snap["capacity"] == 4 and snap["name"] == "w0"

    def test_seq_and_clock_stamps(self):
        rec = self._rec(capacity=8)
        rec.record("a")
        rec.record("b")
        a, b = rec.events()
        assert (a["seq"], b["seq"]) == (1, 2)
        assert a["t"] == 0.25 and b["t"] == 0.5

    def test_kind_filter_and_tail(self):
        rec = self._rec(capacity=16)
        for i in range(6):
            rec.record("even" if i % 2 == 0 else "odd", i=i)
        assert [e["i"] for e in rec.events(kind="odd")] == [1, 3, 5]
        assert [e["i"] for e in rec.events(n=2)] == [4, 5]

    def test_forwarding_stamps_src(self):
        fleet = self._rec(capacity=8, name="fleet")
        w = self._rec(capacity=8, name="w1", forward_to=fleet)
        w.record("fault", step=3, src="should_be_replaced")
        local, = w.events()
        assert local["src"] == "should_be_replaced"  # local keeps it
        fwd, = fleet.events()
        assert fwd["kind"] == "fault" and fwd["step"] == 3
        assert fwd["src"] == "w1"      # forwarded copy is attributed

    def test_fn_gauges_registered(self):
        from paddle_tpu.observability import FlightRecorder
        r = MetricsRegistry()
        rec = FlightRecorder(capacity=2, registry=r)
        for _ in range(5):
            rec.record("x")
        g = r.snapshot()["gauges"]
        assert g["flight_events_seen"] == 5
        assert g["flight_events_dropped"] == 3

    def test_clear_keeps_seen(self):
        rec = self._rec(capacity=4)
        rec.record("x")
        rec.clear()
        assert len(rec) == 0
        assert rec.snapshot()["seq"] == 1


class TestStepProfiler:
    def _prof(self, **kw):
        from paddle_tpu.observability import StepProfiler
        t = [0.0]

        def clock():
            t[0] += 0.001
            return t[0]

        return StepProfiler(clock=clock, **kw), t

    def test_phase_ring_and_summary(self):
        prof, _ = self._prof(capacity=8, worker_id="w0")
        for _ in range(3):
            prof.begin_step()
            with prof.phase("launch"):
                pass
            with prof.phase("host_sync"):
                pass
            prof.end_step()
        s = prof.summary()
        assert s["worker"] == "w0" and s["steps"] == 3
        assert set(s["phases"]) == {"launch", "host_sync"}
        ph = s["phases"]["launch"]
        # ticking clock: every span is exactly one 1ms tick wide
        assert ph["count"] == 3
        assert ph["max_s"] == pytest.approx(0.001)
        assert ph["p50_s"] >= 0.001
        assert s["step_wall"]["count"] == 3

    def test_rings_are_bounded(self):
        prof, _ = self._prof(capacity=4)
        for _ in range(10):
            prof.begin_step()
            with prof.phase("publish"):
                pass
            prof.end_step()
        s = prof.summary()
        assert s["steps"] == 10          # counter keeps counting
        assert s["window"] == 4          # ring keeps the newest 4
        assert s["phases"]["publish"]["count"] == 4

    def test_end_step_without_begin_is_none(self):
        prof, _ = self._prof()
        assert prof.end_step() is None

    def test_unknown_phase_raises(self):
        prof, _ = self._prof()
        with pytest.raises(KeyError):
            prof.phase("not_a_phase")

    def test_registry_histogram_and_gauges(self):
        from paddle_tpu.observability import StepProfiler
        r = MetricsRegistry()
        t = [0.0]

        def clock():
            t[0] += 0.002
            return t[0]

        prof = StepProfiler(clock=clock, registry=r)
        prof.begin_step()
        with prof.phase("admission"):
            pass
        prof.end_step()
        snap = r.snapshot()
        assert snap["histograms"]["engine_step_phase_seconds"][
            "count"] == 1
        assert snap["gauges"]["engine_profiled_steps"] == 1
        assert snap["gauges"]["engine_step_wall_ewma_seconds"] > 0

    def test_outlier_flags_counter_and_flight(self):
        from paddle_tpu.observability import (FlightRecorder,
                                              StepProfiler)
        r = MetricsRegistry()
        rec = FlightRecorder(capacity=16)
        t = [0.0]
        dur = [0.001]

        def clock():
            t[0] += dur[0]
            return t[0]

        prof = StepProfiler(clock=clock, registry=r, recorder=rec,
                            worker_id="w9", outlier_min_steps=4)
        for _ in range(20):
            prof.begin_step()
            prof.end_step()
        dur[0] = 1.0                     # one pathological step
        prof.begin_step()
        prof.end_step()
        assert r.get("engine_step_outliers_total").value == 1
        ev, = rec.events(kind="phase_outlier")
        assert ev["worker"] == "w9" and ev["wall_s"] >= 1.0

    def test_to_events_chrome_shape(self):
        prof, _ = self._prof(capacity=8, worker_id="w0")
        prof.begin_step()
        with prof.phase("launch"):
            pass
        prof.end_step()
        evts = prof.to_events(pid=7)
        steps = [e for e in evts if e["name"] == "engine.step"]
        phases = [e for e in evts if e["name"] == "launch"]
        assert len(steps) == 1 and len(phases) == 1
        for e in evts:
            assert e["ph"] == "X" and e["cat"] == "profile"
            assert e["pid"] == 7 and e["dur"] > 0
        assert steps[0]["tid"] == 0 and phases[0]["tid"] == 1


class TestCompileTracker:
    def _tracker(self, **kw):
        from paddle_tpu.observability import CompileTracker
        t = [0.0]

        def clock():
            t[0] += 0.5
            return t[0]

        return CompileTracker(clock=clock, **kw)

    def test_first_seen_signature_counts_once(self):
        tr = self._tracker()
        fn = tr.wrap("decode", lambda x: x, key=4)
        a = np.zeros((2, 4), np.float32)
        fn(a)
        fn(a)
        fn(np.zeros((2, 8), np.float32))    # new shape -> new compile
        assert tr.stats() == {"compiles": 2, "unexpected": 0,
                              "warm": False}
        log = tr.compile_log()
        assert [e["program"] for e in log] == ["decode", "decode"]
        assert log[0]["bucket_key"] == 4
        assert log[0]["wall_s"] == pytest.approx(0.5)
        assert tr.programs() == {"decode": 2}

    def test_post_warmup_compile_is_unexpected(self):
        from paddle_tpu.observability import FlightRecorder
        r = MetricsRegistry()
        rec = FlightRecorder(capacity=8)
        tr = self._tracker(registry=r, recorder=rec, worker_id="w1")
        fn = tr.wrap("prefill", lambda x: x)
        fn(np.zeros((1, 4), np.int32))
        tr.warmup_done()
        fn(np.zeros((1, 4), np.int32))      # seen: no new compile
        assert tr.stats()["unexpected"] == 0
        fn(np.zeros((1, 16), np.int32))     # stray shape post-warmup
        st = tr.stats()
        assert st == {"compiles": 2, "unexpected": 1, "warm": True}
        snap = r.snapshot()
        assert snap["counters"]["engine_compiles_total"] == 2
        assert snap["gauges"]["engine_unexpected_compiles"] == 1
        kinds = [e["kind"] for e in rec.events()]
        assert kinds == ["compile", "unexpected_compile"]
        assert tr.compile_log()[-1]["post_warmup"] is True

    def test_signature_covers_leaves_and_scalars(self):
        from paddle_tpu.observability import CompileTracker
        sig = CompileTracker.signature(
            (np.zeros((2, 3), np.float32), 7))
        assert sig == ((((2, 3)), "float32"), "int")


class TestDebugHTTPSurface:
    """ISSUE 13 satellite: /healthz, debug routes, the self-diagnosing
    404 and explicit Content-Type on every response."""

    def _serve(self, debug=None):
        from paddle_tpu.inference.fleet_metrics import (
            MetricsAggregator, MetricsHTTPServer)
        r = MetricsRegistry()
        r.counter("c_total").inc()
        agg = MetricsAggregator({"w0": r})
        return MetricsHTTPServer(agg, debug=debug).start()

    @staticmethod
    def _get(srv, path):
        import urllib.error
        import urllib.request
        try:
            resp = urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}{path}", timeout=10)
            return resp.status, resp.headers.get("Content-Type"), \
                resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get("Content-Type"), e.read()

    def test_healthz(self):
        srv = self._serve()
        try:
            code, ctype, body = self._get(srv, "/healthz")
        finally:
            srv.close()
        assert code == 200 and ctype == "application/json"
        assert json.loads(body) == {"status": "ok"}

    def test_debug_route_serves_provider_json(self):
        srv = self._serve(debug={"statusz": lambda: {"x": 1}})
        try:
            code, ctype, body = self._get(srv, "/statusz")
        finally:
            srv.close()
        assert code == 200 and ctype == "application/json"
        assert json.loads(body) == {"x": 1}

    def test_404_lists_served_paths(self):
        srv = self._serve(debug={"flightz": lambda: []})
        try:
            code, ctype, body = self._get(srv, "/nope")
        finally:
            srv.close()
        assert code == 404
        assert ctype.startswith("text/plain")
        text = body.decode()
        for p in ("/metrics", "/metrics.json", "/healthz", "/flightz"):
            assert p in text

    def test_raising_provider_is_500_not_wedge(self):
        def boom():
            raise RuntimeError("kaput")

        srv = self._serve(debug={"statusz": boom})
        try:
            code, ctype, body = self._get(srv, "/statusz")
            # server still answers afterwards
            ok, _, _ = self._get(srv, "/healthz")
        finally:
            srv.close()
        assert code == 500 and ctype.startswith("text/plain")
        assert b"RuntimeError" in body and b"kaput" in body
        assert ok == 200

    def test_metrics_content_types(self):
        srv = self._serve()
        try:
            _, ct_text, _ = self._get(srv, "/metrics")
            _, ct_json, body = self._get(srv, "/metrics.json")
        finally:
            srv.close()
        assert ct_text.startswith("text/plain")
        assert ct_json == "application/json"
        assert "fleet" in json.loads(body)
