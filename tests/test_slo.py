"""ISSUE 5 acceptance: propagated request traces (ONE trace spanning a
failover, with per-worker Chrome lanes), streaming SLO evaluation
(deterministic pending -> firing -> resolved via injected ``now=``,
wired into the fleet's router load penalty), and the resilient
telemetry shipper (always-raising sink drops with backoff, serving
output stays bit-identical)."""

import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.fleet import ServingFleet
from paddle_tpu.inference.fleet_metrics import MetricsAggregator
from paddle_tpu.observability import (MetricsRegistry, RequestTrace,
                                      SLOEngine, SLORule,
                                      TelemetryShipper, merge_snapshots)

ENGINE_KW = dict(capacity=2, s_max=64, chunk=4, block_size=8)


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


# ---------------------------------------------------------------------------
# RequestTrace propagation (tentpole part 1)
# ---------------------------------------------------------------------------
class TestTracePropagation:
    def test_trace_ids_are_unique_and_overridable(self):
        a, b = RequestTrace(t=0.0), RequestTrace(t=0.0)
        assert a.trace_id != b.trace_id
        c = RequestTrace(t=0.0, trace_id="abc")
        assert c.trace_id == "abc"

    def test_summary_keeps_r8_keys_and_appends_fleet_keys(self):
        tr = RequestTrace(request_id=3, t=0.0)
        tr.mark("admitted", t=1.0, worker="w0")
        tr.mark("first_token", t=2.0, worker="w0")
        tr.mark("retired", t=3.0, worker="w0")
        s = tr.summary()
        # r8 consumers' keys, unchanged
        for key in ("request_id", "state", "ttft_s", "queue_wait_s",
                    "preemptions", "decode_chunks", "events"):
            assert key in s
        assert s["state"] == "retired" and s["ttft_s"] == 2.0
        # fleet keys appended
        assert s["trace_id"] == tr.trace_id
        assert s["worker_id"] is None           # no attrs set explicitly
        assert s["hops"] == [] and s["attrs"] == {}
        json.dumps(s)                           # JSON-able

    def test_hop_splits_worker_residency(self):
        tr = RequestTrace(request_id=7, t=0.0)
        tr.mark("queued", t=1.0)
        tr.mark("admitted", t=2.0, worker="w0")
        tr.mark("decode_chunk", t=3.0, worker="w0")
        tr.add_hop("w0", "w1", reason="killed", t=4.0)
        tr.mark("admitted", t=5.0, worker="w1")
        tr.mark("first_token", t=5.5, worker="w1")
        tr.mark("retired", t=6.0, worker="w1")
        # the hop CUTS the w0 span at t=4 even though no w1 event
        # existed yet at that instant
        assert tr._segments() == [("w0", 2.0, 4.0), ("w1", 4.0, 6.0)]
        assert tr.workers == ["w0", "w1"]
        assert tr.attrs["worker_id"] == "w1"
        assert tr.hops == [{"t": 4.0, "from": "w0", "to": "w1",
                            "reason": "killed"}]

    def test_to_events_lanes_and_hop_instant(self):
        pids = {"w0": 1, "w1": 2}
        tr = RequestTrace(request_id=7, t=0.0)
        tr.mark("admitted", t=2.0, worker="w0")
        tr.add_hop("w0", "w1", reason="killed", t=4.0)
        tr.mark("retired", t=6.0, worker="w1")
        ev = tr.to_events(pid_for=lambda w: pids.get(w, 0))
        spans = {e["name"]: e for e in ev if e["ph"] == "X"}
        assert spans["req7@w0"]["pid"] == 1
        assert spans["req7@w0"]["ts"] == 2.0e6
        assert spans["req7@w0"]["dur"] == 2.0e6
        assert spans["req7@w1"]["pid"] == 2
        hop, = [e for e in ev if e["name"] == "req7.hop"]
        assert hop["ph"] == "i" and hop["pid"] == 2
        assert hop["args"]["from"] == "w0"
        assert hop["args"]["reason"] == "killed"
        assert hop["args"]["trace_id"] == tr.trace_id
        # instants carry the pid forward: arrival is router-lane (0),
        # post-admission marks ride the owning worker's lane
        inst = {e["name"]: e["pid"] for e in ev if e["ph"] == "i"}
        assert inst["req7.arrival"] == 0
        assert inst["req7.admitted"] == 1
        assert inst["req7.retired"] == 2
        assert all(e["args"]["trace_id"] == tr.trace_id for e in ev)


# ---------------------------------------------------------------------------
# SLO engine unit semantics (tentpole part 2)
# ---------------------------------------------------------------------------
class TestSLORuleValidation:
    def test_bad_stat_op_and_ratio_without_total_raise(self):
        with pytest.raises(ValueError, match="unknown stat"):
            SLORule("x", "m", "p77", threshold=1.0)
        with pytest.raises(ValueError, match="unknown op"):
            SLORule("x", "m", "p99", threshold=1.0, op="!=")
        with pytest.raises(ValueError, match="total"):
            SLORule("x", "m", "ratio", threshold=0.1)

    def test_holds_ops(self):
        assert SLORule("a", "m", "p99", threshold=1.0).holds(0.5)
        assert not SLORule("a", "m", "p99", threshold=1.0).holds(1.0)
        assert SLORule("a", "m", "p99", threshold=1.0,
                       op="<=").holds(1.0)
        assert SLORule("a", "m", "rate", threshold=1.0,
                       op=">").holds(2.0)

    def test_duplicate_rule_names_raise(self):
        r = SLORule("a", "m", "p99", threshold=1.0)
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine([r, r])


class TestSLOStateMachine:
    def _ttft_engine(self, **kw):
        reg = MetricsRegistry()
        h = reg.histogram("ttft", "time to first token")
        rule = SLORule("ttft_p99", "ttft", "p99", threshold=0.5,
                       window_s=30.0, **kw)
        return reg, h, SLOEngine([rule])

    def test_pending_firing_resolved_is_deterministic(self):
        reg, h, eng = self._ttft_engine(for_s=5.0, clear_for_s=10.0)
        for _ in range(100):
            h.observe(0.01)                     # healthy traffic
        eng.observe(reg.snapshot(), now_=0.0)
        assert eng.check(now_=0.0) == []
        assert eng.states() == {"ttft_p99": "ok"}

        for _ in range(100):
            h.observe(1.0)                      # injected regression
        eng.observe(reg.snapshot(), now_=10.0)
        assert eng.check(now_=10.0) == []       # breach held, not fired
        assert eng.states() == {"ttft_p99": "pending"}

        ev = eng.check(now_=15.0)               # held >= for_s -> fires
        assert [e["state"] for e in ev] == ["firing"]
        assert ev[0]["rule"] == "ttft_p99"
        assert ev[0]["measured"] > 0.5
        # half the windowed observations breach a p99 objective: the
        # error budget (1%) burns at 0.5 / 0.01 = 50x
        assert ev[0]["burn_rate"] == pytest.approx(50.0)
        assert eng.alert("ttft_p99").fired_count == 1
        assert eng.firing() == ["ttft_p99"]

        # regression ends: cumulative counters stop moving, the window
        # slides past the bad stretch -> no data -> objective met
        eng.observe(reg.snapshot(), now_=50.0)
        assert eng.check(now_=50.0) == []       # hysteresis hold
        assert eng.states() == {"ttft_p99": "firing"}
        ev = eng.check(now_=61.0)               # clear held >= clear_for_s
        assert [e["state"] for e in ev] == ["resolved"]
        assert eng.states() == {"ttft_p99": "ok"}
        assert [e["state"] for e in eng.transitions] == ["firing",
                                                         "resolved"]

    def test_for_s_zero_fires_on_first_breaching_check(self):
        reg, h, eng = self._ttft_engine(for_s=0.0)
        for _ in range(10):
            h.observe(1.0)
        eng.observe(reg.snapshot(), now_=0.0)
        ev = eng.check(now_=0.0)
        assert [e["state"] for e in ev] == ["firing"]

    def test_pending_clears_without_firing(self):
        reg, h, eng = self._ttft_engine(for_s=5.0)
        for _ in range(10):
            h.observe(1.0)
        eng.observe(reg.snapshot(), now_=0.0)
        eng.check(now_=0.0)
        assert eng.states() == {"ttft_p99": "pending"}
        eng.observe(reg.snapshot(), now_=40.0)  # breach slid out before
        eng.check(now_=40.0)                    # the for_s hold elapsed
        assert eng.states() == {"ttft_p99": "ok"}
        assert eng.transitions == []

    def test_ratio_rule_is_windowed(self):
        reg = MetricsRegistry()
        failed = reg.counter("failed")
        retired = reg.counter("retired")
        eng = SLOEngine([SLORule(
            "err", "failed", "ratio", threshold=0.1, window_s=30.0,
            total=("retired", "failed"))])
        retired.inc(100)
        failed.inc(1)
        eng.observe(reg.snapshot(), now_=0.0)
        assert eng.check(now_=0.0) == []        # 1/101 < 10%
        failed.inc(50)                          # failure spike
        eng.observe(reg.snapshot(), now_=10.0)
        ev = eng.check(now_=10.0)
        assert [e["state"] for e in ev] == ["firing"]
        assert ev[0]["measured"] == pytest.approx(51 / 151)
        # the spike slides out of the window: delta counters are zero,
        # no-data means the objective is met again
        eng.observe(reg.snapshot(), now_=45.0)
        ev = eng.check(now_=45.0)
        assert [e["state"] for e in ev] == ["resolved"]

    def test_no_data_is_objective_met(self):
        _, _, eng = self._ttft_engine()
        assert eng.check(now_=0.0) == []
        assert eng.states() == {"ttft_p99": "ok"}

    def test_on_alert_exceptions_are_contained(self):
        reg = MetricsRegistry()
        h = reg.histogram("ttft")
        calls = []

        def hook(info):
            calls.append(info)
            raise RuntimeError("pager down")

        eng = SLOEngine([SLORule("ttft_p99", "ttft", "p99",
                                 threshold=0.5)], on_alert=hook)
        h.observe(1.0)
        eng.observe(reg.snapshot(), now_=0.0)
        ev = eng.check(now_=0.0)                # must not raise
        assert len(ev) == len(calls) == 1
        assert eng.transitions == ev            # still recorded

    def test_engine_self_observes_into_registry(self):
        reg = MetricsRegistry()
        h = reg.histogram("ttft")
        meta = MetricsRegistry()
        eng = SLOEngine([SLORule("ttft_p99", "ttft", "p99",
                                 threshold=0.5)], registry=meta)
        h.observe(1.0)
        eng.observe(reg.snapshot(), now_=0.0)
        eng.check(now_=0.0)
        snap = meta.snapshot()
        assert snap["counters"]["slo_alerts_fired_total"] == 1
        assert snap["gauges"]["slo_alerts_firing"] == 1
        eng.observe(reg.snapshot(), now_=100.0)  # past the 60s window
        eng.check(now_=100.0)
        snap = meta.snapshot()
        assert snap["counters"]["slo_alerts_resolved_total"] == 1
        assert snap["gauges"]["slo_alerts_firing"] == 0


# ---------------------------------------------------------------------------
# Telemetry shipper unit semantics (tentpole part 3)
# ---------------------------------------------------------------------------
class _BoomSink:
    def __init__(self):
        self.calls = 0

    def emit(self, payload):
        self.calls += 1
        raise OSError("collector unreachable")


class _FlakySink:
    def __init__(self, fail_first):
        self.fail_first = fail_first
        self.out = []

    def emit(self, payload):
        if self.fail_first > 0:
            self.fail_first -= 1
            raise OSError("transient")
        self.out.append(payload)


class TestTelemetryShipper:
    def test_raising_sink_backs_off_and_drops_oldest(self):
        ship = TelemetryShipper(collect=lambda: {"n": 1},
                                sinks=[_BoomSink()], interval_s=1.0,
                                queue_max=3, backoff_base_s=0.5,
                                backoff_max_s=4.0, jitter=0.0)
        ship.flush(now_=0.0)                    # first failure
        st = ship.stats()
        assert st["sink_errors"] == 1 and st["retries"] == 0
        assert ship._sinks[0].backoff_s == 0.5
        ship.flush(now_=0.25)                   # inside backoff: enqueue
        assert ship.stats()["sink_errors"] == 1  # only, no emit attempt
        assert ship.stats()["queue_depth"] == 2
        ship.flush(now_=0.5)                    # retry -> fail -> double
        assert ship._sinks[0].backoff_s == 1.0
        ship.flush(now_=1.5)
        ship.flush(now_=3.5)
        ship.flush(now_=7.5)                    # 2.0 -> 4.0 -> capped
        st = ship.stats()
        assert ship._sinks[0].backoff_s == 4.0  # == backoff_max_s
        assert st["sink_errors"] == 5 and st["retries"] == 4
        assert st["queue_depth"] == 3           # bounded
        assert st["dropped"] == 3               # drop-OLDEST, counted
        assert st["shipped"] == 0
        snap = ship.registry.snapshot()         # self-observation
        assert snap["counters"]["shipper_dropped_total"] == 3
        assert snap["gauges"]["shipper_queue_depth"] == 3
        assert snap["gauges"]["shipper_backoff_seconds"] == 4.0

    def test_recovery_drains_queue_in_order(self):
        sink = _FlakySink(fail_first=2)
        seq = iter(range(100))
        ship = TelemetryShipper(collect=lambda: {"n": next(seq)},
                                sinks=[sink], interval_s=1.0,
                                queue_max=8, backoff_base_s=0.5,
                                jitter=0.0)
        ship.flush(now_=0.0)
        ship.flush(now_=0.5)
        assert ship.stats()["shipped"] == 0
        delivered = ship.flush(now_=1.5)        # sink recovered
        assert delivered == 3
        assert [p["n"] for p in sink.out] == [0, 1, 2]  # order kept
        st = ship.stats()
        assert st["shipped"] == 3 and st["queue_depth"] == 0
        assert ship._sinks[0].backoff_s == 0.0  # reset on success

    def test_tick_honors_interval(self):
        sink = _FlakySink(fail_first=0)
        ship = TelemetryShipper(collect=lambda: {"n": 1}, sinks=[sink],
                                interval_s=1.0)
        assert ship.tick(now_=0.0) == 1         # first tick flushes
        assert ship.tick(now_=0.5) == 0         # interval not elapsed
        assert ship.tick(now_=1.0) == 1
        assert ship.stats()["enqueued"] == 2

    def test_collect_exception_is_contained(self):
        def boom():
            raise RuntimeError("registry exploded")

        sink = _FlakySink(fail_first=0)
        ship = TelemetryShipper(collect=boom, sinks=[sink])
        assert ship.flush(now_=0.0) == 0        # no raise, no payload
        assert ship.stats()["enqueued"] == 0

    def test_jitter_is_seeded_and_deterministic(self):
        def run(seed):
            ship = TelemetryShipper(collect=lambda: {"n": 1},
                                    sinks=[_BoomSink()], jitter=0.5,
                                    seed=seed, backoff_base_s=0.5)
            ship.flush(now_=0.0)
            ship.flush(now_=100.0)
            return ship._sinks[0].backoff_s

        assert run(7) == run(7)                 # replayable
        a, b = run(1), run(2)
        assert a != b                           # but genuinely jittered


# ---------------------------------------------------------------------------
# Fleet integration: one trace across failover + Chrome lanes
# ---------------------------------------------------------------------------
class TestFleetTraceFailover:
    def test_one_trace_spans_killed_worker(self, tmp_path):
        """The acceptance bar: kill a worker mid-flight; each re-routed
        request keeps ONE trace (same trace_id) whose hop links the
        dead worker's segment to the survivor's, the Chrome export puts
        the segments in per-worker lanes, and output still bit-matches
        solo."""
        m = _model()
        rng = np.random.RandomState(5)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        reqs, expect = [], []
        for _ in range(4):
            p = rng.randint(1, 128, (10,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=16))
            expect.append(_solo(m, p, 16))
        ids_before = [r.trace.trace_id for r in reqs]
        fleet.step()
        assert fleet.workers[1].occupancy > 0
        moved = fleet.kill_worker("w1")
        assert moved > 0
        fleet.run_until_drained()
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=60)).reshape(-1),
                e.reshape(-1))
        # trace identity survived the failover — no new trace was cut
        assert [r.trace.trace_id for r in reqs] == ids_before
        hopped = [r.trace for r in reqs if r.trace.hops]
        assert len(hopped) == moved
        for tr in hopped:
            assert len(tr.hops) == 1
            hop = tr.hops[0]
            assert hop["from"] == "w1" and hop["to"] == "w0"
            assert hop["reason"] == "killed"
            assert tr.workers == ["w1", "w0"]   # first-touch order
            assert tr.attrs["worker_id"] == "w0"
            assert tr.terminal == "retired" and tr.is_complete()
            s = tr.summary()
            assert s["trace_id"] == tr.trace_id
            assert s["hops"] == tr.hops
        untouched = [r.trace for r in reqs if not r.trace.hops]
        assert all(tr.workers == ["w0"] for tr in untouched)
        # every submit stamped the router span
        assert all(r.trace.attrs["route_reason"] == "round_robin"
                   for r in reqs)

        path = tmp_path / "fleet_timeline.json"
        assert fleet.export_chrome_timeline(str(path)) == str(path)
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        lanes = {e["pid"]: e["args"]["name"] for e in evs
                 if e["ph"] == "M"}
        assert lanes == {0: "router", 1: "worker w0", 2: "worker w1"}
        tr = hopped[0]
        spans = [e for e in evs if e["ph"] == "X"
                 and e["args"].get("trace_id") == tr.trace_id]
        assert {(e["args"]["worker"], e["pid"]) for e in spans} == \
            {("w1", 2), ("w0", 1)}              # one lane per worker
        hop_ev, = [e for e in evs if e["name"].endswith(".hop")
                   and e["args"]["trace_id"] == tr.trace_id]
        assert hop_ev["pid"] == 1               # instant on the TARGET
        assert hop_ev["args"]["reason"] == "killed"
        fleet.close()


# ---------------------------------------------------------------------------
# Fleet integration: SLO control loop
# ---------------------------------------------------------------------------
class TestFleetSLOControlLoop:
    def test_ttft_regression_boosts_router_load_penalty(self):
        """Injected TTFT regression drives ok -> pending -> firing ->
        resolved through ``check_slo(now=)`` deterministically, and the
        FIRING alert measurably changes the affinity router's load
        penalty (restored on resolve)."""
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="affinity",
                             engine_kwargs=ENGINE_KW)
        seen = []
        fleet.enable_slo(rules=[SLORule(
            "ttft_p99", "engine_ttft_seconds", "p99", threshold=0.5,
            window_s=30.0, for_s=5.0, clear_for_s=10.0)],
            on_alert=seen.append, load_penalty_boost=4.0)
        base = fleet.load_penalty
        h = fleet.workers[0].registry.get("engine_ttft_seconds")
        assert h is not None                    # engine registers it
        for _ in range(50):
            h.observe(2.0)                      # injected regression
        assert fleet.check_slo(now=0.0) == []
        assert fleet.slo.states() == {"ttft_p99": "pending"}
        assert fleet.load_penalty == base       # pending does nothing
        ev = fleet.check_slo(now=5.0)
        assert [e["state"] for e in ev] == ["firing"]
        assert fleet.load_penalty == base * 4.0  # control loop closed
        assert fleet.slo.alert("ttft_p99").burn_rate > 1.0
        # regression over: no new observations, window slides past
        assert fleet.check_slo(now=50.0) == []  # hysteresis hold
        assert fleet.load_penalty == base * 4.0
        ev = fleet.check_slo(now=61.0)
        assert [e["state"] for e in ev] == ["resolved"]
        assert fleet.load_penalty == base       # restored
        assert [e["state"] for e in seen] == ["firing", "resolved"]
        # the router registry carries the alert counters for scraping
        snap = fleet.metrics.snapshot()
        assert snap["counters"]["slo_alerts_fired_total"] == 1
        assert snap["counters"]["slo_alerts_resolved_total"] == 1
        fleet.close()


# ---------------------------------------------------------------------------
# Fleet integration: shipper resilience + bit-identical serving
# ---------------------------------------------------------------------------
class TestFleetShipper:
    def test_raising_sink_never_perturbs_serving(self):
        """An always-raising sink: the shipper drops with backoff, its
        self-observation counters land in the fleet scrape body, and
        generation output is bit-identical to a shipper-disabled run."""
        m = _model()
        rng = np.random.RandomState(11)
        prompts = [rng.randint(1, 128, (8,)).astype(np.int32)
                   for _ in range(3)]
        expect = [_solo(m, p, 8) for p in prompts]

        def run(sinks):
            fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                                 engine_kwargs=ENGINE_KW)
            if sinks is not None:
                fleet.enable_shipper(sinks, interval_s=0.0,
                                     queue_max=2)
            reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
            fleet.run_until_drained()
            outs = [np.asarray(r.wait(timeout=60)).reshape(-1)
                    for r in reqs]
            return fleet, outs

        f_off, off = run(None)
        f_off.close()
        boom = _BoomSink()
        f_on, on = run([boom])
        for a, b, e in zip(off, on, expect):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, e.reshape(-1))
        assert boom.calls > 0                   # sink genuinely raised
        for _ in range(3):                      # keep collecting against
            f_on.shipper.flush()                # the full, backing-off
        st = f_on.shipper.stats()               # queue
        assert st["sink_errors"] > 0 and st["shipped"] == 0
        assert st["dropped"] > 0                # drop-oldest, counted
        assert st["queue_depth"] == 2           # bounded at queue_max
        text = f_on.aggregator().prometheus_text()
        assert 'shipper_sink_errors_total{worker="shipper"}' in text
        assert 'shipper_dropped_total{worker="shipper"}' in text
        f_on.close()

    def test_collect_telemetry_payload_shape(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        fleet.enable_slo()
        sink = _FlakySink(fail_first=0)
        fleet.enable_shipper([sink], interval_s=0.0)
        r = fleet.submit(np.arange(1, 9, dtype=np.int32),
                         max_new_tokens=4)
        fleet.run_until_drained()
        r.wait(timeout=60)
        fleet.shipper.flush()                   # ship the retirement
        assert sink.out
        last = sink.out[-1]
        assert last["kind"] == "fleet_telemetry"
        assert "engine_retired_total" in last["snapshot"]["counters"]
        assert last["slo"] == {"ttft_p99": "ok", "error_rate": "ok",
                               "queue_wait_p50": "ok"}
        shipped_traces = [t for p in sink.out for t in p["traces"]]
        assert [t["trace_id"] for t in shipped_traces] == \
            [r.trace.trace_id]                  # shipped exactly once
        assert shipped_traces[0]["state"] == "retired"
        fleet.close()


# ---------------------------------------------------------------------------
# Satellites: Prometheus escaping + merge_snapshots degenerate inputs
# ---------------------------------------------------------------------------
class TestPrometheusEscaping:
    PATHOLOGICAL = 'tail p99 \\ of "request\nlatency"'

    def test_pathological_help_is_escaped(self):
        reg = MetricsRegistry()
        reg.counter("weird_total", self.PATHOLOGICAL)
        text = reg.prometheus_text()
        want = 'tail p99 \\\\ of "request\\nlatency"'
        assert f"# HELP weird_total {want}" in text.splitlines()
        # no sample/HELP line was torn by the raw newline
        assert not any(ln.startswith("latency")
                       for ln in text.splitlines())

    def test_label_values_are_escaped(self):
        reg = MetricsRegistry()
        reg.counter("jobs_total", "jobs").inc()
        text = reg.prometheus_text(labels={"worker": 'w"0\\\n'})
        assert 'jobs_total{worker="w\\"0\\\\\\n"} 1' in \
            text.splitlines()

    def test_aggregator_escapes_help_and_labels(self):
        reg = MetricsRegistry()
        reg.counter("weird_total", self.PATHOLOGICAL).inc()
        agg = MetricsAggregator()
        agg.add('w"0\n', reg)
        text = agg.prometheus_text()
        want = 'tail p99 \\\\ of "request\\nlatency"'
        assert f"# HELP weird_total {want}" in text.splitlines()
        assert 'weird_total{worker="w\\"0\\n"} 1' in text.splitlines()


class TestMergeSnapshotsDegenerate:
    def test_union_rule_for_missing_metrics(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("only_a_total").inc(2)
        a.histogram("lat").observe(0.01)
        b.counter("only_b_total").inc(3)
        b.counter("only_a_total").inc(5)
        merged = merge_snapshots([a.snapshot(), b.snapshot()])
        assert merged["counters"] == {"only_a_total": 7.0,
                                      "only_b_total": 3.0}
        # a histogram present on one worker merges as-is
        assert merged["histograms"]["lat"]["count"] == 1
        assert merged["histograms"]["lat"]["p50"] == \
            a.snapshot()["histograms"]["lat"]["p50"]

    def test_single_snapshot_quantiles_round_trip(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for v in (0.001, 0.01, 0.1, 1.0, 1.0, 1.0):
            h.observe(v)
        snap = reg.snapshot()
        merged = merge_snapshots([snap])
        for key in ("count", "sum", "min", "max", "p50", "p99"):
            assert merged["histograms"]["lat"][key] == \
                snap["histograms"]["lat"][key]

    def test_merge_of_empty_iterable_is_empty(self):
        assert merge_snapshots([]) == {"counters": {}, "gauges": {},
                                       "histograms": {}}
