"""Static auto-parallel Engine + rpc tests (reference:
test/auto_parallel/ engine api tests; test/rpc/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.io import Dataset


class RegDS(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, 8).astype(np.float32)
        w = rng.rand(8, 1).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestEngine:
    def test_fit_evaluate_predict(self, tmp_path):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        engine = dist.Engine(model=model, loss=nn.MSELoss(),
                             optimizer=paddle.optimizer.Adam(
                                 learning_rate=1e-2,
                                 parameters=model.parameters()))
        mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
        engine.prepare(mesh=mesh)
        ds = RegDS()
        hist = engine.fit(ds, batch_size=16, epochs=3, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        ev = engine.evaluate(ds, batch_size=16, verbose=0)
        assert ev["loss"] < hist["loss"][0]
        preds = engine.predict(ds, batch_size=16)
        assert preds[0].shape == (16, 1)
        engine.save(str(tmp_path / "m"))
        engine.load(str(tmp_path / "m"))

    def test_sharding_strategy_applies(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(64, 64))
        strategy = dist.Strategy()
        strategy.sharding.enable = True
        strategy.sharding.stage = 3
        engine = dist.Engine(model=model, loss=nn.MSELoss(),
                             optimizer=paddle.optimizer.SGD(
                                 learning_rate=0.1,
                                 parameters=model.parameters()),
                             strategy=strategy)
        mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
        engine.prepare(mesh=mesh)
        w = model[0].weight
        assert "dp" in str(w._value.sharding.spec)  # stage-3 param sharding


def _double(x):
    return x * 2


def _fail():
    raise ValueError("boom")


class TestRpc:
    def test_local_sync_async(self):
        from paddle_tpu.distributed import rpc
        rpc.init_rpc("worker0", rank=0, world_size=1)
        try:
            assert rpc.rpc_sync("worker0", _double, args=(21,)) == 42
            fut = rpc.rpc_async(0, _double, args=(5,))
            assert fut.wait() == 10
            info = rpc.get_worker_info("worker0")
            assert info.rank == 0
            assert rpc.get_current_worker_info().name == "worker0"
            assert len(rpc.get_all_worker_infos()) == 1
        finally:
            rpc.shutdown()

    def test_remote_exception_propagates(self):
        from paddle_tpu.distributed import rpc
        rpc.init_rpc("w", rank=0, world_size=1)
        try:
            with pytest.raises(ValueError, match="boom"):
                rpc.rpc_sync("w", _fail)
        finally:
            rpc.shutdown()
