"""Worker script for the launch CLI test (reference analogue:
test/collective/ per-API scripts run by TestDistBase multi-process).

Run under ``python -m paddle_tpu.distributed.launch --nproc_per_node 2``;
exercises the cross-host eager communication surface over the
jax.distributed CPU rendezvous."""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 2, f"expected world=2, got {world}"

    # all_reduce
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(np.asarray(t._value), np.full((4,), 3.0))

    # all_gather
    outs = []
    dist.all_gather(outs, paddle.to_tensor(
        np.full((2,), float(rank), np.float32)))
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[1]._value), [1.0, 1.0])

    # broadcast
    b = paddle.to_tensor(np.full((3,), float(rank * 7 + 1), np.float32))
    dist.broadcast(b, src=0)
    np.testing.assert_allclose(np.asarray(b._value), np.full((3,), 1.0))

    # scatter (src=0 holds [10, 11])
    target = paddle.zeros([2])
    parts = [paddle.to_tensor(np.full((2,), 10.0 + i, np.float32))
             for i in range(2)] if rank == 0 else None
    dist.scatter(target, parts, src=0)
    np.testing.assert_allclose(np.asarray(target._value),
                               np.full((2,), 10.0 + rank))

    # all_to_all: rank r sends [r*10+i] to rank i
    ins = [paddle.to_tensor(np.full((2,), float(rank * 10 + i), np.float32))
           for i in range(2)]
    outs = []
    dist.all_to_all(outs, ins)
    for i in range(2):
        np.testing.assert_allclose(np.asarray(outs[i]._value),
                                   np.full((2,), float(i * 10 + rank)))

    # reduce_scatter
    rs = paddle.zeros([2])
    dist.reduce_scatter(rs, ins)  # sum over ranks of ins[j], keep mine
    expect = np.full((2,), float(0 * 10 + rank) + float(1 * 10 + rank))
    np.testing.assert_allclose(np.asarray(rs._value), expect)

    # send / recv over the KV store
    if rank == 0:
        dist.send(paddle.to_tensor(np.arange(4, dtype=np.float32)), dst=1)
    else:
        buf = paddle.zeros([4])
        dist.recv(buf, src=0)
        np.testing.assert_allclose(np.asarray(buf._value), np.arange(4.0))

    # LARGE send/recv rides the direct TCP data plane (SURVEY item 17):
    # 2M floats = 8MB, far above the coordinator-KV control-plane cap
    big = np.arange(2_000_000, dtype=np.float32)
    if rank == 0:
        dist.send(paddle.to_tensor(big), dst=1)
        # and a second one to exercise sequence ordering on the channel
        dist.send(paddle.to_tensor(big * 2), dst=1)
    else:
        buf = paddle.zeros([2_000_000])
        dist.recv(buf, src=0)
        np.testing.assert_allclose(np.asarray(buf._value), big)
        dist.recv(buf, src=0)
        np.testing.assert_allclose(np.asarray(buf._value), big * 2)

    # batch_isend_irecv ring exchange
    from paddle_tpu.distributed.communication import P2POp, batch_isend_irecv
    send_t = paddle.to_tensor(np.full((2,), float(rank), np.float32))
    recv_t = paddle.zeros([2])
    ops = [P2POp(dist.communication.send, send_t, (rank + 1) % 2),
           P2POp(dist.communication.recv, recv_t, (rank + 1) % 2)]
    batch_isend_irecv(ops)
    np.testing.assert_allclose(np.asarray(recv_t._value),
                               np.full((2,), float((rank + 1) % 2)))

    # eager DataParallel: per-grad allreduce hooks (EagerReducer analogue)
    import paddle_tpu.nn as nn
    paddle.seed(7)  # same init on both ranks
    model = dist.DataParallel(nn.Linear(4, 2))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    np.random.seed(100 + rank)  # different data per rank  # staticcheck: disable=SC04
    x = paddle.to_tensor(  # stream seeded above
        np.random.randn(8, 4).astype(np.float32))  # staticcheck: disable=SC04
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()
    # after averaged grads, params must be identical across ranks
    w = np.asarray(model.parameters()[0]._value)
    outs = []
    dist.all_gather(outs, paddle.to_tensor(w))
    np.testing.assert_allclose(np.asarray(outs[0]._value),
                               np.asarray(outs[1]._value), atol=1e-6)

    # no_sync gradient accumulation: avg(g1+g2) parity with the reference
    # reducer (grads from the no_sync backward get synced on the next
    # normal backward)
    paddle.seed(9)
    m2 = dist.DataParallel(nn.Linear(4, 2))
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=m2.parameters())
    np.random.seed(200 + rank)  # staticcheck: disable=SC04 — per-rank fixture data
    xa = paddle.to_tensor(  # stream seeded above
        np.random.randn(4, 4).astype(np.float32))  # staticcheck: disable=SC04
    xb = paddle.to_tensor(  # stream seeded above
        np.random.randn(4, 4).astype(np.float32))  # staticcheck: disable=SC04
    with m2.no_sync():
        (m2(xa) ** 2).mean().backward()
    (m2(xb) ** 2).mean().backward()
    opt2.step()
    w2 = np.asarray(m2.parameters()[0]._value)
    outs2 = []
    dist.all_gather(outs2, paddle.to_tensor(w2))
    np.testing.assert_allclose(np.asarray(outs2[0]._value),
                               np.asarray(outs2[1]._value), atol=1e-6)

    # subgroup collectives (VERDICT #7): a proper 1-of-2 subgroup —
    # member reduces with itself over the KV rendezvous; the non-member
    # returns immediately instead of deadlocking
    g0 = dist.new_group([0])
    t0 = paddle.to_tensor(np.full((2,), float(rank + 5), np.float32))
    dist.all_reduce(t0, group=g0)
    np.testing.assert_allclose(np.asarray(t0._value),
                               np.full((2,), float(rank + 5)))

    dist.barrier()
    print(f"rank {rank}: COMM_OK")


if __name__ == "__main__":
    main()
