"""Pallas kernel tests (interpret mode on CPU; real Mosaic on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


class TestFlashAttention:
    def _rand(self, b, s, h, d, dtype=np.float32, seed=0):
        rng = np.random.RandomState(seed)
        return (rng.randn(b, s, h, d).astype(dtype) * 0.5 for _ in range(3))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        q, k, v = self._rand(2, 128, 2, 32)
        out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal, True)
        ref = _sdpa_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3), \
            np.abs(np.asarray(out) - np.asarray(ref)).max()

    def test_grad_flows(self):
        from paddle_tpu.kernels.flash_attention import flash_attention

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, True) ** 2)

        q, k, v = self._rand(1, 64, 2, 16)
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert np.isfinite(np.asarray(gq)).all()
        # compare against pure-XLA attention grads
        from paddle_tpu.kernels.flash_attention import _sdpa_reference

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, True) ** 2)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert np.allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        assert np.allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    def test_odd_shapes_fall_back(self):
        from paddle_tpu.kernels.flash_attention import flash_attention_fwd
        q = jnp.asarray(np.random.randn(1, 5, 2, 7).astype(np.float32))
        out = flash_attention_fwd(q, q, q, causal=True)
        assert out.shape == (1, 5, 2, 7)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_reference(self, causal):
        # the Pallas dq/dkv kernels vs XLA autodiff of reference attention
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32))

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, True) * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, causal) * w)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_forward_backward(self, causal):
        # grouped K/V heads (H=4, Hkv=2) without materializing repeats
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        rng = np.random.RandomState(7)
        q = jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32))

        out = flash_attention(q, k, v, causal, True)
        ref = _sdpa_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, True) * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, causal) * w)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    def test_gqa_reference_matches_repeat(self):
        # grouped reference == naive repeat-KV reference
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        rng = np.random.RandomState(11)
        q = jnp.asarray(rng.randn(1, 32, 6, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        out = _sdpa_reference(q, k, v, True)
        kr = jnp.repeat(k, 3, axis=2)
        vr = jnp.repeat(v, 3, axis=2)
        ref = _sdpa_reference(q, kr, vr, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
