"""Pallas kernel tests (interpret mode on CPU; real Mosaic on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


class TestFlashAttention:
    def _rand(self, b, s, h, d, dtype=np.float32, seed=0):
        rng = np.random.RandomState(seed)
        return (rng.randn(b, s, h, d).astype(dtype) * 0.5 for _ in range(3))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        q, k, v = self._rand(2, 128, 2, 32)
        out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal, True)
        ref = _sdpa_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3), \
            np.abs(np.asarray(out) - np.asarray(ref)).max()

    def test_grad_flows(self):
        from paddle_tpu.kernels.flash_attention import flash_attention

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, True) ** 2)

        q, k, v = self._rand(1, 64, 2, 16)
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert np.isfinite(np.asarray(gq)).all()
        # compare against pure-XLA attention grads
        from paddle_tpu.kernels.flash_attention import _sdpa_reference

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, True) ** 2)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert np.allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        assert np.allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    def test_odd_shapes_fall_back(self):
        from paddle_tpu.kernels.flash_attention import flash_attention_fwd
        q = jnp.asarray(np.random.randn(1, 5, 2, 7).astype(np.float32))
        out = flash_attention_fwd(q, q, q, causal=True)
        assert out.shape == (1, 5, 2, 7)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_reference(self, causal):
        # the Pallas dq/dkv kernels vs XLA autodiff of reference attention
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(2, 128, 4, 32).astype(np.float32))

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, True) * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, causal) * w)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_forward_backward(self, causal):
        # grouped K/V heads (H=4, Hkv=2) without materializing repeats
        from paddle_tpu.kernels.flash_attention import (_sdpa_reference,
                                                        flash_attention)
        rng = np.random.RandomState(7)
        q = jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(2, 64, 4, 16).astype(np.float32))

        out = flash_attention(q, k, v, causal, True)
        ref = _sdpa_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, True) * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, causal) * w)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-3)

    def test_grouped_fwd_vmem_gate(self):
        """The GQA-grouped fwd launch must refuse configs whose resident
        set can't fit scoped VMEM (MQA-scale G falls back to the
        ungrouped kernel) and still produce correct output either way."""
        from paddle_tpu.kernels.flash_attention import (_grouped_bq,
                                                        _sdpa_reference,
                                                        flash_attention)
        # llama G=4 keeps full blocks; qwen G=7 shrinks; MQA G=32 refuses
        assert _grouped_bq(4, 2048, 128, 512, 512, jnp.bfloat16) == 512
        assert _grouped_bq(7, 2048, 128, 512, 512, jnp.bfloat16) == 256
        assert _grouped_bq(32, 2048, 128, 512, 512, jnp.bfloat16) is None
        # MQA parity through whatever path the gate picks (interpret)
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 64, 8, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(1, 64, 1, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(1, 64, 1, 16).astype(np.float32) * 0.3)
        out = flash_attention(q, k, v, True, True)
        ref = _sdpa_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

    def test_gqa_reference_matches_repeat(self):
        # grouped reference == naive repeat-KV reference
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        rng = np.random.RandomState(11)
        q = jnp.asarray(rng.randn(1, 32, 6, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        out = _sdpa_reference(q, k, v, True)
        kr = jnp.repeat(k, 3, axis=2)
        vr = jnp.repeat(v, 3, axis=2)
        ref = _sdpa_reference(q, kr, vr, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestFlashAttentionWithLse:
    """flash_attention_with_lse: the (out, lse) building block for
    blockwise/ring attention (VERDICT #4). The lse cotangent must fold
    into the FA2 backward via delta' = delta - g_lse."""

    def test_lse_matches_reference(self):
        from paddle_tpu.kernels.flash_attention import (
            _sdpa_reference_with_lse, flash_attention_with_lse)
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 128, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32) * 0.3)
        out, lse = flash_attention_with_lse(q, k, v, True, True)
        ref_out, ref_lse = _sdpa_reference_with_lse(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   atol=2e-3)

    def test_lse_cotangent_grads(self):
        from paddle_tpu.kernels.flash_attention import (
            _sdpa_reference_with_lse, flash_attention_with_lse)
        rng = np.random.RandomState(5)
        q = jnp.asarray(rng.randn(1, 128, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(1, 128, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(1, 128, 2, 16).astype(np.float32) * 0.3)
        wl = jnp.asarray(rng.randn(4, 1, 128).astype(np.float32))
        wo = jnp.asarray(rng.randn(1, 128, 4, 16).astype(np.float32))

        def loss(fn):
            def f(q, k, v):
                out, lse = fn(q, k, v)
                return jnp.sum(out * wo) + jnp.sum(lse * wl)
            return f

        g = jax.grad(loss(lambda q, k, v: flash_attention_with_lse(
            q, k, v, True, True)), argnums=(0, 1, 2))(q, k, v)
        r = jax.grad(loss(lambda q, k, v: _sdpa_reference_with_lse(
            q, k, v, True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3)


class TestChooseBlocksVmem:
    def test_stream_flag_tracks_budget(self):
        """VERDICT weak #7: _choose_blocks must be a real VMEM check, not
        unchecked arithmetic — long sequences flip to the streaming path."""
        import os
        from paddle_tpu.kernels.flash_attention import _choose_blocks
        bq, bk, stream = _choose_blocks(2048, 128, jnp.bfloat16)
        assert not stream
        bq, bk, stream = _choose_blocks(32768, 128, jnp.bfloat16)
        assert stream
        os.environ["PT_FLASH_VMEM_MB"] = "0.5"
        try:
            _, _, stream = _choose_blocks(2048, 128, jnp.bfloat16)
            assert stream
        finally:
            del os.environ["PT_FLASH_VMEM_MB"]


class TestRingAttentionBlockwise:
    def test_ring_parity_large_local_block(self):
        """Ring attention at local_S=1024 (2 shards) matches full
        attention — grads included (lse-combination path)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.sep import ring_attention
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        mesh = dist.ProcessMesh(shape=[1, 1, 2, 1, 1],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2048, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(1, 2048, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(1, 2048, 2, 16).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(1, 2048, 4, 16).astype(np.float32))

        def ring_loss(q, k, v):
            o = ring_attention(q, k, v, causal=True, mesh=mesh.jax_mesh)
            return jnp.sum(o * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, True) * w)

        lr, gr = jax.value_and_grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        lf, gf = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        assert abs(float(lr) - float(lf)) / abs(float(lf)) < 1e-4
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3)


class TestStreamingKernels:
    """The double-buffered DMA kernels must be exercised in CI (interpret
    mode executes pltpu.make_async_copy faithfully): force the stream
    path via the VMEM budget env and check fwd+grad parity."""

    def test_forced_stream_parity(self, monkeypatch):
        from paddle_tpu.kernels.flash_attention import (_choose_blocks,
                                                        _sdpa_reference,
                                                        flash_attention)
        monkeypatch.setenv("PT_FLASH_VMEM_MB", "0.01")
        assert _choose_blocks(128, 16, jnp.float32)[2]  # streaming on
        rng = np.random.RandomState(9)
        q = jnp.asarray(rng.randn(2, 128, 4, 16).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(2, 128, 4, 16).astype(np.float32))
        out = flash_attention(q, k, v, True, True)
        ref = _sdpa_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, True) * w)

        def ref_loss(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, True) * w)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        r = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3)


class TestGroupedBackward:
    """r5 (VERDICT r4 #3): the GQA-grouped launch extended to the
    BACKWARD kernels and to the streaming (long-context) regime — the
    explicit S<=8192 forward cap is gone, replaced by the VMEM budget."""

    def _data(self, S=256, H=4, Hkv=2, D=32, seed=5):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(1, S, H, D).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(1, S, Hkv, D).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(1, S, Hkv, D).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(1, S, H, D).astype(np.float32))
        return q, k, v, w

    def _grads(self, fn, q, k, v, w, causal):
        import inspect
        n = len(inspect.signature(fn).parameters)

        def loss(q, k, v):
            out = fn(q, k, v, causal, True) if n >= 5 \
                else fn(q, k, v, causal)
            return jnp.sum(out * w)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grouped_bwd_kernels_selected_and_match(self, causal,
                                                    monkeypatch):
        import paddle_tpu.kernels.flash_attention as fa
        used = []
        for name in ("_dq_kernel_grouped", "_dkv_kernel_grouped",
                     "_dq_kernel", "_dkv_kernel"):
            orig = getattr(fa, name)

            def wrap(orig=orig, name=name):
                def f(*a, **kw):
                    used.append(name)
                    return orig(*a, **kw)
                return f
            monkeypatch.setattr(fa, name, wrap())
        q, k, v, w = self._data()
        gq, gk, gv = self._grads(fa.flash_attention, q, k, v, w, causal)
        rq, rk, rv = self._grads(fa._sdpa_reference, q, k, v, w, causal)
        assert "_dq_kernel_grouped" in used and "_dq_kernel" not in used
        assert "_dkv_kernel_grouped" in used and "_dkv_kernel" not in used
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                                   atol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_streaming_grouped_fwd_bwd_parity(self, causal, monkeypatch):
        """Force the streaming regime: the grouped streaming fwd/dq/dkv
        kernels must be selected and bit-match the XLA reference within
        fp tolerance. The stream flag is forced directly (not via a tiny
        PT_FLASH_VMEM_MB) because the unified budget knob now also sizes
        the grouped tiles — a starvation budget would rightly disable
        grouping, which is not the regime under test."""
        import paddle_tpu.kernels.flash_attention as fa
        orig_choose = fa._choose_blocks
        monkeypatch.setattr(
            fa, "_choose_blocks",
            lambda s, d, t: orig_choose(s, d, t)[:2] + (True,))
        used = []
        for name in ("_fwd_kernel_stream_grouped", "_fwd_kernel_stream",
                     "_dq_kernel_stream_grouped", "_dq_kernel_stream",
                     "_dkv_kernel_stream_grouped", "_dkv_kernel_stream"):
            orig = getattr(fa, name)

            def wrap(orig=orig, name=name):
                def f(*a, **kw):
                    used.append(name)
                    return orig(*a, **kw)
                return f
            monkeypatch.setattr(fa, name, wrap())
        q, k, v, w = self._data()
        out = fa.flash_attention(q, k, v, causal, True)
        ref = fa._sdpa_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)
        gq, gk, gv = self._grads(fa.flash_attention, q, k, v, w, causal)
        rq, rk, rv = self._grads(fa._sdpa_reference, q, k, v, w, causal)
        assert "_fwd_kernel_stream_grouped" in used
        assert "_fwd_kernel_stream" not in used
        assert "_dq_kernel_stream_grouped" in used
        assert "_dq_kernel_stream" not in used
        assert "_dkv_kernel_stream_grouped" in used
        assert "_dkv_kernel_stream" not in used
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                                   atol=2e-3)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                                   atol=2e-3)

    def test_mqa_scale_group_falls_back_in_backward(self, monkeypatch):
        """A group too wide for the grouped budget (MQA-scale G) must
        fall back to the ungrouped backward kernels, not launch a
        program the budget says cannot fit."""
        import paddle_tpu.kernels.flash_attention as fa
        monkeypatch.setattr(fa, "_grouped_bq_dq",
                            lambda *a, **k: None)
        monkeypatch.setattr(fa, "_grouped_bq_dkv",
                            lambda *a, **k: None)
        used = []
        for name in ("_dq_kernel", "_dkv_kernel"):
            orig = getattr(fa, name)

            def wrap(orig=orig, name=name):
                def f(*a, **kw):
                    used.append(name)
                    return orig(*a, **kw)
                return f
            monkeypatch.setattr(fa, name, wrap())
        q, k, v, w = self._data()
        gq, gk, gv = self._grads(fa.flash_attention, q, k, v, w, True)
        rq, rk, rv = self._grads(fa._sdpa_reference, q, k, v, w, True)
        assert "_dq_kernel" in used and "_dkv_kernel" in used
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                                   atol=2e-3)

    def test_stream_gate_is_seq_free(self):
        """_grouped_bq_stream must admit arbitrarily long sequences (its
        resident set has no whole-seq K/V term) while _grouped_bq
        (non-stream) shrinks with S."""
        from paddle_tpu.kernels.flash_attention import (_grouped_bq,
                                                        _grouped_bq_stream)
        assert _grouped_bq_stream(2, 128, 512, 512,
                                  jnp.bfloat16) is not None
        # same result regardless of S (not an argument at all for fwd/dq)
        assert _grouped_bq_stream(4, 128, 512, 512, jnp.bfloat16) == \
            _grouped_bq_stream(4, 128, 512, 512, jnp.bfloat16)
        # non-stream grouped gate remains budget-bound in S
        big = _grouped_bq(4, 131072, 128, 512, 512, jnp.bfloat16)
        assert big is None
