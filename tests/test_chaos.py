"""Self-healing fleet under deterministic fault injection (ISSUE 9):
seeded FaultPlan schedules, the FaultInjector's fleet hooks
(worker_crash / worker_hang / alloc_oom / sink_fail), worker restart &
rejoin (manual + auto with capped backoff on an injected clock),
poison-request quarantine with innocent bystanders completing
bit-identical, total-outage parking with unpark-on-rejoin, and the
SLO-driven degradation ladder.

The determinism contract under test: chaos disabled (the default
``fleet.chaos is None``) OR an installed injector with an EMPTY plan
leaves fleet outputs bit-identical to the r13 seed behaviour, and the
whole fault machinery runs on the fleet STEP INDEX plus injected
clocks — no wall time anywhere (see test_no_adhoc_timers)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.chaos import (FAULT_KINDS, ChaosPoisonError,
                                        FaultEvent, FaultInjector,
                                        FaultPlan)
from paddle_tpu.inference.fleet import (NoHealthyWorkersError,
                                        RequestPoisonedError,
                                        RestartPolicy, ServingFleet)

ENGINE_KW = dict(capacity=2, s_max=64, chunk=4, block_size=8)


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


def _out(req, timeout=60):
    return np.asarray(req.wait(timeout=timeout)).reshape(-1)


class TestFaultPlan:
    def test_seeded_schedule_is_deterministic(self):
        a = FaultPlan.random(7, 200, ["w0", "w1"], rate=0.1)
        b = FaultPlan.random(7, 200, ["w0", "w1"], rate=0.1)
        assert len(a) > 0
        assert a.signature() == b.signature()
        c = FaultPlan.random(8, 200, ["w0", "w1"], rate=0.1)
        assert c.signature() != a.signature()

    def test_event_validation(self):
        with pytest.raises(ValueError, match="kind"):
            FaultEvent(0, "meteor_strike")
        with pytest.raises(ValueError):
            FaultEvent(-1, "worker_crash")
        with pytest.raises(ValueError):
            FaultEvent(0, "worker_hang", duration=0)
        assert set(FAULT_KINDS) == {"worker_crash", "worker_hang",
                                    "slow_step", "alloc_oom",
                                    "sink_fail", "migration_fail"}
        # FaultPlan.random's DEFAULT draw set stays the r14 five: a
        # wider uniform draw would reshuffle every seeded plan and
        # break the chaos preset's pinned replay signatures (r19)
        from paddle_tpu.inference.chaos import RANDOM_KINDS
        assert RANDOM_KINDS == ("worker_crash", "worker_hang",
                                "slow_step", "alloc_oom", "sink_fail")

    def test_events_sorted_and_indexed_by_step(self):
        plan = FaultPlan([FaultEvent(5, "worker_hang", "w0"),
                          FaultEvent(2, "worker_crash", "w1")])
        assert [e.step for e in plan.events] == [2, 5]
        assert [e.kind for e in plan.at(5)] == ["worker_hang"]
        assert plan.at(3) == []


class TestChaosDisabledBitIdentical:
    def test_default_and_empty_plan_leave_outputs_bit_identical(self):
        """The r13 regression: a fleet without chaos (the default) and
        one with an installed injector whose plan is EMPTY must produce
        byte-for-byte the same tokens — and both must match the
        single-engine oracle."""
        m = _model()
        rng = np.random.RandomState(11)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 11)]

        def run(install_empty):
            fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                                 engine_kwargs=ENGINE_KW)
            if install_empty:
                inj = FaultInjector(FaultPlan([])).install(fleet)
                assert fleet.chaos is inj
            else:
                assert fleet.chaos is None
            reqs = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            fleet.run_until_drained()
            outs = [_out(r) for r in reqs]
            fired = fleet.chaos.fired if fleet.chaos is not None else []
            fleet.close()
            return outs, fired

        base, _ = run(False)
        empty, fired = run(True)
        assert fired == []
        for a, b, p in zip(base, empty, prompts):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, _solo(m, p, 6).reshape(-1))


class TestInjectedFaults:
    def test_worker_crash_fails_over_and_auto_restarts(self):
        """ISSUE 9 acceptance: capacity provably returns to N within
        the backoff bound, the prefix directory re-registers the
        rejoined worker, and every request still completes
        bit-identical to the solo oracle."""
        m = _model()
        rng = np.random.RandomState(4)
        vt = [0.0]
        fleet = ServingFleet(
            m, n_workers=2, policy="round_robin", engine_kwargs=ENGINE_KW,
            restart=RestartPolicy(auto=True, backoff_base_s=1.0,
                                  clock=lambda: vt[0]))
        inj = FaultInjector(
            FaultPlan([FaultEvent(1, "worker_crash", "w1")])).install(fleet)
        reqs, expect = [], []
        for _ in range(4):
            p = rng.randint(1, 128, (10,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=12))
            expect.append(_solo(m, p, 12))
        fleet.step()                    # step 0: both workers admit
        vt[0] += 0.25
        fleet.step()                    # step 1: w1 crashes mid-step
        assert not fleet.workers[1].healthy
        assert fleet.stats()["failovers"] == 1
        # backoff bound: first restart is backoff_s(0) = 1.0s after the
        # drain is observed — at 0.25s/step that is <= 6 steps away
        steps = 0
        while not fleet.workers[1].healthy:
            vt[0] += 0.25
            fleet.step()
            steps += 1
            assert steps <= 6, "restart missed the backoff bound"
        st = fleet.stats()
        assert st["healthy_workers"] == 2
        assert st["restarts"] == 1
        assert fleet.workers[1].restarts == 1
        # rejoin re-registered the directory listener under the same wid
        assert "w1" in fleet.directory.stats()
        fleet.run_until_drained()
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(_out(r), e.reshape(-1))
        assert inj.fired == [(1, "worker_crash", "w1")]
        # probation burns down one healthy step at a time (the drain may
        # finish first — idle steps burn it too)
        fleet.step()
        fleet.step()
        assert fleet.workers[1].probation == 0
        fleet.close()

    def test_worker_hang_freezes_heartbeat_until_watchdog_fires(self):
        """A hang is NOT a crash: the worker raises nothing, its
        device-steps heartbeat just stops. The stall watchdog is the
        component that must notice — same detection path as a real
        wedged device loop."""
        m = _model()
        rng = np.random.RandomState(5)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             stall_s=5.0, engine_kwargs=ENGINE_KW)
        inj = FaultInjector(FaultPlan(
            [FaultEvent(1, "worker_hang", "w0", duration=1000)]))
        inj.install(fleet)
        reqs, expect = [], []
        for _ in range(2):
            p = rng.randint(1, 128, (8,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=10))
            expect.append(_solo(m, p, 10))
        fleet.step()                            # step 0: both decode
        assert fleet.check_watchdogs(now=50.0) == []    # baseline
        fleet.step()                            # step 1: w0 hung
        assert inj.suppress_step(fleet.workers[0])
        fired = fleet.check_watchdogs(now=56.0)         # > stall_s
        assert [wid for wid, _ in fired] == ["w0"]
        assert not fleet.workers[0].healthy
        assert fleet.workers[0].fail_reason == "stall"
        fleet.run_until_drained()               # survivor drains all
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(_out(r), e.reshape(-1))
        assert fleet.stats()["failovers"] == 1
        # a stall says nothing about WHICH request is poison: no blame
        assert all(getattr(r, "retry_count", 0) == 0 for r in reqs)
        fleet.close()

    def test_alloc_oom_surfaces_as_step_fault(self):
        """An injected allocator OOM raises out of ``admit`` inside the
        worker step — the fleet must treat it exactly like any other
        raising step (fail the WORKER, re-route, finish elsewhere)."""
        m = _model()
        rng = np.random.RandomState(6)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        FaultInjector(FaultPlan(
            [FaultEvent(0, "alloc_oom", "w0")])).install(fleet)
        p = rng.randint(1, 128, (10,)).astype(np.int32)
        req = fleet.submit(p, max_new_tokens=8)     # round-robin -> w0
        expect = _solo(m, p, 8)
        fleet.run_until_drained()
        np.testing.assert_array_equal(_out(req), expect.reshape(-1))
        assert not fleet.workers[0].healthy
        assert fleet.workers[0].fail_reason == "drained"
        assert fleet.stats()["failovers"] == 1
        fleet.close()

    def test_sink_fail_window_then_delivery_resumes(self):
        """During the window every sink emit raises (counted, payloads
        retained under backoff); after the window expires the original
        sink is restored and the queue drains."""

        class _ListSink:
            def __init__(self):
                self.payloads = []

            def emit(self, payload):
                self.payloads.append(payload)

        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        rec = _ListSink()
        fleet.enable_shipper([rec], interval_s=1e9)
        FaultInjector(FaultPlan(
            [FaultEvent(1, "sink_fail", duration=2)])).install(fleet)
        fleet.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
        fleet.step()                    # step 0: first tick flushes
        n0 = len(rec.payloads)
        assert n0 >= 1
        fleet.step()                    # step 1: sinks wrapped
        fleet.shipper.enqueue({"probe": 1})
        assert fleet.shipper.flush(now_=1000.0) == 0
        assert fleet.shipper.stats()["sink_errors"] >= 1
        assert len(rec.payloads) == n0          # nothing leaked through
        fleet.step()                    # step 2: window still open
        fleet.step()                    # step 3: sink restored
        assert fleet.shipper.flush(now_=2000.0) >= 1    # past backoff
        assert any("probe" in p for p in rec.payloads)
        fleet.run_until_drained()
        fleet.close()


class TestRestartAndRejoin:
    def test_restart_worker_rebuilds_and_directory_repopulates(self):
        m = _model()
        rng = np.random.RandomState(7)
        fleet = ServingFleet(m, n_workers=2, policy="affinity",
                             engine_kwargs=ENGINE_KW)
        p = rng.randint(1, 128, (16,)).astype(np.int32)
        req = fleet.submit(p, max_new_tokens=4)
        fleet.run_until_drained()
        req.wait(timeout=60)
        stats = fleet.directory.stats()
        owner = max(stats, key=lambda w: stats[w])
        assert stats[owner] > 0         # retire published the prefix
        old_engine = next(w.engine for w in fleet.workers
                          if w.wid == owner)
        fleet.kill_worker(owner)
        assert owner not in fleet.directory.stats()     # index wiped
        n = fleet.restart_worker(owner)
        assert n == 1
        w = next(x for x in fleet.workers if x.wid == owner)
        assert w.healthy and w.engine is not old_engine
        assert fleet.stats()["healthy_workers"] == 2
        assert fleet.directory.stats()[owner] == 0      # re-registered
        assert w.probation == 2
        # the same prefix republished through the NEW cache shows up in
        # the directory again — the listener really was re-wired
        tail = rng.randint(1, 128, (4,)).astype(np.int32)
        req2 = fleet.submit(np.concatenate([p, tail]), max_new_tokens=4)
        fleet.run_until_drained()
        req2.wait(timeout=60)
        assert sum(fleet.directory.stats().values()) > 0
        fleet.close()

    def test_restart_rejects_healthy_and_unknown_workers(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        with pytest.raises(RuntimeError, match="healthy"):
            fleet.restart_worker("w0")
        with pytest.raises(ValueError, match="unknown worker"):
            fleet.restart_worker("w99")
        fleet.close()

    def test_probation_excludes_rejoined_worker_from_routing(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        fleet.kill_worker("w1")
        fleet.restart_worker("w1")
        w1 = fleet.workers[1]
        assert w1.probation == 2
        for _ in range(3):
            fleet.submit(np.arange(1, 9, dtype=np.int32),
                         max_new_tokens=2)
        # warm-up window: the router skips the rejoined worker
        assert len(fleet.workers[0].pending) == 3
        assert len(w1.pending) == 0
        fleet.run_until_drained()
        assert w1.probation == 0        # burned down by healthy steps
        fleet.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=2)
        fleet.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=2)
        assert [len(w.pending) for w in fleet.workers] == [1, 1]
        fleet.run_until_drained()
        fleet.close()

    def test_counters_survive_restart(self):
        """Fleet-level totals must NOT reset when a worker's registry
        is replaced on restart (the chaos bench caught exactly this:
        every worker restarted during the run and the final snapshot
        claimed zero retires). The dead incarnation's counters fold
        into the merge; its gauges die with it."""
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        req = fleet.submit(np.arange(1, 9, dtype=np.int32),
                           max_new_tokens=4)
        fleet.run_until_drained()
        req.wait(timeout=60)
        before = fleet.merged_snapshot()["counters"]["engine_retired_total"]
        assert before >= 1
        for wid in ("w0", "w1"):
            fleet.kill_worker(wid)
            fleet.restart_worker(wid)
        snap = fleet.merged_snapshot()
        assert snap["counters"]["engine_retired_total"] == before
        agg = fleet.aggregator().snapshot()
        assert agg["fleet"]["counters"]["engine_retired_total"] == before
        # gauges come only from the LIVE incarnations — no double count
        live = sum(w.registry.snapshot()["gauges"].get(
            "engine_backlog", 0.0) for w in fleet.workers)
        assert snap["gauges"]["engine_backlog"] == live
        fleet.close()

    def test_max_restarts_caps_flapping(self):
        m = _model()
        vt = [0.0]
        fleet = ServingFleet(
            m, n_workers=2, engine_kwargs=ENGINE_KW,
            restart=RestartPolicy(auto=True, backoff_base_s=0.0,
                                  max_restarts=1, clock=lambda: vt[0]))
        fleet.kill_worker("w0")
        fleet.step()                    # schedules restart_at
        vt[0] += 1.0
        fleet.step()                    # restart #1
        assert fleet.workers[0].healthy
        fleet.kill_worker("w0")
        for _ in range(3):
            vt[0] += 1.0
            fleet.step()
        assert not fleet.workers[0].healthy     # cap: stays dead
        assert fleet.workers[0].restarts == 1
        assert fleet.stats()["restarts"] == 1
        fleet.close()

    def test_backoff_is_capped_exponential(self):
        pol = RestartPolicy(backoff_base_s=0.5, backoff_max_s=4.0)
        assert [pol.backoff_s(n) for n in range(5)] == \
            [0.5, 1.0, 2.0, 4.0, 4.0]


class TestPoisonQuarantine:
    def test_poison_cascade_is_quarantined_and_innocents_bitmatch(self):
        """ISSUE 9 acceptance: one request that crashes every worker it
        is admitted on must end with RequestPoisonedError after
        max_retries re-routes — with ALL workers healthy again (auto
        restart) and every innocent request's output bit-identical to
        the fault-free oracle."""
        m = _model()
        rng = np.random.RandomState(9)
        fleet = ServingFleet(
            m, n_workers=3, policy="round_robin", engine_kwargs=ENGINE_KW,
            restart=RestartPolicy(auto=True, backoff_base_s=0.0))
        # empty plan + poison token: the only faults are the ones the
        # poison request itself causes
        FaultInjector(FaultPlan([]), poison_token=120).install(fleet)
        innocents, expect = [], []
        for _ in range(4):
            p = rng.randint(1, 100, (10,)).astype(np.int32)    # no 120
            innocents.append(fleet.submit(p, max_new_tokens=10))
            expect.append(_solo(m, p, 10))
        # long enough that the poison can never RETIRE within one step
        # of a re-admission (the crash fires at the NEXT step's chaos
        # check, so a request finishing in its admission step would
        # escape the third attribution)
        poison = fleet.submit(np.array([5, 120, 7, 8], dtype=np.int32),
                              max_new_tokens=40)
        fleet.run_until_drained(max_steps=500)
        with pytest.raises(RequestPoisonedError, match="quarantined"):
            poison.wait(timeout=60)
        # the trace tells the whole story
        tr = poison.trace
        assert tr.attrs["poison_reason"]
        assert tr.count("quarantined") == 1
        assert tr.count("retry") == poison.retry_count == 3
        assert tr.summary()["poison_reason"] is not None
        assert tr.summary()["retries"] == 3
        for r, e in zip(innocents, expect):
            assert getattr(r, "retry_count", 0) <= fleet.max_retries
            np.testing.assert_array_equal(_out(r), e.reshape(-1))
        # the drain ends once the work does — a victim crashed on the
        # final step still has its (zero-backoff) restart pending; a
        # few idle steps let the fleet finish healing
        steps = 0
        while fleet.stats()["healthy_workers"] < 3:
            fleet.step()
            steps += 1
            assert steps < 10
        st = fleet.stats()
        assert st["poisoned"] == 1
        assert st["healthy_workers"] == 3       # every victim restarted
        assert st["restarts"] >= 1
        fleet.close()

    def test_total_outage_parks_then_unparks_on_rejoin(self):
        """Zero healthy workers mid-failover: requests PARK (step never
        raises), submit raises the typed error, and the auto-restarted
        worker unparks everything with a ``restarted`` hop."""
        m = _model()
        rng = np.random.RandomState(10)
        vt = [0.0]
        fleet = ServingFleet(
            m, n_workers=1, engine_kwargs=ENGINE_KW,
            restart=RestartPolicy(auto=True, backoff_base_s=1.0,
                                  clock=lambda: vt[0]))
        FaultInjector(FaultPlan(
            [FaultEvent(1, "worker_crash", "w0")])).install(fleet)
        reqs, expect = [], []
        for _ in range(2):
            p = rng.randint(1, 128, (8,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=8))
            expect.append(_solo(m, p, 8))
        fleet.step()                    # step 0: admit
        fleet.step()                    # step 1: crash -> nowhere to go
        assert fleet.stats()["healthy_workers"] == 0
        assert fleet.stats()["parked"] == 2
        with pytest.raises(NoHealthyWorkersError):
            fleet.submit(np.arange(1, 5, dtype=np.int32))
        assert fleet.pending_work() >= 2        # parked is still work
        steps = 0
        while fleet.pending_work():
            vt[0] += 0.5
            fleet.step()
            steps += 1
            assert steps < 60
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(_out(r), e.reshape(-1))
        assert any(h["reason"] == "restarted"
                   for r in reqs for h in r.trace.hops)
        st = fleet.stats()
        assert st["parked"] == 0
        assert st["restarts"] == 1
        fleet.close()


class TestDegradationLadder:
    def test_knob_transitions_and_full_restore(self):
        m = _model()
        kw = dict(ENGINE_KW, spec_decode=True, step_budget=16)
        fleet = ServingFleet(m, n_workers=2, engine_kwargs=kw)
        fleet.enable_slo()              # default boost 4.0
        base_lp = fleet.load_penalty
        e0 = fleet.workers[0].engine
        gauge = fleet.metrics.get("fleet_degradation_level")
        assert gauge.value == 0
        fleet._set_degradation(1)
        assert gauge.value == 1
        assert fleet.load_penalty == base_lp * 4.0
        assert e0.spec_decode is True and e0.step_budget == 16
        fleet._set_degradation(2)
        assert e0.spec_decode is False and e0.step_budget == 16
        fleet._set_degradation(3)
        assert e0.spec_decode is False
        assert e0.step_budget == 8      # halved, still >= chunk
        fleet._set_degradation(0)       # fully restored on resolve
        assert gauge.value == 0
        assert fleet.load_penalty == base_lp
        assert e0.spec_decode is True and e0.step_budget == 16
        assert fleet.workers[0].deg_saved is None
        fleet.close()

    def test_budget_never_halves_below_chunk(self):
        m = _model()
        kw = dict(ENGINE_KW, spec_decode=True, step_budget=6)
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=kw)
        fleet.enable_slo()
        fleet._set_degradation(3)
        assert fleet.workers[0].engine.step_budget == 4     # == chunk
        fleet._set_degradation(0)
        assert fleet.workers[0].engine.step_budget == 6
        fleet.close()

    def test_restarted_worker_joins_at_current_brownout_level(self):
        m = _model()
        kw = dict(ENGINE_KW, spec_decode=True, step_budget=16)
        fleet = ServingFleet(m, n_workers=2, engine_kwargs=kw)
        fleet.enable_slo()
        fleet._set_degradation(2)
        fleet.kill_worker("w1")
        fleet.restart_worker("w1")
        e1 = fleet.workers[1].engine
        assert e1.spec_decode is False  # rejoined INTO the brownout
        fleet._set_degradation(0)
        assert e1.spec_decode is True
        fleet.close()

    def test_check_slo_escalates_then_restores(self):
        """The closed loop: a firing backlog alert climbs the ladder one
        level per evaluation; the first clean evaluation restores every
        knob."""
        from paddle_tpu.observability import SLORule
        m = _model()
        kw = dict(ENGINE_KW, spec_decode=True, step_budget=16)
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=kw)
        fleet.enable_slo(rules=[SLORule(
            "backlog", "engine_backlog", "value", threshold=0.5,
            op="<", window_s=60.0, for_s=0.5, clear_for_s=1.0)])
        for _ in range(6):              # capacity 2: deep backlog
            fleet.submit(np.arange(1, 9, dtype=np.int32),
                         max_new_tokens=4)
        fleet.step()
        assert fleet.merged_snapshot()["gauges"]["engine_backlog"] > 0.5
        fleet.check_slo(now=0.0)        # breach -> pending
        assert fleet._degradation == 0
        fleet.check_slo(now=1.0)        # for_s held -> firing
        assert fleet._degradation == 1
        fleet.check_slo(now=2.0)
        assert fleet._degradation == 2
        assert fleet.workers[0].engine.spec_decode is False
        fleet.check_slo(now=3.0)
        assert fleet._degradation == 3
        assert fleet.workers[0].engine.step_budget == 8
        fleet.check_slo(now=4.0)
        assert fleet._degradation == 3  # capped
        fleet.run_until_drained()       # backlog clears
        fleet.check_slo(now=10.0)       # clear hysteresis starts
        fleet.check_slo(now=20.0)       # resolved -> restore
        assert fleet._degradation == 0
        assert fleet.workers[0].engine.spec_decode is True
        assert fleet.workers[0].engine.step_budget == 16
        fleet.close()


class TestSatellites:
    def test_no_healthy_workers_error_is_typed(self):
        assert issubclass(NoHealthyWorkersError, RuntimeError)
        assert issubclass(RequestPoisonedError, RuntimeError)
        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        fleet.workers[0].healthy = False
        with pytest.raises(NoHealthyWorkersError, match="no healthy"):
            fleet.submit(np.arange(1, 5, dtype=np.int32))
        fleet.close()

    def test_shipper_close_flushes_and_counts_drops(self):
        from paddle_tpu.observability import TelemetryShipper

        class _ListSink:
            def __init__(self):
                self.payloads = []

            def emit(self, payload):
                self.payloads.append(payload)

        class _BoomSink:
            def __init__(self):
                self.calls = 0

            def emit(self, payload):
                self.calls += 1
                raise OSError("dead sink")

        good, bad = _ListSink(), _BoomSink()
        sh = TelemetryShipper(sinks=[good, bad], interval_s=1e9)
        for i in range(3):
            sh.enqueue({"i": i})
        assert good.payloads == []      # nothing flushed yet
        counts = sh.close()
        assert [p["i"] for p in good.payloads] == [0, 1, 2]
        assert counts["flushed"] == 3
        assert counts["dropped"] == 3   # the dead sink's whole queue
        assert bad.calls == 1           # abandoned at first failure
        assert sh.stats()["shipped"] == 3
        assert sh.stats()["dropped"] == 3

    def test_fleet_close_runs_final_flush(self):
        class _ListSink:
            def __init__(self):
                self.payloads = []

            def emit(self, payload):
                self.payloads.append(payload)

        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        rec = _ListSink()
        fleet.enable_shipper([rec], interval_s=1e9)
        req = fleet.submit(np.arange(1, 9, dtype=np.int32),
                           max_new_tokens=2)
        fleet.run_until_drained()
        req.wait(timeout=60)
        fleet.shipper.enqueue({"final": True})
        fleet.close()
        assert any(p.get("final") for p in rec.payloads)

    def test_run_until_drained_reports_stuck_work(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        fleet.submit(np.arange(1, 9, dtype=np.int32),
                     max_new_tokens=4, tenant="acme")
        fleet.kill_worker("w0")         # parks it; no restart policy
        with pytest.raises(RuntimeError) as ei:
            fleet.run_until_drained(max_steps=3)
        msg = str(ei.value)
        assert "stuck work" in msg
        assert "tenant='acme'" in msg
        assert "parked" in msg
        assert "state=" in msg
        fleet.close()

    def test_lifecycle_states_extended_in_order(self):
        from paddle_tpu.observability.tracing import LIFECYCLE_STATES
        i = LIFECYCLE_STATES.index
        assert i("preempted") < i("retry") < i("quarantined") \
            < i("retired") < i("failed")

    def test_summary_appends_new_keys_after_r11(self):
        """Shape-compat: consumers indexing the r11 summary keys
        positionally must be unaffected — the ISSUE 9 keys come LAST."""
        from paddle_tpu.observability import RequestTrace
        tr = RequestTrace(t=0.0)
        keys = list(tr.summary().keys())
        r11 = ["request_id", "state", "ttft_s", "queue_wait_s",
               "preemptions", "decode_chunks", "served_tokens",
               "events", "trace_id", "worker_id", "hops", "attrs",
               "tenant"]
        assert keys[:len(r11)] == r11
        assert keys[len(r11):] == ["retries", "poison_reason"]
        tr.mark("retry")
        tr.mark("retry")
        assert tr.summary()["retries"] == 2
        assert tr.summary()["poison_reason"] is None

    def test_new_counters_and_gauge_registered(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        for name in ("fleet_restarts_total", "fleet_poisoned_total",
                     "fleet_degradation_level"):
            assert fleet.metrics.get(name) is not None
        text = fleet.aggregator().prometheus_text()
        assert "fleet_restarts_total" in text
        assert "fleet_poisoned_total" in text
        assert "fleet_degradation_level" in text
        st = fleet.stats()
        for key in ("restarts", "poisoned", "parked", "degradation"):
            assert key in st
        fleet.close()


class TestPostmortemBundles:
    """ISSUE 13: every injected crash leaves a postmortem bundle whose
    flight ring shows the fault next to the failover it provoked, and
    the whole observability stack (profiler + recorders + bundles)
    never perturbs the token stream."""

    def test_bundle_per_crash_with_bit_identical_outputs(self, tmp_path):
        m = _model()
        rng = np.random.RandomState(23)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 11, 6, 9)]
        plan = FaultPlan([FaultEvent(1, "worker_crash", worker="w1"),
                          FaultEvent(2, "worker_crash", worker="w2")])

        def run(with_chaos, pdir=None):
            fleet = ServingFleet(
                m, n_workers=3, policy="round_robin",
                engine_kwargs=ENGINE_KW, profile=with_chaos,
                postmortem_dir=pdir)
            inj = None
            if with_chaos:
                inj = FaultInjector(plan).install(fleet)
            reqs = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            fleet.run_until_drained()
            outs = [_out(r) for r in reqs]
            faults = fleet.flight.events(kind="fault")
            fleet.close()
            return outs, inj, faults

        base, _, _ = run(False)
        pdir = tmp_path / "bundles"
        outs, inj, faults = run(True, pdir=str(pdir))
        # bit-parity: failover is recompute-resume, the profiler and
        # bundle dumping are pure observers
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)
        # the flight ring's fault events ARE the plan signature
        assert [(e["step"], e["fault"], e["worker"], e["duration"],
                 e["magnitude"]) for e in faults] == plan.signature()
        assert [(s, k, w) for s, k, w in inj.fired] == \
            [(e.step, e.kind, e.worker) for e in plan.events]
        bundles = sorted(p.name for p in pdir.iterdir()
                         if p.name.startswith("postmortem_"))
        crash_bundles = [b for b in bundles if "failover" in b]
        assert len(crash_bundles) == len(plan)
        import json
        doc = json.loads((pdir / crash_bundles[0]).read_text())
        assert doc["bundle_version"] == 1
        assert doc["reason"].startswith("failover:w1")
        kinds = [e["kind"] for e in doc["flight"]["events"]]
        assert "fault" in kinds and "failover" in kinds
        assert kinds.index("fault") < kinds.index("failover")
        # the bundle carries the observatory: compile log + state
        assert any(e["program"] for e in doc["compile_log"])
        assert set(doc["state"]["workers"]) == {"w0", "w1", "w2"}

    def test_stall_dumps_bundle(self, tmp_path):
        """A tripped stall watchdog triggers a bundle BEFORE the fleet
        harvests the worker (reason ``stall:<wid>``)."""
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             stall_s=1.0, engine_kwargs=ENGINE_KW,
                             postmortem_dir=str(tmp_path))
        plan = FaultPlan([FaultEvent(1, "worker_hang", worker="w0",
                                     duration=50)])
        FaultInjector(plan).install(fleet)
        rng = np.random.RandomState(5)
        reqs = [fleet.submit(rng.randint(1, 128, (7,)).astype(np.int32),
                             max_new_tokens=4) for _ in range(3)]
        t = 0.0
        for _ in range(6):
            fleet.step()
            t += 0.5
            fleet.check_watchdogs(now=t)
        fleet.run_until_drained()
        for r in reqs:
            _out(r)
        fleet.close()
        names = sorted(p.name for p in tmp_path.iterdir())
        assert any("stall-w0" in n for n in names)
        stalls = fleet.flight.events(kind="stall")
        assert stalls and stalls[0]["src"] == "w0"


class TestBundleDeterminism:
    """Two recorders driven by the same scripted clock and events must
    dump byte-identical bundles — the postmortem format carries no
    hidden wall-clock state (sorted keys, injected clocks only)."""

    @staticmethod
    def _scripted(tmpdir):
        from paddle_tpu.observability import (FlightRecorder,
                                              dump_postmortem)
        t = [0.0]

        def clock():
            t[0] += 0.125
            return t[0]

        rec = FlightRecorder(capacity=16, clock=clock, name="w0")
        rec.record("fault", step=3, fault="worker_crash", worker="w0")
        rec.record("failover", worker="w0", rerouted=2, parked=0)
        path = dump_postmortem(
            str(tmpdir), reason="failover:w0", recorder=rec,
            registry={"counters": {"fleet_failovers_total": 1.0},
                      "gauges": {}, "histograms": {}},
            traces=[{"request_id": "r1", "terminal": "retired"}],
            compile_log=[{"program": "decode_chunk", "bucket_key": 4,
                          "wall_s": 0.5, "post_warmup": False}],
            config={"n_workers": 2}, state={"degradation": 0})
        assert path is not None
        return path

    def test_same_script_same_bytes(self, tmp_path):
        a = self._scripted(tmp_path / "a")
        b = self._scripted(tmp_path / "b")
        import pathlib
        pa, pb = pathlib.Path(a), pathlib.Path(b)
        assert pa.name == pb.name
        assert pa.read_bytes() == pb.read_bytes()

    def test_keep_prunes_oldest(self, tmp_path):
        from paddle_tpu.observability import (FlightRecorder,
                                              dump_postmortem)
        rec = FlightRecorder(capacity=4, clock=lambda: 1.0)
        for i in range(5):
            dump_postmortem(str(tmp_path), reason=f"r{i}",
                            recorder=rec, keep=3)
        names = sorted(p.name for p in tmp_path.iterdir())
        assert len(names) == 3
        assert names[-1].endswith("_r4.json")


class TestProfiledFleetBitIdentical:
    """ISSUE 13 acceptance: ``profile=True`` (step profiler + compile
    tracker + always-on flight ring) must leave fleet outputs
    byte-identical to the unprofiled default."""

    def test_profile_on_off_same_tokens(self):
        m = _model()
        rng = np.random.RandomState(17)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (5, 12, 9)]

        def run(profile):
            fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                                 engine_kwargs=ENGINE_KW,
                                 profile=profile)
            reqs = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            fleet.run_until_drained()
            outs = [_out(r) for r in reqs]
            fleet.close()
            return outs, fleet

        base, fleet_off = run(False)
        prof, fleet_on = run(True)
        for a, b in zip(base, prof):
            np.testing.assert_array_equal(a, b)
        # off: engines carry no instruments at all
        assert all(w.engine.profile is None and w.engine.compiles is None
                   for w in fleet_off.workers)
        # on: every worker profiled, phases populated, compiles seen
        s = fleet_on.workers[0].engine.profile.summary()
        assert s["steps"] > 0 and "launch" in s["phases"]
        assert fleet_on.workers[0].engine.compiles.stats()["compiles"] > 0
        assert fleet_on.mark_warm() == 2


class TestMigrationFault:
    """ISSUE 14: ``migration_fail`` kills transplants touching the
    faulted worker for the window. A dead transplant must fail BEFORE
    any pages move, and the fleet must fall back to a cold prefill on
    the routed worker — one slower request, never a wrong one."""

    def test_dead_transplant_cold_prefills(self):
        m = _model()
        rng = np.random.RandomState(21)
        A = rng.randint(1, 128, (24,)).astype(np.int32)
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             migration_budget_pages=8,
                             load_penalty=100.0)
        plan = FaultPlan([FaultEvent(0, "migration_fail", "w0",
                                     duration=10**6)])
        FaultInjector(plan).install(fleet)
        r1 = fleet.submit(A, max_new_tokens=8)
        fleet.run_until_drained()
        out1 = _out(r1)
        # pile load on the cached worker so the route would migrate
        for n in (16, 16, 16):
            fleet.submit(rng.randint(1, 128, (n,)).astype(np.int32),
                         max_new_tokens=4)
        r2 = fleet.submit(A, max_new_tokens=8)
        st = fleet.stats()
        assert st["migrations"] == 0       # transplant died, no pages
        fails = [e for e in fleet.flight.snapshot()["events"]
                 if e.get("kind") == "kv_migration_failed"]
        assert fails and fails[0]["error"] == "ChaosMigrationError"
        fleet.run_until_drained()
        np.testing.assert_array_equal(out1, _out(r2))  # cold, correct
        np.testing.assert_array_equal(out1, _solo(m, A, 8).reshape(-1))
        for w in fleet.workers:
            assert w.engine._alloc.conservation_ok
        fleet.close()

    def test_dead_handoff_keeps_row_on_prefill_worker(self):
        """Role-split under a permanent migration_fail window: every
        handoff dies, rows decode to completion on the prefill worker,
        outputs still match the oracle."""
        m = _model()
        rng = np.random.RandomState(22)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (24, 14)]
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             roles=("prefill", "decode"))
        plan = FaultPlan([FaultEvent(0, "migration_fail", "w1",
                                     duration=10**6)])
        FaultInjector(plan).install(fleet)
        reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
        fleet.run_until_drained()
        assert fleet.stats()["migrations"] == 0
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                _out(r), _solo(m, p, 8).reshape(-1))
        for w in fleet.workers:
            assert w.engine._alloc.conservation_ok
        fleet.close()
