"""Serving fleet (ISSUE 4): GlobalPrefixDirectory indexing and cache
wiring, prefix-affinity vs round-robin routing, failover (killed
worker, raising step, watchdog stall) with bit-identical completion on
survivors, worker_id threading, and the cross-worker metrics
aggregator + stdlib scrape endpoint."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.fleet import GlobalPrefixDirectory, ServingFleet
from paddle_tpu.inference.fleet_metrics import (MetricsAggregator,
                                                MetricsHTTPServer)
from paddle_tpu.observability import MetricsRegistry

ENGINE_KW = dict(capacity=2, s_max=64, chunk=4, block_size=8)


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


class TestGlobalPrefixDirectory:
    def test_full_blocks_only(self):
        d = GlobalPrefixDirectory(4)
        d.on_insert("w0", list(range(10)))      # 2 full blocks + tail 2
        assert d.cached_tokens("w0", list(range(10))) == 8
        assert d.cached_tokens("w0", list(range(4))) == 4
        assert d.cached_tokens("w0", [9, 9, 9, 9]) == 0
        assert d.cached_tokens("w1", list(range(10))) == 0

    def test_partial_insert_not_indexed(self):
        d = GlobalPrefixDirectory(4)
        d.on_insert("w0", [1, 2, 3])            # sub-block: no signal
        assert d.cached_tokens("w0", [1, 2, 3, 4]) == 0
        assert d.stats() == {"w0": 0}

    def test_evict_removes_deepest_only(self):
        d = GlobalPrefixDirectory(4)
        d.on_insert("w0", list(range(12)))      # chain depth 3
        d.on_evict("w0", list(range(12)))       # victim = deepest node
        assert d.cached_tokens("w0", list(range(12))) == 8
        d.on_evict("w0", list(range(8)))
        assert d.cached_tokens("w0", list(range(12))) == 4

    def test_partial_leaf_evict_is_noop(self):
        d = GlobalPrefixDirectory(4)
        d.on_insert("w0", list(range(8)))
        d.on_evict("w0", list(range(7)))        # partial path: ignored
        assert d.cached_tokens("w0", list(range(8))) == 8

    def test_drop_worker_wipes(self):
        d = GlobalPrefixDirectory(4)
        d.on_insert("w0", list(range(8)))
        d.on_insert("w1", list(range(8)))
        d.drop_worker("w0")
        assert d.cached_tokens("w0", list(range(8))) == 0
        assert d.cached_tokens("w1", list(range(8))) == 8

    def test_wired_through_prefix_cache(self):
        """The listener hook on PrefixCache keeps the directory in sync
        with real insert/evict traffic, including the cascading evict's
        per-node notifications."""
        from paddle_tpu.inference.paged_cache import BlockAllocator
        from paddle_tpu.inference.prefix_cache import PrefixCache
        d = GlobalPrefixDirectory(4)
        a = BlockAllocator(9)
        c = PrefixCache(a, 4, listener=d.listener("w0"))
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        pages = a.allocate(2)
        c.insert(toks, pages)
        for p in pages:                 # row released; cache's ref holds
            a.decref(p)
        assert d.cached_tokens("w0", toks) == 8
        assert c.evict(2) == 2          # cascades leaf then parent
        assert d.cached_tokens("w0", toks) == 0
        assert d.stats() == {"w0": 0}

    def test_listener_fault_does_not_break_publish(self):
        from paddle_tpu.inference.paged_cache import BlockAllocator
        from paddle_tpu.inference.prefix_cache import PrefixCache

        class Boom:
            def on_insert(self, tokens):
                raise RuntimeError("listener bug")

            def on_evict(self, tokens):
                raise RuntimeError("listener bug")

        a = BlockAllocator(9)
        c = PrefixCache(a, 4, listener=Boom())
        pages = a.allocate(2)
        assert c.insert([1, 2, 3, 4, 5], pages) == 2    # no raise
        for p in pages:
            a.decref(p)
        assert c.evict(2) == 2                          # no raise


class TestRouting:
    def test_affinity_follows_published_prefix(self):
        """Serial shared-prefix traffic: once the first request retires
        and publishes its pages, every follow-up with the same system
        prompt routes to THAT worker (directory hit beats the load
        tie), and the affinity counter records it."""
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="affinity",
                             engine_kwargs=ENGINE_KW)
        rng = np.random.RandomState(3)
        sys_p = rng.randint(1, 128, (24,)).astype(np.int32)
        owner = None
        for i in range(3):
            suf = rng.randint(1, 128, (4,)).astype(np.int32)
            req = fleet.submit(np.concatenate([sys_p, suf]),
                               max_new_tokens=4)
            fleet.run_until_drained()
            req.wait(timeout=60)
            admitted = {w.wid: w.engine.stats()["admitted"]
                        for w in fleet.workers}
            if i == 0:
                owner = max(admitted, key=admitted.get)
            else:
                assert admitted[owner] == i + 1, admitted
        st = fleet.stats()
        assert st["affinity_hits"] == 2
        hit = {w: s["prefix_hit_tokens"]
               for w, s in st["workers"].items()}
        assert hit[owner] > 0
        fleet.close()

    def test_round_robin_alternates(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        p = np.arange(1, 9, dtype=np.int32)
        for _ in range(4):
            fleet.submit(p, max_new_tokens=2)
        counts = [len(w.pending) for w in fleet.workers]
        assert counts == [2, 2]
        fleet.run_until_drained()
        fleet.close()

    def test_submit_with_no_healthy_workers_raises(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=1, engine_kwargs=ENGINE_KW)
        fleet.workers[0].healthy = False
        with pytest.raises(RuntimeError, match="no healthy"):
            fleet.submit(np.arange(1, 5, dtype=np.int32))
        fleet.close()


class TestFailover:
    def test_killed_worker_requests_bitmatch_solo(self):
        """The acceptance bar: kill a worker while its rows are
        mid-decode; every request still completes on the survivor,
        token-for-token identical to an undisturbed solo run (the r7
        recompute-resume path, applied cross-worker)."""
        m = _model()
        rng = np.random.RandomState(5)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        reqs, expect = [], []
        for _ in range(4):
            p = rng.randint(1, 128, (10,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=16))
            expect.append(_solo(m, p, 16))
        fleet.step()            # admit + first chunk on both workers
        victim = fleet.workers[1]
        assert victim.occupancy > 0     # rows genuinely in flight
        moved = fleet.kill_worker("w1")
        assert moved > 0
        fleet.run_until_drained()
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=60)).reshape(-1),
                e.reshape(-1))
        st = fleet.stats()
        assert st["failovers"] == 1 and st["rerouted"] == moved
        assert st["healthy_workers"] == 1
        # a re-routed resumed request never double-counts TTFT
        assert all(r.trace.ttft is not None for r in reqs)
        fleet.close()

    def test_raising_step_fails_worker_not_fleet(self):
        m = _model()
        rng = np.random.RandomState(6)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             engine_kwargs=ENGINE_KW)
        reqs, expect = [], []
        for _ in range(2):
            p = rng.randint(1, 128, (9,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=12))
            expect.append(_solo(m, p, 12))
        fleet.step()
        # wedge w1's next decode: the fleet must drain it, not crash
        def boom():
            raise RuntimeError("device lost")
        fleet.workers[1].engine.decode_once = boom
        fleet.run_until_drained()
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=60)).reshape(-1),
                e.reshape(-1))
        assert fleet.workers[1].fail_reason == "drained"
        assert not fleet.workers[1].healthy
        assert fleet.stats()["failovers"] == 1
        fleet.close()

    def test_watchdog_stall_flags_worker_for_failover(self):
        """Drive the per-worker EngineStallWatchdog deterministically:
        a heartbeat that sits still while the worker is busy fires
        once, the on_stall hook marks the worker unhealthy, and the
        next step() re-routes its work."""
        m = _model()
        rng = np.random.RandomState(8)
        fleet = ServingFleet(m, n_workers=2, policy="round_robin",
                             stall_s=10.0, engine_kwargs=ENGINE_KW)
        reqs, expect = [], []
        for _ in range(2):
            p = rng.randint(1, 128, (8,)).astype(np.int32)
            reqs.append(fleet.submit(p, max_new_tokens=16))
            expect.append(_solo(m, p, 16))
        fleet.step()                        # both workers now busy
        assert fleet.check_watchdogs(now=100.0) == []   # arms baseline
        fired = fleet.check_watchdogs(now=111.0)        # > stall_s idle
        assert [wid for wid, _ in fired] == ["w0", "w1"]
        # both flagged — restore w0 so the fleet has a survivor (the
        # stall was synthetic: its heartbeat never actually wedged)
        fleet.workers[0].healthy = True
        fleet.workers[0].fail_reason = None
        fleet.run_until_drained()
        for r, e in zip(reqs, expect):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=60)).reshape(-1),
                e.reshape(-1))
        assert not fleet.workers[1].healthy
        assert fleet.stats()["failovers"] >= 1
        fleet.close()


class TestWorkerIds:
    def test_engine_stats_worker_id(self):
        m = _model()
        from paddle_tpu.inference.serving import DecodeEngine
        eng = DecodeEngine(m, worker_id="w7", **ENGINE_KW)
        assert eng.stats()["worker_id"] == "w7"
        eng2 = DecodeEngine(m, **ENGINE_KW)
        assert eng2.stats()["worker_id"] is None

    def test_batching_server_threads_worker_id(self):
        m = _model()
        from paddle_tpu.inference.serving import (BatchingServer,
                                                  GenerationPredictor)
        srv = BatchingServer(GenerationPredictor(m), max_batch=2,
                             continuous=True, worker_id="w3",
                             engine_kwargs=dict(s_max=64, chunk=4,
                                                block_size=8))
        try:
            s = srv.stats()
            assert s["worker_id"] == "w3"
            assert s["engine"]["worker_id"] == "w3"
        finally:
            srv.close()

    def test_fleet_assigns_distinct_ids(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=2, engine_kwargs=ENGINE_KW)
        ws = fleet.stats()["workers"]
        assert set(ws) == {"w0", "w1"}
        assert all(s["worker_id"] == wid for wid, s in ws.items())
        fleet.close()


class TestAggregatorAndEndpoint:
    def _regs(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("jobs_total", "jobs").inc(3)
        r2.counter("jobs_total", "jobs").inc(4)
        r1.histogram("lat_seconds").observe(0.01)
        r2.histogram("lat_seconds").observe(0.02)
        return r1, r2

    def test_merged_snapshot_sums_workers(self):
        r1, r2 = self._regs()
        agg = MetricsAggregator({"w0": r1, "w1": r2})
        snap = agg.snapshot()
        assert snap["workers"]["w0"]["counters"]["jobs_total"] == 3
        assert snap["fleet"]["counters"]["jobs_total"] == 7
        assert snap["fleet"]["histograms"]["lat_seconds"]["count"] == 2

    def test_prometheus_per_worker_labels_single_type_header(self):
        r1, r2 = self._regs()
        agg = MetricsAggregator({"w0": r1, "w1": r2})
        text = agg.prometheus_text()
        assert 'jobs_total{worker="w0"} 3' in text
        assert 'jobs_total{worker="w1"} 4' in text
        assert text.count("# TYPE jobs_total counter") == 1
        assert text.count("# TYPE lat_seconds histogram") == 1
        assert 'lat_seconds_bucket{worker="w1",le="+Inf"} 1' in text

    def test_type_conflict_raises(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("x")
        r2.gauge("x")
        agg = MetricsAggregator({"w0": r1, "w1": r2})
        with pytest.raises(TypeError, match="conflicting"):
            agg.prometheus_text()

    def test_duplicate_label_raises(self):
        agg = MetricsAggregator({"w0": MetricsRegistry()})
        with pytest.raises(ValueError, match="duplicate"):
            agg.add("w0", MetricsRegistry())

    def test_scrape_endpoint(self):
        r1, r2 = self._regs()
        srv = MetricsHTTPServer(
            MetricsAggregator({"w0": r1, "w1": r2})).start()
        try:
            body = urllib.request.urlopen(srv.url, timeout=10).read()
            text = body.decode()
            assert 'jobs_total{worker="w0"} 3' in text
            js = json.loads(urllib.request.urlopen(
                srv.url + ".json", timeout=10).read())
            assert js["fleet"]["counters"]["jobs_total"] == 7
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/nope", timeout=10)
        finally:
            srv.close()

    def test_fleet_serve_metrics_includes_router(self):
        m = _model()
        fleet = ServingFleet(m, n_workers=2, engine_kwargs=ENGINE_KW)
        req = fleet.submit(np.arange(1, 9, dtype=np.int32),
                           max_new_tokens=2)
        fleet.run_until_drained()
        req.wait(timeout=60)
        srv = fleet.serve_metrics()
        try:
            text = urllib.request.urlopen(srv.url,
                                          timeout=10).read().decode()
            assert 'fleet_submitted_total{worker="router"} 1' in text
            assert 'engine_retired_total{worker="w' in text
            assert "# TYPE engine_ttft_seconds histogram" in text
        finally:
            fleet.close()
