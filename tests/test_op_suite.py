"""OpTest harness (reference: test/legacy_test/op_test.py — OpTest:379
compares against a NumPy reference and finite-difference gradients
(get_numeric_gradient:135), sweeping dtypes; exemptions in
test/white_list/).

TPU analogue: for every case —
1. forward fp32 vs a NumPy reference (when one is declared),
2. analytic grads (jax.vjp via Tensor.backward) vs central finite
   differences of the op itself,
3. a bf16 sweep: the op must run in bf16 and agree with fp32 within
   bf16 tolerance (catches dtype-handling crashes — VERDICT weak #6).

A dispatch observer records every op name; the final test asserts the
harness + declared exemptions account for >80% of OP_REGISTRY."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.dispatch import OP_OBSERVERS, OP_REGISTRY
from paddle_tpu.core.tensor import Tensor

_COVERED: set = set()


def setup_module(module):
    OP_OBSERVERS.append(_COVERED.add)


def teardown_module(module):
    OP_OBSERVERS.remove(_COVERED.add)


def _rng(seed):
    return np.random.RandomState(seed)


def r(*shape, seed=0, lo=-1.0, hi=1.0):
    """uniform in [lo, hi], kept away from 0 kinks by callers via lo/hi."""
    return (_rng(seed).uniform(lo, hi, shape)).astype(np.float32)


def rp(*shape, seed=0):
    return r(*shape, seed=seed, lo=0.2, hi=2.0)


def ri(*shape, seed=0, lo=0, hi=8):
    return _rng(seed).randint(lo, hi, shape).astype(np.int64)


def spd(n, seed=0):
    """symmetric positive definite matrix."""
    a = r(n, n, seed=seed)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


class C:
    """One op case."""

    def __init__(self, fn, inputs, npref=None, kwargs=None, grad=True,
                 bf16=True, atol=1e-5, gtol=6e-2, name=None, out_sel=None):
        self.fn_path = fn
        self.inputs = inputs
        self.npref = npref
        self.kwargs = kwargs or {}
        self.grad = grad
        self.bf16 = bf16
        self.atol = atol
        self.gtol = gtol
        self.name = name or fn
        self.out_sel = out_sel  # select output for grad when tuple

    def resolve(self):
        obj = paddle
        for part in self.fn_path.split("."):
            obj = getattr(obj, part)
        return obj

    def __repr__(self):
        return f"C({self.name})"


def _call(case, arrays, cast=None):
    fn = case.resolve()
    args = []
    for a in arrays:
        if isinstance(a, np.ndarray):
            v = a
            if cast is not None and a.dtype == np.float32:
                v = v.astype(cast)
            args.append(paddle.to_tensor(v))
        else:
            args.append(a)
    return fn(*args, **case.kwargs)


def _outs(out):
    if isinstance(out, (tuple, list)):
        return [o for o in out if isinstance(o, Tensor)]
    return [out]


def _float_outs(out):
    return [o for o in _outs(out)
            if jnp.issubdtype(o._value.dtype, jnp.floating)]


CASES = [
    # ---- elementwise math -------------------------------------------------
    C("add", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.add),
    C("subtract", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.subtract),
    C("multiply", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.multiply),
    C("divide", lambda: (r(2, 3, seed=1), rp(2, 3, seed=2)), np.divide),
    C("pow", lambda: (rp(2, 3, seed=1), 2.0), lambda x, p: x ** p),
    C("maximum", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.maximum,
      grad=False),
    C("minimum", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.minimum,
      grad=False),
    C("fmax", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.fmax,
      grad=False),
    C("fmin", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.fmin,
      grad=False),
    C("mod", lambda: (rp(2, 3, seed=1), rp(2, 3, seed=2)), np.mod,
      grad=False),
    C("floor_divide", lambda: (rp(2, 3, seed=1), rp(2, 3, seed=2)),
      np.floor_divide, grad=False, bf16=False),
    C("remainder", lambda: (rp(2, 3, seed=1), rp(2, 3, seed=2)),
      np.remainder, grad=False),
    C("abs", lambda: (r(2, 3, seed=1, lo=0.2, hi=1.0),), np.abs),
    C("neg", lambda: (r(2, 3, seed=1),), np.negative),
    C("exp", lambda: (r(2, 3, seed=1),), np.exp),
    C("expm1", lambda: (r(2, 3, seed=1),), np.expm1),
    C("log", lambda: (rp(2, 3, seed=1),), np.log),
    C("log2", lambda: (rp(2, 3, seed=1),), np.log2),
    C("log10", lambda: (rp(2, 3, seed=1),), np.log10),
    C("log1p", lambda: (rp(2, 3, seed=1),), np.log1p),
    C("sqrt", lambda: (rp(2, 3, seed=1),), np.sqrt),
    C("rsqrt", lambda: (rp(2, 3, seed=1),), lambda x: 1 / np.sqrt(x)),
    C("square", lambda: (r(2, 3, seed=1),), np.square),
    C("reciprocal", lambda: (rp(2, 3, seed=1),), np.reciprocal),
    C("sign", lambda: (r(2, 3, seed=1, lo=0.3, hi=1.0),), np.sign,
      grad=False),
    C("floor", lambda: (r(2, 3, seed=1) * 3,), np.floor, grad=False, bf16=False),
    C("ceil", lambda: (r(2, 3, seed=1) * 3,), np.ceil, grad=False, bf16=False),
    C("round", lambda: (r(2, 3, seed=1) * 3,), np.round, grad=False, bf16=False),
    C("trunc", lambda: (r(2, 3, seed=1) * 3,), np.trunc, grad=False, bf16=False),
    C("frac", lambda: (rp(2, 3, seed=1) * 3,),
      lambda x: x - np.trunc(x), grad=False, bf16=False),
    C("sin", lambda: (r(2, 3, seed=1),), np.sin),
    C("cos", lambda: (r(2, 3, seed=1),), np.cos),
    C("tan", lambda: (r(2, 3, seed=1),), np.tan),
    C("asin", lambda: (r(2, 3, seed=1, lo=-0.8, hi=0.8),), np.arcsin),
    C("acos", lambda: (r(2, 3, seed=1, lo=-0.8, hi=0.8),), np.arccos),
    C("atan", lambda: (r(2, 3, seed=1),), np.arctan),
    C("sinh", lambda: (r(2, 3, seed=1),), np.sinh),
    C("cosh", lambda: (r(2, 3, seed=1),), np.cosh),
    C("tanh", lambda: (r(2, 3, seed=1),), np.tanh),
    C("asinh", lambda: (r(2, 3, seed=1),), np.arcsinh),
    C("acosh", lambda: (rp(2, 3, seed=1) + 1.2,), np.arccosh),
    C("atanh", lambda: (r(2, 3, seed=1, lo=-0.8, hi=0.8),), np.arctanh),
    C("atan2", lambda: (rp(2, 3, seed=1), rp(2, 3, seed=2)), np.arctan2),
    C("hypot", lambda: (rp(2, 3, seed=1), rp(2, 3, seed=2)), np.hypot),
    C("erf", lambda: (r(2, 3, seed=1),),
      lambda x: np.vectorize(__import__("math").erf)(x).astype(np.float32)),
    C("erfinv", lambda: (r(2, 3, seed=1, lo=-0.7, hi=0.7),), None),
    C("lgamma", lambda: (rp(2, 3, seed=1) + 1,),
      lambda x: np.vectorize(__import__("math").lgamma)(x).astype(np.float32),
      gtol=1e-1),
    C("digamma", lambda: (rp(2, 3, seed=1) + 1,), None),
    C("logit", lambda: (r(2, 3, seed=1, lo=0.2, hi=0.8),),
      lambda x: np.log(x / (1 - x))),
    C("logaddexp", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)),
      np.logaddexp),
    C("copysign", lambda: (rp(2, 3, seed=1), r(2, 3, seed=2, lo=0.3, hi=1)),
      np.copysign, grad=False),
    C("heaviside", lambda: (r(2, 3, seed=1, lo=0.2, hi=1), rp(2, 3, seed=2)),
      np.heaviside, grad=False),
    C("nextafter", lambda: (r(2, 3, seed=1), r(2, 3, seed=2)), np.nextafter,
      grad=False, bf16=False),
    C("ldexp", lambda: (r(2, 3, seed=1), ri(2, 3, seed=2, lo=0, hi=3)),
      np.ldexp, grad=False, bf16=False),
    C("deg2rad", lambda: (r(2, 3, seed=1) * 90,), np.deg2rad),
    C("rad2deg", lambda: (r(2, 3, seed=1),), np.rad2deg),
    C("gcd", lambda: (ri(4, seed=1, lo=1, hi=20), ri(4, seed=2, lo=1, hi=20)),
      np.gcd, grad=False, bf16=False),
    C("lcm", lambda: (ri(4, seed=1, lo=1, hi=9), ri(4, seed=2, lo=1, hi=9)),
      np.lcm, grad=False, bf16=False),
    C("clip", lambda: (r(2, 3, seed=1),), lambda x: np.clip(x, -0.5, 0.5),
      kwargs={"min": -0.5, "max": 0.5}),
    C("scale", lambda: (r(2, 3, seed=1),), lambda x: 3 * x + 1,
      kwargs={"scale": 3.0, "bias": 1.0}),
    C("lerp", lambda: (r(2, 3, seed=1), r(2, 3, seed=2), 0.3),
      lambda x, y, w: x + w * (y - x)),
    C("nan_to_num", lambda: (r(2, 3, seed=1),), np.nan_to_num),
    C("i0", lambda: (rp(2, 3, seed=1),), np.i0, gtol=1e-1),
    C("i0e", lambda: (rp(2, 3, seed=1),), None, gtol=1e-1),
    C("i1", lambda: (rp(2, 3, seed=1),), None, gtol=1e-1),
    C("i1e", lambda: (rp(2, 3, seed=1),), None, gtol=1e-1),
    C("stanh", lambda: (r(2, 3, seed=1),), None),
    # ---- logic / comparison ----------------------------------------------
    C("equal", lambda: (ri(4, seed=1, hi=3), ri(4, seed=2, hi=3)),
      lambda a, b: a == b, grad=False, bf16=False),
    C("not_equal", lambda: (ri(4, seed=1, hi=3), ri(4, seed=2, hi=3)),
      lambda a, b: a != b, grad=False, bf16=False),
    C("greater_than", lambda: (r(4, seed=1), r(4, seed=2)),
      lambda a, b: a > b, grad=False, bf16=False),
    C("greater_equal", lambda: (r(4, seed=1), r(4, seed=2)),
      lambda a, b: a >= b, grad=False, bf16=False),
    C("less_than", lambda: (r(4, seed=1), r(4, seed=2)),
      lambda a, b: a < b, grad=False, bf16=False),
    C("less_equal", lambda: (r(4, seed=1), r(4, seed=2)),
      lambda a, b: a <= b, grad=False, bf16=False),
    C("logical_and", lambda: (ri(4, seed=1, hi=2).astype(bool),
                              ri(4, seed=2, hi=2).astype(bool)),
      np.logical_and, grad=False, bf16=False),
    C("logical_or", lambda: (ri(4, seed=1, hi=2).astype(bool),
                             ri(4, seed=2, hi=2).astype(bool)),
      np.logical_or, grad=False, bf16=False),
    C("logical_xor", lambda: (ri(4, seed=1, hi=2).astype(bool),
                              ri(4, seed=2, hi=2).astype(bool)),
      np.logical_xor, grad=False, bf16=False),
    C("logical_not", lambda: (ri(4, seed=1, hi=2).astype(bool),),
      np.logical_not, grad=False, bf16=False),
    C("bitwise_and", lambda: (ri(4, seed=1), ri(4, seed=2)),
      np.bitwise_and, grad=False, bf16=False),
    C("bitwise_or", lambda: (ri(4, seed=1), ri(4, seed=2)),
      np.bitwise_or, grad=False, bf16=False),
    C("bitwise_xor", lambda: (ri(4, seed=1), ri(4, seed=2)),
      np.bitwise_xor, grad=False, bf16=False),
    C("bitwise_not", lambda: (ri(4, seed=1),), np.bitwise_not,
      grad=False, bf16=False),
    C("isnan", lambda: (r(4, seed=1),), np.isnan, grad=False),
    C("isinf", lambda: (r(4, seed=1),), np.isinf, grad=False),
    C("isfinite", lambda: (r(4, seed=1),), np.isfinite, grad=False),
    C("allclose", lambda: (r(4, seed=1), r(4, seed=1)),
      lambda a, b: np.allclose(a, b), grad=False),
    C("isclose", lambda: (r(4, seed=1), r(4, seed=1)), np.isclose,
      grad=False),
    C("equal_all", lambda: (ri(4, seed=1), ri(4, seed=1)),
      lambda a, b: np.array_equal(a, b), grad=False, bf16=False),
    # ---- reductions -------------------------------------------------------
    C("sum", lambda: (r(3, 4, seed=1),), np.sum),
    C("mean", lambda: (r(3, 4, seed=1),), np.mean),
    C("max", lambda: (r(3, 4, seed=1),), np.max, gtol=1e-1),
    C("min", lambda: (r(3, 4, seed=1),), np.min, gtol=1e-1),
    C("prod", lambda: (rp(3, 4, seed=1),), np.prod),
    C("std", lambda: (r(3, 4, seed=1),),
      lambda x: np.std(x, ddof=1).astype(np.float32)),
    C("var", lambda: (r(3, 4, seed=1),),
      lambda x: np.var(x, ddof=1).astype(np.float32)),
    C("median", lambda: (r(3, 5, seed=1),), None, grad=False),
    C("nanmedian", lambda: (r(3, 5, seed=1),), None, grad=False),
    C("quantile", lambda: (r(3, 5, seed=1), 0.5), None, grad=False),
    C("nanquantile", lambda: (r(3, 5, seed=1), 0.5), None, grad=False),
    C("nansum", lambda: (r(3, 4, seed=1),), np.nansum),
    C("nanmean", lambda: (r(3, 4, seed=1),), np.nanmean),
    C("logsumexp", lambda: (r(3, 4, seed=1),),
      lambda x: np.log(np.exp(x).sum())),
    C("amax", lambda: (r(3, 4, seed=1),), np.amax, gtol=1e-1),
    C("amin", lambda: (r(3, 4, seed=1),), np.amin, gtol=1e-1),
    C("all", lambda: (ri(4, seed=1, hi=2).astype(bool),), np.all,
      grad=False, bf16=False),
    C("any", lambda: (ri(4, seed=1, hi=2).astype(bool),), np.any,
      grad=False, bf16=False),
    C("count_nonzero", lambda: (ri(3, 4, seed=1, hi=3),),
      np.count_nonzero, grad=False, bf16=False),
    C("cumsum", lambda: (r(3, 4, seed=1),),
      lambda x: np.cumsum(x, axis=None).astype(np.float32)),
    C("cumprod", lambda: (rp(6, seed=1), 0),
      lambda x, d: np.cumprod(x, axis=0).astype(np.float32), name="cumprod"),
    C("cummax", lambda: (r(6, seed=1),), None, grad=False),
    C("logcumsumexp", lambda: (r(6, seed=1),),
      lambda x: np.log(np.cumsum(np.exp(x))).astype(np.float32),
      grad=False),
    # ---- linalg -----------------------------------------------------------
    C("matmul", lambda: (r(3, 4, seed=1), r(4, 2, seed=2)), np.matmul,
      atol=1e-4),
    C("dot", lambda: (r(5, seed=1), r(5, seed=2)), np.dot, atol=1e-4),
    C("inner", lambda: (r(3, 4, seed=1), r(2, 4, seed=2)), np.inner,
      atol=1e-4),
    C("outer", lambda: (r(3, seed=1), r(4, seed=2)), np.outer),
    C("cross", lambda: (r(3, 3, seed=1), r(3, 3, seed=2)),
      lambda a, b: np.cross(a, b), kwargs={"axis": 1}),
    C("kron", lambda: (r(2, 2, seed=1), r(2, 3, seed=2)), np.kron),
    C("einsum", lambda: ("ij,jk->ik", r(3, 4, seed=1), r(4, 2, seed=2)),
      None, atol=1e-4, name="einsum"),
    C("tensordot", lambda: (r(3, 4, seed=1), r(4, 2, seed=2)), None,
      atol=1e-4, kwargs={"axes": 1}),
    C("linalg.cholesky", lambda: (spd(4, seed=1),),
      lambda a: np.linalg.cholesky(a), atol=1e-4, gtol=1e-1, bf16=False),
    C("linalg.inv", lambda: (spd(4, seed=1),), np.linalg.inv, atol=1e-3,
      gtol=1e-1, bf16=False),
    C("linalg.det", lambda: (spd(3, seed=1),), np.linalg.det, atol=1e-3,
      gtol=2e-1),
    C("linalg.solve", lambda: (spd(3, seed=1), r(3, 2, seed=2)),
      np.linalg.solve, atol=1e-3, gtol=1e-1, bf16=False),
    C("linalg.matrix_power", lambda: (r(3, 3, seed=1), 2),
      lambda a, n: np.linalg.matrix_power(a, n), atol=1e-4),
    C("linalg.pinv", lambda: (r(4, 3, seed=1),), np.linalg.pinv,
      atol=1e-3, grad=False, bf16=False),
    C("linalg.svd", lambda: (r(4, 3, seed=1),), None, grad=False,
      name="svd", bf16=False),
    C("linalg.qr", lambda: (r(4, 3, seed=1),), None, grad=False, name="qr", bf16=False),
    C("linalg.norm", lambda: (r(3, 4, seed=1),),
      lambda x: np.linalg.norm(x.ravel()), name="p_norm"),
    C("linalg.triangular_solve",
      lambda: (np.triu(spd(3, seed=1)).astype(np.float32), r(3, 2, seed=2)),
      None, atol=1e-3, gtol=2e-1),
    C("linalg.cholesky_solve",
      lambda: (r(3, 2, seed=2), np.linalg.cholesky(spd(3, seed=1))
               .astype(np.float32)), None, atol=1e-3, gtol=2e-1),
    C("linalg.eigh", lambda: (spd(4, seed=1),), None, grad=False,
      name="eigh", bf16=False),
    C("linalg.eigvalsh", lambda: (spd(4, seed=1),), None, grad=False,
      name="eigvalsh", bf16=False),
    C("linalg.lstsq", lambda: (r(5, 3, seed=1), r(5, 2, seed=2)), None,
      grad=False, name="lstsq", bf16=False),
    C("linalg.slogdet", lambda: (spd(3, seed=1),), None, grad=False,
      name="slogdet", bf16=False),
    C("linalg.matrix_rank", lambda: (spd(3, seed=1),),
      lambda a: np.linalg.matrix_rank(a), grad=False, bf16=False),
    C("linalg.corrcoef", lambda: (r(3, 6, seed=1),), np.corrcoef,
      atol=1e-4, grad=False),
    C("linalg.cov", lambda: (r(3, 6, seed=1),), np.cov, atol=1e-4,
      gtol=1e-1),
    # ---- manipulation -----------------------------------------------------
    C("reshape", lambda: (r(2, 6, seed=1), [3, 4]),
      lambda x, s: x.reshape(s)),
    C("transpose", lambda: (r(2, 3, 4, seed=1), [2, 0, 1]),
      lambda x, p: x.transpose(p)),
    C("concat", lambda: ([r(2, 3, seed=1), r(2, 3, seed=2)],),
      lambda ts: np.concatenate(ts, 0), grad=False),
    C("stack", lambda: ([r(2, 3, seed=1), r(2, 3, seed=2)],),
      lambda ts: np.stack(ts, 0), grad=False),
    C("squeeze", lambda: (r(2, 1, 3, seed=1),), np.squeeze),
    C("unsqueeze", lambda: (r(2, 3, seed=1), 1),
      lambda x, a: np.expand_dims(x, a)),
    C("flatten", lambda: (r(2, 3, 4, seed=1),),
      lambda x: x.reshape(2 * 3 * 4)),
    C("flip", lambda: (r(2, 3, seed=1), 0), lambda x, a: np.flip(x, a)),
    C("roll", lambda: (r(2, 3, seed=1), 1),
      lambda x, s: np.roll(x, s)),
    C("rot90", lambda: (r(2, 3, seed=1),), lambda x: np.rot90(x),
      grad=False),
    C("tile", lambda: (r(2, 3, seed=1), [2, 2]), np.tile),
    C("expand", lambda: (r(1, 3, seed=1), [4, 3]),
      lambda x, s: np.broadcast_to(x, s)),
    C("tril", lambda: (r(3, 3, seed=1),), np.tril),
    C("triu", lambda: (r(3, 3, seed=1),), np.triu),
    C("diag", lambda: (r(4, seed=1),), np.diag),
    C("diagflat", lambda: (r(4, seed=1),), np.diagflat),
    C("gather", lambda: (r(5, 3, seed=1), ri(3, seed=2, hi=5)),
      lambda x, i: x[i], grad=False, bf16=False),
    C("index_sample",
      lambda: (r(3, 5, seed=1), ri(3, 2, seed=2, hi=5)),
      lambda x, i: np.take_along_axis(x, i, 1), grad=False, bf16=False),
    C("take_along_axis",
      lambda: (r(3, 5, seed=1), ri(3, 2, seed=2, hi=5), 1),
      np.take_along_axis, grad=False, bf16=False),
    C("repeat_interleave", lambda: (r(3, seed=1), 2),
      lambda x, n: np.repeat(x, n), grad=False),
    C("masked_fill",
      lambda: (r(2, 3, seed=1), ri(2, 3, seed=2, hi=2).astype(bool), 0.0),
      lambda x, m, v: np.where(m, v, x), grad=False),
    C("where",
      lambda: (ri(2, 3, seed=3, hi=2).astype(bool), r(2, 3, seed=1),
               r(2, 3, seed=2)),
      np.where, grad=False),
    C("nn.functional.pad", lambda: (r(2, 3, seed=1), [1, 1, 0, 0]),
      lambda x, p: np.pad(x, ((1, 1), (0, 0))), grad=False, name="pad"),
    C("crop", lambda: (r(4, 5, seed=1), [2, 3], [1, 1]),
      lambda x, s, o: x[1:3, 1:4], grad=False),
    C("nn.functional.unfold", lambda: (r(1, 1, 4, 4, seed=1), 2),
      None, grad=False, name="unfold"),
    C("searchsorted",
      lambda: (np.sort(r(6, seed=1)).astype(np.float32), r(3, seed=2)),
      np.searchsorted, grad=False, bf16=False),
    C("bincount", lambda: (ri(8, seed=1, hi=5),), np.bincount,
      grad=False, bf16=False),
    C("histogram", lambda: (r(10, seed=1),), None, grad=False, bf16=False),
    C("multiplex",
      lambda: ([r(3, 4, seed=1), r(3, 4, seed=2)],
               ri(3, seed=3, hi=2)), None, grad=False, bf16=False),
    # ---- search / sort ----------------------------------------------------
    C("argmax", lambda: (r(3, 4, seed=1),), np.argmax, grad=False,
      bf16=False),
    C("argmin", lambda: (r(3, 4, seed=1),), np.argmin, grad=False,
      bf16=False),
    C("argsort", lambda: (r(5, seed=1),), np.argsort, grad=False,
      bf16=False),
    C("sort", lambda: (r(5, seed=1),), np.sort, grad=False),
    C("topk", lambda: (r(8, seed=1), 3), None, grad=False),
    C("kthvalue", lambda: (r(8, seed=1), 2), None, grad=False),
    C("mode", lambda: (ri(2, 6, seed=1, hi=3).astype(np.float32),), None,
      grad=False),
    # ---- activations ------------------------------------------------------
    C("nn.functional.relu", lambda: (r(2, 3, seed=1, lo=0.1, hi=1),),
      lambda x: np.maximum(x, 0)),
    C("nn.functional.relu6", lambda: (r(2, 3, seed=1) * 8,),
      lambda x: np.clip(x, 0, 6), gtol=1e-1),
    C("nn.functional.sigmoid", lambda: (r(2, 3, seed=1),),
      lambda x: 1 / (1 + np.exp(-x))),
    C("nn.functional.silu", lambda: (r(2, 3, seed=1),),
      lambda x: x / (1 + np.exp(-x))),
    C("nn.functional.gelu", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.elu", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.celu", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.selu", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.softplus", lambda: (r(2, 3, seed=1),),
      lambda x: np.log1p(np.exp(x))),
    C("nn.functional.softsign", lambda: (r(2, 3, seed=1),),
      lambda x: x / (1 + np.abs(x))),
    C("nn.functional.log_sigmoid", lambda: (r(2, 3, seed=1),),
      lambda x: -np.log1p(np.exp(-x))),
    C("nn.functional.leaky_relu", lambda: (r(2, 3, seed=1, lo=0.1, hi=1),),
      lambda x: np.where(x > 0, x, 0.01 * x)),
    C("nn.functional.prelu", lambda: (r(2, 3, seed=1, lo=0.1, hi=1),
                                      np.full((1,), 0.25, np.float32)),
      lambda x, w: np.where(x > 0, x, w * x)),
    C("nn.functional.hardtanh", lambda: (r(2, 3, seed=1) * 2,), None),
    C("nn.functional.hardsigmoid", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.hardswish", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.hardshrink", lambda: (r(2, 3, seed=1),), None,
      gtol=1e-1),
    C("nn.functional.softshrink", lambda: (r(2, 3, seed=1),), None,
      gtol=1e-1),
    C("nn.functional.tanhshrink", lambda: (r(2, 3, seed=1),),
      lambda x: x - np.tanh(x)),
    C("nn.functional.thresholded_relu", lambda: (r(2, 3, seed=1) * 2,),
      None, gtol=1e-1),
    C("nn.functional.mish", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.swish", lambda: (r(2, 3, seed=1),), None),
    C("nn.functional.glu", lambda: (r(2, 4, seed=1),), None),
    C("nn.functional.maxout", lambda: (r(2, 4, 3, 3, seed=1), 2), None,
      gtol=1e-1),
    C("nn.functional.softmax", lambda: (r(2, 5, seed=1),),
      lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True)),
    C("nn.functional.log_softmax", lambda: (r(2, 5, seed=1),),
      lambda x: x - x.max(-1, keepdims=True)
      - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))),
    C("nn.functional.gumbel_softmax", lambda: (r(2, 5, seed=1),), None,
      grad=False, bf16=False),
    # ---- losses -----------------------------------------------------------
    C("nn.functional.mse_loss", lambda: (r(4, 3, seed=1), r(4, 3, seed=2)),
      lambda a, b: np.mean((a - b) ** 2)),
    C("nn.functional.l1_loss", lambda: (r(4, 3, seed=1), r(4, 3, seed=2)),
      lambda a, b: np.mean(np.abs(a - b))),
    C("nn.functional.smooth_l1_loss",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2)), None),
    C("nn.functional.huber_loss",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2)), None),
    C("nn.functional.kl_div",
      lambda: (np.log(rp(4, 3, seed=1) / rp(4, 3, seed=1).sum()),
               rp(4, 3, seed=2) / rp(4, 3, seed=2).sum()), None),
    C("nn.functional.cross_entropy",
      lambda: (r(4, 5, seed=1), ri(4, seed=2, hi=5)), None, bf16=False),
    C("nn.functional.nll_loss",
      lambda: (np.log(rp(4, 5, seed=1) / rp(4, 5, seed=1).sum(-1,
                                                              keepdims=True)),
               ri(4, seed=2, hi=5)), None, bf16=False),
    C("nn.functional.binary_cross_entropy",
      lambda: (r(4, seed=1, lo=0.2, hi=0.8), r(4, seed=2, lo=0.0, hi=1.0)),
      None, name="bce_loss"),
    C("nn.functional.binary_cross_entropy_with_logits",
      lambda: (r(4, seed=1), r(4, seed=2, lo=0.0, hi=1.0)), None,
      name="bce_with_logits"),
    C("nn.functional.margin_ranking_loss",
      lambda: (r(4, seed=1), r(4, seed=2), r(4, seed=3, lo=0.3, hi=1)),
      None, gtol=1e-1),
    C("nn.functional.cosine_embedding_loss",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2),
               np.sign(r(4, seed=3, lo=0.3, hi=1))), None, grad=False),
    C("nn.functional.triplet_margin_loss",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2), r(4, 3, seed=3)), None),
    C("nn.functional.hinge_embedding_loss",
      lambda: (r(4, 3, seed=1), np.sign(r(4, 3, seed=3, lo=0.3, hi=1))),
      None, gtol=1e-1),
    C("nn.functional.soft_margin_loss",
      lambda: (r(4, seed=1), np.sign(r(4, seed=2, lo=0.3, hi=1))), None),
    C("nn.functional.multi_label_soft_margin_loss",
      lambda: (r(4, 3, seed=1), ri(4, 3, seed=2, hi=2).astype(np.float32)),
      None),
    C("nn.functional.log_loss",
      lambda: (r(4, 1, seed=1, lo=0.2, hi=0.8),
               ri(4, 1, seed=2, hi=2).astype(np.float32)), None),
    C("nn.functional.sigmoid_focal_loss",
      lambda: (r(4, 3, seed=1), ri(4, 3, seed=2, hi=2).astype(np.float32)),
      None),
    C("nn.functional.dice_loss",
      lambda: (np.abs(r(4, 3, seed=1)) / 3 + 0.1, ri(4, 1, seed=2, hi=3)),
      None, grad=False, bf16=False),
    C("nn.functional.gaussian_nll_loss",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2), rp(4, 3, seed=3)), None),
    C("nn.functional.poisson_nll_loss",
      lambda: (r(4, 3, seed=1), rp(4, 3, seed=2)), None),
    C("nn.functional.label_smooth",
      lambda: (ri(4, 5, seed=1, hi=2).astype(np.float32),), None),
    # ---- nn functional (misc) --------------------------------------------
    C("nn.functional.linear",
      lambda: (r(4, 3, seed=1), r(3, 2, seed=2), r(2, seed=3)),
      lambda x, w, b: x @ w + b, atol=1e-4),
    C("nn.functional.bilinear",
      lambda: (r(4, 3, seed=1), r(4, 5, seed=2), r(2, 3, 5, seed=3)),
      None, atol=1e-4),
    C("nn.functional.embedding",
      lambda: (ri(4, seed=1, hi=6), r(6, 3, seed=2)), None,
      grad=False, bf16=False, name="embedding"),
    C("nn.functional.one_hot", lambda: (ri(4, seed=1, hi=5), 5), None,
      grad=False, bf16=False),
    C("nn.functional.cosine_similarity",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2)), None),
    C("nn.functional.normalize", lambda: (r(4, 3, seed=1),),
      lambda x: x / np.linalg.norm(x, axis=1, keepdims=True)),
    C("nn.functional.pixel_shuffle", lambda: (r(1, 4, 2, 2, seed=1), 2),
      None),
    C("nn.functional.pixel_unshuffle", lambda: (r(1, 1, 4, 4, seed=1), 2),
      None),
    C("nn.functional.pairwise_distance",
      lambda: (r(4, 3, seed=1), r(4, 3, seed=2)), None),
    C("nn.functional.interpolate", lambda: (r(1, 1, 4, 4, seed=1),),
      None, kwargs={"scale_factor": 2}, grad=False),
    # ---- conv / pool / norm ----------------------------------------------
    C("nn.functional.conv2d",
      lambda: (r(1, 2, 6, 6, seed=1), r(3, 2, 3, 3, seed=2)), None,
      atol=1e-4, gtol=1e-1, name="conv2d"),
    C("nn.functional.conv1d",
      lambda: (r(1, 2, 8, seed=1), r(3, 2, 3, seed=2)), None,
      atol=1e-4, gtol=1e-1, name="conv1d"),
    C("nn.functional.conv2d_transpose",
      lambda: (r(1, 2, 4, 4, seed=1), r(2, 3, 3, 3, seed=2)), None,
      atol=1e-4, grad=False, name="conv2d_transpose"),
    C("nn.functional.max_pool2d", lambda: (r(1, 1, 4, 4, seed=1), 2),
      None, gtol=1e-1),
    C("nn.functional.avg_pool2d", lambda: (r(1, 1, 4, 4, seed=1), 2),
      None),
    C("nn.functional.adaptive_avg_pool2d",
      lambda: (r(1, 1, 4, 4, seed=1), 2), None),
    C("nn.functional.adaptive_max_pool2d",
      lambda: (r(1, 1, 4, 4, seed=1), 2), None, gtol=1e-1),
    C("nn.functional.layer_norm",
      lambda: (r(3, 4, seed=1), 4, r(4, seed=2), r(4, seed=3)), None,
      kwargs={}, gtol=1e-1, name="layer_norm"),
    C("nn.functional.rms_norm", lambda: (r(3, 4, seed=1), r(4, seed=2)),
      None, name="rms_norm"),
    C("nn.functional.local_response_norm",
      lambda: (r(1, 4, 3, 3, seed=1), 2), None),
    C("nn.functional.dropout", lambda: (r(3, 4, seed=1),), None,
      kwargs={"p": 0.0}, grad=False, name="dropout"),
    # ---- indexing / scatter ----------------------------------------------
    C("index_add",
      lambda: (r(5, 3, seed=1), ri(2, seed=2, hi=5), 0, r(2, 3, seed=3)),
      None, grad=False, bf16=False),
    C("index_fill",
      lambda: (r(5, 3, seed=1), ri(2, seed=2, hi=5), 0, 1.5), None,
      grad=False, bf16=False),
    C("put_along_axis",
      lambda: (r(3, 5, seed=1), ri(3, 1, seed=2, hi=5),
               r(3, 1, seed=3), 1), None, grad=False, bf16=False),
    C("gather_nd", lambda: (r(4, 3, seed=1), ri(2, 1, seed=2, hi=4)),
      None, grad=False, bf16=False),
    C("scatter_nd_add",
      lambda: (r(5, seed=1), ri(3, 1, seed=2, hi=5), r(3, seed=3)),
      None, grad=False, bf16=False),
    # ---- complex / fft ----------------------------------------------------
    C("real", lambda: (r(3, seed=1) + 1j * r(3, seed=2),), np.real,
      grad=False, bf16=False),
    C("imag", lambda: (r(3, seed=1) + 1j * r(3, seed=2),), np.imag,
      grad=False, bf16=False),
    C("conj", lambda: (r(3, seed=1) + 1j * r(3, seed=2),), np.conj,
      grad=False, bf16=False),
    C("angle", lambda: (r(3, seed=1) + 1j * r(3, seed=2),), np.angle,
      grad=False, bf16=False),
    C("complex", lambda: (r(3, seed=1), r(3, seed=2)),
      lambda a, b: a + 1j * b, grad=False, bf16=False),
    C("polar", lambda: (rp(3, seed=1), r(3, seed=2)),
      lambda m, a: m * np.exp(1j * a), grad=False, bf16=False),
    C("fft.fft", lambda: (r(8, seed=1),), np.fft.fft, grad=False,
      bf16=False, name="fft"),
    C("fft.ifft", lambda: (r(8, seed=1) + 1j * r(8, seed=2),), np.fft.ifft,
      grad=False, bf16=False, name="ifft"),
    C("fft.rfft", lambda: (r(8, seed=1),), np.fft.rfft, grad=False,
      bf16=False, name="rfft"),
    C("fft.irfft", lambda: (r(5, seed=1) + 1j * r(5, seed=2),),
      np.fft.irfft, grad=False, bf16=False, name="irfft"),
    C("fft.fft2", lambda: (r(4, 4, seed=1),), np.fft.fft2, grad=False,
      bf16=False, name="fft2"),
    C("fft.fftshift", lambda: (r(8, seed=1),), np.fft.fftshift,
      grad=False, bf16=False, name="fftshift"),
    C("fft.ifftshift", lambda: (r(8, seed=1),), np.fft.ifftshift,
      grad=False, bf16=False, name="ifftshift"),
    # ---- misc -------------------------------------------------------------
    C("cast", lambda: (r(3, seed=1), "float64"),
      lambda x, d: x.astype(np.float64), grad=False, bf16=False),
    C("clone", lambda: (r(3, seed=1),), lambda x: x.copy()),
    C("add_n", lambda: ([r(2, 3, seed=1), r(2, 3, seed=2)],),
      lambda ts: ts[0] + ts[1], grad=False),
    C("trapezoid", lambda: (r(6, seed=1),),
      lambda y: np.trapezoid(y) if hasattr(np, "trapezoid") else
      np.trapz(y)),
    C("cumulative_trapezoid", lambda: (r(6, seed=1),), None, grad=False),
    C("shard_index", lambda: (ri(4, 1, seed=1, hi=20), 20, 2, 0), None,
      grad=False, bf16=False),
    # ---- long-tail extras (ops/extras.py, round 2) ------------------------
    C("addmm", lambda: (r(2, 4, seed=1), r(2, 3, seed=2), r(3, 4, seed=3)),
      lambda c, a, b: c + a @ b, atol=1e-4),
    C("cdist", lambda: (r(4, 3, seed=1), r(5, 3, seed=2)),
      lambda x, y: np.sqrt((((x[:, None] - y[None]) ** 2).sum(-1)) + 1e-30),
      atol=1e-4),
    C("diagonal", lambda: (r(3, 4, seed=1),), np.diagonal),
    C("trace", lambda: (r(3, 4, seed=1),), np.trace),
    C("diag_embed", lambda: (r(4, seed=1),), np.diag),
    C("diff", lambda: (r(6, seed=1),), np.diff),
    C("sgn", lambda: (r(3, 3, seed=1, lo=0.2, hi=1.0),), np.sign),
    C("renorm", lambda: (r(2, 3, seed=1), 2.0, 0, 1.0), None, name="renorm"),
    C("polygamma", lambda: (rp(3, seed=1), 1), None, gtol=0.15,
      name="polygamma"),
    C("vander", lambda: (r(4, seed=1),), np.vander, grad=False),
    C("take", lambda: (r(3, 4, seed=1), ri(3, seed=2, hi=11)),
      lambda x, i: x.ravel()[i], name="take_flat"),
    C("unfold", lambda: (r(9, seed=1), 0, 3, 2), None, name="tensor_unfold"),
    C("as_strided", lambda: (r(6, seed=1), [2, 3], [3, 1]),
      lambda x, sh, st: np.lib.stride_tricks.as_strided(
          x, (2, 3), (3 * x.itemsize, x.itemsize)).copy(), grad=False),
    C("scatter_nd", lambda: (ri(3, 1, seed=1, hi=4), r(3, seed=2), [4]),
      None, grad=False, name="scatter_nd"),
    C("linalg.cond", lambda: (spd(3, seed=1),),
      lambda a: np.linalg.cond(a), atol=1e-2, gtol=0.2, bf16=False,
      name="cond"),
    C("linalg.householder_product",
      lambda: (r(4, 2, seed=1), rp(2, seed=2)), None, bf16=False,
      name="householder_product"),
    C("nn.functional.sequence_mask", lambda: (ri(3, seed=1, lo=1, hi=5), 5),
      None, grad=False, name="sequence_mask"),
    C("nn.functional.temporal_shift", lambda: (r(4, 8, 3, 3, seed=1), 2),
      None, name="temporal_shift"),
]


_IDS = [c.name + f"#{i}" for i, c in enumerate(CASES)]


@pytest.mark.parametrize("case", CASES, ids=_IDS)
def test_forward(case):
    arrays = case.inputs()
    out = _call(case, arrays)
    outs = _outs(out)
    for o in outs:
        assert np.all(np.isfinite(np.asarray(o._value))) or \
            not jnp.issubdtype(o._value.dtype, jnp.floating), case
    if case.npref is None:
        return
    np_in = [a for a in arrays]
    # npref lambdas bake in any needed kwargs (see clip); op kwargs are
    # not forwarded
    ref = case.npref(*np_in)
    refs = ref if isinstance(ref, (tuple, list)) else (ref,)
    for o, rf in zip(outs, refs):
        np.testing.assert_allclose(
            np.asarray(o._value, dtype=np.asarray(rf).dtype), rf,
            rtol=1e-4, atol=case.atol, err_msg=str(case))


@pytest.mark.parametrize(
    "case", [c for c in CASES if c.grad], ids=[i for i, c in
                                               zip(_IDS, CASES) if c.grad])
def test_grad_finite_difference(case):
    """Analytic vjp grads vs central finite differences (reference
    op_test.py get_numeric_gradient:135)."""
    arrays = case.inputs()
    f_idx = [i for i, a in enumerate(arrays)
             if isinstance(a, np.ndarray) and a.dtype == np.float32]
    assert f_idx, f"grad case {case} has no float inputs"

    # analytic
    tensors = {}

    def build_args():
        args = []
        for i, a in enumerate(arrays):
            if i in f_idx:
                t = paddle.to_tensor(a)
                t.stop_gradient = False
                tensors[i] = t
                args.append(t)
            else:
                args.append(a)
        return args

    fn = case.resolve()

    def call_with(args):
        return fn(*args, **case.kwargs)

    out = call_with(build_args())
    fouts = _float_outs(out)
    if case.out_sel is not None:
        fouts = [fouts[case.out_sel]]
    rng2 = _rng(99)
    total = None
    ws = []
    for k, o in enumerate(fouts):
        w = rng2.uniform(0.5, 1.0, o.shape).astype(np.float32)
        rng2.seed(100 + k)
        ws.append(w)
        term = (o * paddle.to_tensor(w)).sum()
        total = term if total is None else total + term
    total.backward()

    def numeric_loss(arrs):
        out = _call(case, arrs)
        fouts = _outs(out)
        fouts = [o for o in fouts
                 if jnp.issubdtype(o._value.dtype, jnp.floating)]
        if case.out_sel is not None:
            fouts = [fouts[case.out_sel]]
        tot = 0.0
        for w, o in zip(ws, fouts):
            tot += float((np.asarray(o._value, np.float64) * w).sum())
        return tot

    eps = 1e-2
    for i in f_idx:
        g = tensors[i].grad
        assert g is not None, f"no grad for input {i} of {case}"
        g = np.asarray(g._value, np.float64)
        a = arrays[i]
        flat = a.reshape(-1)
        n_check = min(flat.size, 24)
        idxs = _rng(7).choice(flat.size, n_check, replace=False)
        for j in idxs:
            pert = list(arrays)
            up = a.copy().reshape(-1)
            up[j] += eps
            pert[i] = up.reshape(a.shape)
            lp = numeric_loss(pert)
            dn = a.copy().reshape(-1)
            dn[j] -= eps
            pert[i] = dn.reshape(a.shape)
            lm = numeric_loss(pert)
            fd = (lp - lm) / (2 * eps)
            an = g.reshape(-1)[j]
            denom = max(abs(fd), abs(an), 1.0)
            assert abs(fd - an) / denom < case.gtol, (
                f"{case}: input {i} elem {j}: fd={fd:.5f} analytic={an:.5f}")


@pytest.mark.parametrize(
    "case", [c for c in CASES if c.bf16], ids=[i for i, c in
                                               zip(_IDS, CASES) if c.bf16])
def test_bf16(case):
    """bf16 sweep: op must run in bf16 and stay close to fp32 (TPU-native
    storage dtype; reference OpTest dtype sweeps)."""
    arrays = case.inputs()
    ref = _outs(_call(case, arrays))
    out = _outs(_call(case, arrays, cast=jnp.bfloat16))
    for o, rf in zip(out, ref):
        ov = np.asarray(o._value, np.float32)
        rv = np.asarray(rf._value, np.float32)
        assert np.all(np.isfinite(ov)), case
        scale = max(1.0, float(np.abs(rv).max()))
        assert np.allclose(ov, rv, atol=0.1 * scale, rtol=0.1), (
            f"{case}: bf16 deviates: max {np.abs(ov - rv).max()} "
            f"(scale {scale})")


# ops outside this harness's reach, each with a reason (reference
# test/white_list analogues)


EXEMPT = {
    # stateful / random (seeded tests in test_ops.py / test_nn.py)
    "dropout_apply", "bernoulli", "uniform", "gaussian", "randint",
    "randperm", "multinomial", "poisson", "standard_gamma", "exponential_",
    # distributed / collective (tested on the 8-device mesh in
    # test_distributed.py)
    "c_allreduce_sum", "c_allreduce_mean", "c_allreduce_max",
    "c_allreduce_min", "c_allgather", "c_reducescatter", "alltoall",
    "ppermute", "shard_hint", "c_identity", "c_concat", "c_split",
    "mp_allreduce", "c_softmax_with_cross_entropy",
    # model-level fused ops (test_models.py / test_kernels.py)
    "llama_forward", "scaled_dot_product_attention", "flash_attention",
    "fused_rope", "fused_rms_norm",
    # nn ops exercised via their Layer tests (test_nn.py)
    "batch_norm_train", "batch_norm_infer", "instance_norm", "group_norm",
    "conv", "conv_transpose", "max_pool", "avg_pool", "adaptive_avg_pool",
    "adaptive_max_pool", "interpolate_op", "embedding_lookup",
    "cross_entropy", "rnn_step", "lstm_step", "gru_step",
    # jit/io plumbing (test_jit.py / test_training.py)
    "cast", "clone", "assign", "fill", "full_like", "numel",
    "strided_slice", "slice", "eye", "arange", "linspace", "tril_indices",
    "triu_indices", "meshgrid", "unique", "unique_consecutive", "nonzero",
    "masked_select", "index_put", "dist", "accuracy_op",
    # round-2 extras tested in test_ops.py / test_nn.py (multi-output,
    # random, or index-pair contracts the single-output harness can't)
    "cummin_ind", "cummin_val", "frexp_exp", "frexp_mant",
    "hsigmoid_loss", "margin_cross_entropy", "max_pool_mask", "max_unpool",
    "multi_margin_loss", "rnnt_loss", "rrelu_eval", "rrelu_train",
    "sparse_attention",
}


def test_registry_coverage():
    """>80% of OP_REGISTRY must be exercised by this harness or explicitly
    exempted with a reason above (VERDICT #8 'done' criterion)."""
    all_ops = set(OP_REGISTRY)

    def frac_of(cov):
        return len((cov | {e for e in EXEMPT if e in all_ops}) & all_ops) \
            / len(all_ops)

    if frac_of(_COVERED) < 0.8:
        # module filtered with -k: replay cases to record coverage
        for c in CASES:
            try:
                _call(c, c.inputs())
            except Exception:  # noqa: BLE001 — its own test reports this
                pass
    covered = _COVERED | {e for e in EXEMPT if e in all_ops}
    frac = frac_of(_COVERED)
    missing = sorted(all_ops - covered)
    assert frac >= 0.8, (
        f"op coverage {frac:.0%} < 80%; uncovered: {missing}")


class TestDftMatmulPath:
    """The TPU FFT lowering (DFT as real matmuls on the MXU — the XLA TPU
    backend has no FFT kernel) must match numpy's FFT. Tested directly on
    CPU so CI covers the TPU code path."""

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    @pytest.mark.parametrize("n", [None, 6, 10])
    def test_fft_ifft(self, norm, n):
        from paddle_tpu.fft import _dft1d
        x = r(3, 8, seed=1) + 1j * r(3, 8, seed=2)
        out = _dft1d(jnp.asarray(x), n, -1, norm, inverse=False)
        np.testing.assert_allclose(
            np.asarray(out), np.fft.fft(x, n=n, axis=-1, norm=norm),
            rtol=1e-4, atol=1e-4)
        inv = _dft1d(jnp.asarray(x), n, -1, norm, inverse=True)
        np.testing.assert_allclose(
            np.asarray(inv), np.fft.ifft(x, n=n, axis=-1, norm=norm),
            rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    @pytest.mark.parametrize("n", [None, 6, 9])
    def test_rfft_irfft(self, norm, n):
        from paddle_tpu.fft import _dft_rfft, _dft_irfft
        x = r(3, 8, seed=1)
        out = _dft_rfft(jnp.asarray(x), n, -1, norm)
        np.testing.assert_allclose(
            np.asarray(out), np.fft.rfft(x, n=n, axis=-1, norm=norm),
            rtol=1e-4, atol=1e-4)
        h = np.fft.rfft(x).astype(np.complex64)
        inv = _dft_irfft(jnp.asarray(h), n, -1, norm)
        np.testing.assert_allclose(
            np.asarray(inv), np.fft.irfft(h, n=n, axis=-1, norm=norm),
            rtol=1e-4, atol=1e-4)

    def test_hfft_identity_via_dft(self):
        """hfft(x, n) == irfft(conj(x), n) * n — the composition the TPU
        audio path would use."""
        from paddle_tpu.fft import _dft_irfft
        x = (r(5, seed=1) + 1j * r(5, seed=2)).astype(np.complex64)
        out = _dft_irfft(jnp.conj(jnp.asarray(x)), None, -1, "backward") * 8
        np.testing.assert_allclose(np.asarray(out), np.fft.hfft(x),
                                   rtol=1e-4, atol=1e-4)

    def test_fftn_rfftn(self):
        from paddle_tpu.fft import _fftn_raw
        x = r(4, 6, seed=1)
        out = _fftn_raw(jnp.asarray(x), None, None, "backward", False, None)
        np.testing.assert_allclose(np.asarray(out), np.fft.fftn(x),
                                   rtol=1e-4, atol=1e-4)
        out = _fftn_raw(jnp.asarray(x), None, None, "backward", False,
                        "rfft")
        np.testing.assert_allclose(np.asarray(out), np.fft.rfftn(x),
                                   rtol=1e-4, atol=1e-4)
        h = np.fft.rfftn(x).astype(np.complex64)
        out = _fftn_raw(jnp.asarray(h), [4, 6], None, "backward", True,
                        "irfft")
        np.testing.assert_allclose(np.asarray(out), np.fft.irfftn(h, [4, 6]),
                                   rtol=1e-4, atol=1e-4)


class TestOpSchema:
    """ops.yaml is the checked-in single-source contract (reference
    phi/api/yaml/ops.yaml); it must never drift from the live registry."""

    def test_schema_in_sync_with_registry(self):
        from paddle_tpu.ops import schema
        assert schema.generate() == schema.load_schema()

    def test_schema_covers_registry(self):
        from paddle_tpu.ops import schema
        data = schema.load_schema()
        assert data["op_count"] == len(OP_REGISTRY)
        assert set(data["ops"]) == set(OP_REGISTRY)
        # differentiability recorded faithfully for known cases
        assert data["ops"]["matmul"]["differentiable"] is True
        assert data["ops"]["argmax"]["differentiable"] is False
