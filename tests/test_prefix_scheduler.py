"""Prefix-sharing radix KV cache + preempting scheduler (ISSUE 2):
refcounted allocator semantics, radix insert/match/evict, the
no-page-aliased-by-two-writers ownership invariant (property-style
simulation of the engine's allocation protocol), scheduler ordering,
prefix-hit admission charging only the uncached suffix, and lossless
preemption round-trips (tiny pool bit-matches ample pool)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.paged_cache import BlockAllocator
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.inference.scheduler import RequestScheduler


class TestAllocatorRefcounts:
    def test_incref_decref_lifecycle(self):
        a = BlockAllocator(5)
        (p,) = a.allocate(1)
        assert a.refcount(p) == 1
        a.incref(p)
        assert a.refcount(p) == 2
        a.decref(p)
        assert a.refcount(p) == 1 and a.num_used == 1
        a.decref(p)                        # last reader frees
        assert a.refcount(p) == 0 and a.num_free == 4

    def test_ref_ops_on_unallocated_raise(self):
        a = BlockAllocator(5)
        with pytest.raises(ValueError):
            a.incref(1)
        with pytest.raises(ValueError):
            a.decref(1)

    def test_free_of_shared_page_raises(self):
        """A unilateral free of a page another reader still maps is the
        aliasing bug the refcount layer exists to prevent."""
        a = BlockAllocator(5)
        pages = a.allocate(2)
        a.incref(pages[0])
        with pytest.raises(ValueError, match="decref"):
            a.free(pages)
        a.decref(pages[0])
        a.free(pages)                      # exclusive again: fine
        assert a.num_used == 0

    def test_watermark_and_cumulative_counters(self):
        a = BlockAllocator(9)
        first = a.allocate(3)
        a.free(first)
        a.allocate(2)
        assert a.high_watermark == 3       # peak, not current
        assert a.total_allocated == 5      # cumulative, never decreases
        assert a.stats()["high_watermark"] == 3


class TestRadixTree:
    def _cache(self, n_blocks=17, bs=4):
        a = BlockAllocator(n_blocks)
        return a, PrefixCache(a, bs)

    def test_insert_then_full_match(self):
        a, c = self._cache()
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        pages = a.allocate(2)
        assert c.insert(toks, pages) == 2
        m = c.match(toks, 8)
        assert m.pages == pages and m.cached_len == 8
        assert m.cow_src is None
        assert a.refcount(pages[0]) == 3   # row + cache + match
        c.release(m)
        assert a.refcount(pages[0]) == 2

    def test_partial_tail_is_cow_only(self):
        """A node shorter than block_size is never handed out shared —
        the matcher returns it as a COW source."""
        a, c = self._cache()
        pages = a.allocate(2)
        c.insert([1, 2, 3, 4, 5, 6], pages)    # full page + 2-token leaf
        m = c.match([1, 2, 3, 4, 5, 9], 6)
        assert m.pages == [pages[0]]
        assert m.cow_src == pages[1] and m.cow_len == 1
        assert m.cached_len == 5
        c.release(m)

    def test_limit_caps_the_match(self):
        """limit = ns-1 in the engine: the admitting row always keeps
        at least one real token to prefill, even on a full-prompt hit."""
        a, c = self._cache()
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        c.insert(toks, a.allocate(2))
        m = c.match(toks, 7)               # second full page blocked...
        assert len(m.pages) == 1
        assert m.cow_len == 3              # ...but COWs up to the cap
        assert m.cached_len == 7
        c.release(m)
        m = c.match(toks, 4)               # page-aligned cap: no COW
        assert len(m.pages) == 1 and m.cow_src is None
        c.release(m)

    def test_insert_is_first_wins(self):
        a, c = self._cache()
        toks = [1, 2, 3, 4]
        incumbent = a.allocate(1)
        c.insert(toks, incumbent)
        dup = a.allocate(1)
        assert c.insert(toks, dup) == 0    # duplicate adopts nothing
        assert a.refcount(dup[0]) == 1     # still only the caller's ref
        m = c.match(toks, 8)
        assert m.pages == incumbent
        c.release(m)

    def _publish(self, a, c, toks, n_pages):
        """The engine's retire shape: insert, then the row drops its
        own references (the cache's ref is what keeps pages alive)."""
        pages = a.allocate(n_pages)
        c.insert(toks, pages)
        for p in pages:
            a.decref(p)
        return pages

    def test_evict_lru_and_cascade(self):
        a, c = self._cache()
        self._publish(a, c, [1, 2, 3, 4, 5, 6, 7, 8], 2)
        self._publish(a, c, [9, 10, 11, 12], 1)
        c.release(c.match([1, 2, 3, 4, 5, 6, 7, 8], 8))   # touch all of
        used0 = a.num_used      # chain 1: chain 2 becomes the LRU victim
        assert c.evict(1) == 1
        m = c.match([9, 10, 11, 12], 4)
        assert m.cached_len == 0 and not m.pages
        # chain 1's leaf then its exposed parent go next (cascade)
        assert c.evict(2) == 2
        assert len(c) == 0
        assert a.num_used == used0 - 3

    def test_evict_never_touches_referenced_pages(self):
        a, c = self._cache()
        toks = [1, 2, 3, 4]
        self._publish(a, c, toks, 1)
        m = c.match(toks, 8)               # a live reader holds a ref
        assert c.evict(5) == 0
        c.release(m)
        assert c.evict(5) == 1             # reader gone: evictable


class TestOwnershipInvariant:
    def test_no_page_aliased_by_two_writers(self):
        """Property-style simulation of the engine's exact allocation
        protocol (match -> allocate -> adopt/COW -> insert -> decref)
        under a small token alphabet (to force heavy sharing): at every
        step, every page a live row may WRITE has refcount exactly 1,
        shared pages are never writable, and full teardown returns the
        pool to empty."""
        rng = np.random.RandomState(0)
        bs = 4
        a = BlockAllocator(41)
        c = PrefixCache(a, bs)
        writers: dict[int, int] = {}       # page -> owning row id
        live: dict[int, dict] = {}
        next_id = 0

        def check():
            for p, owner in writers.items():
                assert a.refcount(p) == 1, \
                    f"page {p} writable by row {owner} has readers"
            for row in live.values():
                for p in row["shared"]:
                    assert p not in writers
                    assert a.refcount(p) >= 2   # cache + this row

        for _ in range(300):
            if live and (rng.rand() < 0.4 or len(live) >= 6):
                rid = rng.choice(list(live))
                row = live.pop(rid)
                c.insert(row["seq"], row["shared"] + row["own"])
                for p in row["own"]:
                    del writers[p]         # published = read-only now
                for p in row["shared"] + row["own"]:
                    a.decref(p)
                check()
                continue
            seq = list(rng.randint(1, 5, rng.randint(2, 21)))
            ns = len(seq)
            m = c.match(seq, ns - 1)
            need = -(-ns // bs) - len(m.pages)
            pages = a.allocate(need)
            if pages is None:
                c.evict(need - a.num_free)
                pages = a.allocate(need)
            if pages is None:
                c.release(m)               # pool busy: skip this arrival
                continue
            for p in pages:
                assert a.refcount(p) == 1 and p not in writers
            if m.cow_src is not None:      # "device copy" then release
                assert a.refcount(m.cow_src) >= 2
                c.release_cow(m)
            rid, next_id = next_id, next_id + 1
            live[rid] = {"seq": seq, "shared": list(m.pages),
                         "own": list(pages)}
            for p in pages:
                writers[p] = rid
            check()
        for rid in list(live):
            row = live.pop(rid)
            for p in row["shared"] + row["own"]:
                a.decref(p)
        c.evict(a.capacity)
        assert a.num_used == 0 and a.num_free == a.capacity


class _Req:
    def __init__(self, priority=0):
        self.priority = priority


class TestRequestScheduler:
    def test_priority_then_fcfs(self):
        s = RequestScheduler()
        lo1, hi, lo2 = _Req(0), _Req(2), _Req(0)
        for r in (lo1, hi, lo2):
            s.add(r)
        assert s.peek() is hi              # peek does not remove
        assert len(s) == 3
        assert [s.pop() for _ in range(3)] == [hi, lo1, lo2]
        assert not s

    def test_requeue_keeps_original_arrival_order(self):
        """A preempted request re-enters at its ORIGINAL FCFS position
        among equal priorities — preemption must not cost it its turn."""
        s = RequestScheduler()
        r1, r2 = _Req(), _Req()
        s.add(r1)
        s.add(r2)
        assert s.pop() is r1               # admitted...
        r3 = _Req()
        s.add(r3)
        s.add(r1)                          # ...then preempted back in
        assert [s.pop() for _ in range(3)] == [r1, r2, r3]

    def test_drain_returns_queue_order(self):
        s = RequestScheduler()
        reqs = [_Req(p) for p in (0, 3, 1)]
        for r in reqs:
            s.add(r)
        assert s.drain() == [reqs[1], reqs[2], reqs[0]]
        assert len(s) == 0
        with pytest.raises(IndexError):
            s.pop()


class TestPrefixEngine:
    def _model(self):
        paddle.seed(0)
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        m.eval()
        return m

    @staticmethod
    def _drive(eng, pending, iters=300):
        for _ in range(iters):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                return
        raise AssertionError("engine did not drain the workload")

    def _solo(self, m, p, mn):
        return np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=mn,
            temperature=0.0)._value)[0]

    def test_resubmission_allocates_zero_prefix_pages(self):
        """The acceptance delta: an identical re-submission funds ZERO
        pages for the shared prefix — only the one tail page (the
        allocator's cumulative counter makes the charge observable)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(11)
        # 17 tokens / bs 8: two FULL shared pages + a 1-token tail;
        # 17 + 4 new stays inside 3 pages, so admission is the only
        # allocation and the charge is exact
        p = rng.randint(1, 128, (17,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8)
        r1 = _Request(p, 4)
        self._drive(eng, [r1])
        cold_delta = eng._alloc.total_allocated
        assert cold_delta == 3             # ceil(17/8), charged in full
        r2 = _Request(p, 4)
        self._drive(eng, [r2])
        warm_delta = eng._alloc.total_allocated - cold_delta
        assert warm_delta == 1             # tail page only: both shared
        #                                    prefix pages cost nothing
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      r2.wait(timeout=1))
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      self._solo(m, p, 4))
        s = eng.stats()
        assert s["prefix_hit_tokens"] == 16
        assert s["admitted"] == 2 and s["retired"] == 2
        assert s["prefix_cache"]["hits"] == 1

    def test_shared_system_prompt_outputs_match_solo(self):
        """Mid-page sharing: requests repeat a 12-token system prompt
        (one full page + 4 COW tokens at bs 8) with distinct suffixes.
        Every warm admission runs the COW + position-offset tail
        prefill; greedy outputs must still bit-match solo generate."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(12)
        sys_p = rng.randint(1, 128, (12,)).astype(np.int32)
        prompts = [np.concatenate([sys_p, rng.randint(
            1, 128, (5,)).astype(np.int32)]) for _ in range(4)]
        solo = [self._solo(m, p, 6) for p in prompts]
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8)
        reqs = []
        for p in prompts:                  # serial: each retire
            r = _Request(p, 6)             # publishes before the next
            self._drive(eng, [r])          # admission matches
            reqs.append(r)
        for r, s in zip(reqs, solo):
            np.testing.assert_array_equal(r.wait(timeout=1), s)
        st = eng.stats()
        assert st["prefix_hit_tokens"] > 0
        assert st["prefix_cache"]["hits"] >= 3

    def test_preemption_roundtrip_tiny_pool_matches_ample(self):
        """The lossless-preemption acceptance: a pool too small for two
        growing rows forces self-preemption + recompute-resume; greedy
        outputs must be bit-identical to an ample pool (and solo)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(13)
        prompts = [rng.randint(1, 128, (7,)).astype(np.int32)
                   for _ in range(2)]
        solo = [self._solo(m, p, 12) for p in prompts]

        def run(**kw):
            eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                               block_size=8, **kw)
            reqs = [_Request(p, 12) for p in prompts]
            self._drive(eng, list(reqs))
            return eng, [r.wait(timeout=1) for r in reqs]

        # 3 usable pages; each row needs 3 to finish (7 + 12 - 1 = 18
        # tokens) — they cannot coexist, so one must round-trip through
        # preemption while the other runs the pool alone
        tiny_eng, tiny = run(n_blocks=4)
        ample_eng, ample = run()
        assert tiny_eng.stats()["preempted"] >= 1
        assert ample_eng.stats()["preempted"] == 0
        for t, a, s in zip(tiny, ample, solo):
            np.testing.assert_array_equal(t, a)
            np.testing.assert_array_equal(t, s)
        assert tiny_eng._alloc.num_used <= 3   # only cached pages remain

    def test_priority_admits_first_and_preempts_lower(self):
        """Priority beats arrival at admission, and a high-priority
        arrival evicts a strictly-lower running row when the pool can't
        fund it otherwise — the evicted row still finishes losslessly."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(14)
        p_lo = rng.randint(1, 128, (7,)).astype(np.int32)
        p_hi = rng.randint(1, 128, (17,)).astype(np.int32)
        solo_lo = self._solo(m, p_lo, 12)
        solo_hi = self._solo(m, p_hi, 4)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4)
        lo = _Request(p_lo, 12)
        eng.admit([lo])
        eng.decode_once()                  # lo is mid-generation...
        hi = _Request(p_hi, 4, priority=5)
        pending = [hi]                     # ...when hi needs all 3 pages
        self._drive(eng, pending)
        np.testing.assert_array_equal(hi.wait(timeout=1), solo_hi)
        np.testing.assert_array_equal(lo.wait(timeout=1), solo_lo)
        assert eng.stats()["preempted"] >= 1

    def test_equal_priority_never_preempted_at_admission(self):
        """Strictly-lower only: an equal-priority claimant WAITS for the
        running row instead of evicting it (no preemption cycles)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(15)
        p1 = rng.randint(1, 128, (12,)).astype(np.int32)
        p2 = rng.randint(1, 128, (17,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4)
        r1 = _Request(p1, 4)
        eng.admit([r1])                    # holds 2 of 3 pages
        r2 = _Request(p2, 4)               # needs 3: must wait
        eng.admit([r2])
        assert eng.stats()["preempted"] == 0
        assert eng.backlog == 1 and not eng.idle()
        self._drive(eng, [])
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      self._solo(m, p1, 4))
        np.testing.assert_array_equal(r2.wait(timeout=1),
                                      self._solo(m, p2, 4))

    def test_infeasible_prompt_fails_loudly(self):
        """A prompt no amount of eviction/preemption can fund fails with
        the pool arithmetic in the message, not a silent hang."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(16)
        p = rng.randint(1, 128, (30,)).astype(np.int32)   # 4 pages
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4)      # pool holds 3
        r = _Request(p, 4)
        eng.admit([r])
        with pytest.raises(RuntimeError, match="pool holds 3"):
            r.wait(timeout=1)
        assert eng.stats()["failed"] == 1
        assert eng.idle()                  # not parked in the backlog

    def test_prefix_cache_off_still_serves(self):
        """prefix_cache=False: no radix cache, no self-preemption — the
        r6 exhaustion behavior — but plain workloads are unchanged."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(17)
        p = rng.randint(1, 128, (9,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, prefix_cache=False)
        r1, r2 = _Request(p, 4), _Request(p, 4)
        self._drive(eng, [r1, r2])
        np.testing.assert_array_equal(r1.wait(timeout=1),
                                      r2.wait(timeout=1))
        s = eng.stats()
        assert "prefix_cache" not in s
        assert s["pool"]["used"] == 0      # nothing retained


@pytest.mark.slow
class TestPreemptionStress:
    def test_mixed_priority_starved_pool_all_bit_match_solo(self):
        """Sustained mixed-priority arrivals through a pool an order of
        magnitude too small for the aggregate demand: every request that
        completes must bit-match solo, nothing may hang, and the only
        allowed failures are explicit pool-infeasibility errors."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        paddle.seed(0)
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        m.eval()
        rng = np.random.RandomState(18)
        eng = DecodeEngine(m, capacity=3, s_max=64, chunk=4,
                           block_size=8, n_blocks=6)
        reqs, solo = [], []
        for i in range(10):
            n = int(rng.randint(3, 14))
            mn = int(rng.choice([3, 6, 10]))
            p = rng.randint(1, 128, (n,)).astype(np.int32)
            reqs.append(_Request(p, mn, priority=int(rng.randint(0, 3))))
            solo.append(np.asarray(m.generate(
                paddle.to_tensor(p[None, :]), max_new_tokens=mn,
                temperature=0.0)._value)[0])
        queue = list(reqs)
        pending = []
        for _ in range(2000):
            while queue and len(pending) < 2:
                pending.append(queue.pop(0))
            eng.admit(pending)
            eng.decode_once()
            if not queue and not pending and eng.idle():
                break
        else:
            raise AssertionError("stress workload did not drain")
        for r, s in zip(reqs, solo):
            np.testing.assert_array_equal(r.wait(timeout=1), s)
        st = eng.stats()
        assert st["retired"] == 10 and st["failed"] == 0
        assert st["pool"]["high_watermark"] <= 5
