"""Distributed tests on the 8-device virtual CPU mesh (SURVEY §4: reference
uses multi-process localhost; our analogue is a real multi-device mesh in
one process — collectives actually execute)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class TestMeshAndPlacement:
    def test_process_mesh_props(self):
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        assert mesh.shape == [2, 4]
        assert mesh.get_dim_size("mp") == 4
        assert len(mesh.process_ids) == 8

    def test_shard_and_reshard_values(self):
        mesh = dist.ProcessMesh(shape=[8], dim_names=["x"])
        x = paddle.arange(0, 32, dtype="float32").reshape([8, 4])
        xs = dist.shard_tensor(x, mesh, [dist.Shard(0)])
        assert np.allclose(_np(xs), _np(x))
        xr = dist.reshard(xs, mesh, [dist.Replicate()])
        assert np.allclose(_np(xr), _np(x))
        # sharded compute produces correct global result
        y = paddle.sum(xs * 2)
        assert float(y) == float(paddle.sum(x * 2))

    def test_partial_placement_repr(self):
        p = dist.Partial()
        assert p.is_partial()
        s = dist.Shard(1)
        assert s.is_shard(1) and not s.is_shard(0)


class TestTopology:
    def test_communicate_topology(self):
        from paddle_tpu.distributed.fleet import CommunicateTopology
        topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"],
                                   [2, 2, 1, 1, 2])
        assert topo.world_size() == 8
        coord = topo.get_coord(5)
        assert topo.get_rank(**coord) == 5
        groups = topo.get_comm_list("model")
        assert len(groups) == 4 and all(len(g) == 2 for g in groups)

    def test_hybrid_group(self):
        from paddle_tpu.distributed.fleet import (CommunicateTopology,
                                                  HybridCommunicateGroup)
        topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"],
                                   [2, 1, 1, 1, 4])
        hcg = HybridCommunicateGroup(topo, rank=0)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 4
        mesh = hcg.get_mesh()
        assert mesh.shape == [2, 1, 1, 1, 4]

    def test_fleet_init(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4


class TestCollectivesCompiled:
    """Functional collectives inside shard_map over the 8-device mesh."""

    def test_psum_allgather(self):
        from jax.experimental.shard_map import shard_map
        mesh = dist.ProcessMesh(shape=[8], dim_names=["x"]).jax_mesh

        def f(x):
            s = jax.lax.psum(x, "x")
            g = jax.lax.all_gather(x, "x", tiled=True)
            return s, g

        xs = jnp.arange(8.0).reshape(8, 1)
        f_sharded = shard_map(f, mesh=mesh, in_specs=P("x", None),
                              out_specs=(P("x", None), P("x", None)))
        s, g = f_sharded(xs)
        assert np.allclose(np.asarray(s), 28.0)

    def test_fcollectives_through_tape(self):
        """fcollectives ops record on the tape; grad of psum is identity
        broadcast."""
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed import fcollectives as fc
        mesh = dist.ProcessMesh(shape=[8], dim_names=["x"]).jax_mesh

        def step(x):
            def inner(xv):
                return jax.lax.psum(xv * 2.0, "x")
            return shard_map(inner, mesh=mesh, in_specs=P("x"),
                             out_specs=P())(x)

        x = jnp.arange(8.0)
        out = step(x)
        assert float(np.asarray(out).reshape(())) == 2 * sum(range(8))
        g = jax.grad(lambda x: step(x).reshape(()))(x)
        assert np.allclose(np.asarray(g), 2.0)


class TestEagerCommAPI:
    def test_single_process_semantics(self):
        t = paddle.to_tensor([1.0, 2.0])
        dist.all_reduce(t)
        assert np.allclose(_np(t), [1, 2])
        out = []
        dist.all_gather(out, t)
        assert len(out) == 1
        g = dist.new_group([0])
        assert g.nranks == 1
        objs = []
        dist.all_gather_object(objs, {"a": 1})
        assert objs == [{"a": 1}]

    def test_reduce_scatter_local(self):
        t = paddle.zeros([2])
        dist.reduce_scatter(t, [paddle.ones([2]), paddle.ones([2])])
        assert np.allclose(_np(t), [2, 2])


class TestTPLayers:
    def _mesh(self):
        return dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])

    def test_column_row_parallel_match_dense(self):
        paddle.seed(3)
        col = dist.fleet.ColumnParallelLinear(8, 16, has_bias=True,
                                              gather_output=False)
        row = dist.fleet.RowParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.randn([4, 8])
        ref = F.linear(F.linear(x, col.weight, col.bias), row.weight, row.bias)
        # under mesh ctx with sharding hints
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        with sharding_ctx(self._mesh().jax_mesh):
            out = row(col(x))
        assert np.allclose(_np(out), _np(ref), atol=1e-5)
        assert col.weight._dist_spec == (None, "mp")
        assert row.weight._dist_spec == ("mp", None)

    def test_vocab_parallel_embedding(self):
        emb = dist.fleet.VocabParallelEmbedding(100, 16)
        ids = paddle.to_tensor(np.array([[1, 5], [7, 99]]))
        out = emb(ids)
        assert out.shape == [2, 2, 16]
        assert emb.weight._dist_spec == ("mp", None)

    def test_parallel_cross_entropy(self):
        pce = dist.fleet.ParallelCrossEntropy()
        logits = paddle.randn([4, 10])
        labels = paddle.to_tensor(np.random.randint(0, 10, (4,)))
        loss = pce(logits, labels)
        ref = F.cross_entropy(logits, labels, reduction="none")
        assert np.allclose(_np(loss)[:, 0], _np(ref), atol=1e-5)

    def test_rng_tracker(self):
        tracker = dist.fleet.get_rng_state_tracker()
        tracker.reset()
        tracker.add("test_rng", 1234)
        with tracker.rng_state("test_rng"):
            a = paddle.randn([4])
        tracker.reset()
        tracker.add("test_rng", 1234)
        with tracker.rng_state("test_rng"):
            b = paddle.randn([4])
        assert np.allclose(_np(a), _np(b))


class TestSequenceParallelNumerics:
    """VERDICT weak #9: the Megatron-SP surface must be real — the
    Column/Row pair matches dense numerics under the seq-sharded layout,
    and the Row side's reduce-scatter is an ACTUAL reduce-scatter on the
    wire (GSPMD alone emitted all-reduce+slice, 2x the bytes)."""

    def _pair(self):
        from paddle_tpu.distributed.fleet.sequence_parallel import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear)
        paddle.seed(0)
        col = ColumnSequenceParallelLinear(16, 32, has_bias=True)
        row = RowSequenceParallelLinear(32, 16, has_bias=True)
        return col, row

    def test_sp_pair_matches_dense_and_uses_reduce_scatter(self):
        import re
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.distributed.fleet.sequence_parallel import scatter
        col, row = self._pair()
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        x = paddle.randn([4, 8, 16])
        ref = F.linear(F.linear(x, col.weight, col.bias),
                       row.weight, row.bias)

        def f(xv):
            with sharding_ctx(mesh.jax_mesh):
                return row(col(scatter(Tensor(xv))))._value

        c = jax.jit(f).lower(x._value).compile()
        out = c(x._value)
        assert np.allclose(np.asarray(out), _np(ref), atol=1e-5)
        txt = c.as_text()
        assert re.search(r"reduce-scatter", txt)
        assert not re.search(r"all-reduce", txt)  # rs replaces ar+slice

    def test_sp_grads_flow(self):
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        from paddle_tpu.distributed.fleet.sequence_parallel import scatter
        col, row = self._pair()
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        x = paddle.randn([4, 8, 16])
        # dense reference grads
        ref_out = F.linear(F.linear(x, col.weight, col.bias),
                           row.weight, row.bias)
        (ref_out ** 2).mean().backward()
        g_ref = _np(row.weight.grad).copy()
        col.clear_gradients()
        row.clear_gradients()
        with sharding_ctx(mesh.jax_mesh):
            out = row(col(scatter(x)))
            (out ** 2).mean().backward()
        assert np.allclose(_np(row.weight.grad), g_ref, atol=1e-4)


class TestRecompute:
    def test_recompute_grads_match(self):
        from paddle_tpu.distributed.fleet import recompute
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 8))
        x = paddle.randn([4, 8])
        x.stop_gradient = False
        out1 = paddle.sum(net(x) ** 2)
        out1.backward()
        g_ref = [_np(p.grad) for p in net.parameters()]
        gx_ref = _np(x.grad)
        net.clear_gradients()
        x2 = paddle.to_tensor(_np(x), stop_gradient=False)
        out2 = paddle.sum(recompute(net, x2) ** 2)
        out2.backward()
        assert np.allclose(float(out1), float(out2), atol=1e-5)
        for p, g in zip(net.parameters(), g_ref):
            assert np.allclose(_np(p.grad), g, atol=1e-5)
        assert np.allclose(_np(x2.grad), gx_ref, atol=1e-5)


class TestShardingStages:
    def test_group_sharded_api(self):
        model = nn.Sequential(nn.Linear(64, 64), nn.ReLU(), nn.Linear(64, 8))
        opt = paddle.optimizer.AdamW(parameters=model.parameters())
        m2, o2, _ = dist.group_sharded_parallel(model, opt, "p_g_os")
        specs = [p._dist_spec for p in m2.parameters() if p.size >= 1024]
        assert any(s is not None and "sharding" in str(s) for s in specs)

    def test_stage1_partition_balanced(self):
        from paddle_tpu.distributed.fleet import DygraphShardingOptimizer
        model = nn.Sequential(*[nn.Linear(32, 32) for _ in range(4)])
        opt = paddle.optimizer.SGD(parameters=model.parameters())
        mapping = DygraphShardingOptimizer._partition_parameters(
            opt._parameter_list, 2)
        s0 = sum(p.size for p in mapping[0])
        s1 = sum(p.size for p in mapping[1])
        assert abs(s0 - s1) <= 32 * 32


class TestDistTrainStep:
    def test_dp_mp_train_step_matches_single(self):
        """The compiled hybrid step on a dp×mp mesh must match single-device
        SGD numerics."""
        paddle.seed(11)

        class TPNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.col = dist.fleet.ColumnParallelLinear(
                    16, 32, has_bias=True, gather_output=False)
                self.row = dist.fleet.RowParallelLinear(
                    32, 4, input_is_parallel=True)

            def forward(self, x):
                return self.row(F.relu(self.col(x)))

        def loss_fn(model, x, y):
            return F.cross_entropy(model(x), y)

        x = np.random.randn(8, 16).astype(np.float32)
        y = np.random.randint(0, 4, (8,))

        # single-device reference
        net1 = TPNet()
        opt1 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net1.parameters())
        losses1 = []
        for _ in range(3):
            loss = loss_fn(net1, paddle.to_tensor(x), paddle.to_tensor(y))
            loss.backward()
            opt1.step()
            opt1.clear_grad()
            losses1.append(float(loss))

        # mesh step
        paddle.seed(11)
        net2 = TPNet()
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        dist.shard_model_state(net2, mesh)
        step = dist.DistTrainStep(net2, opt2, loss_fn, mesh, donate=False)
        losses2 = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
                   for _ in range(3)]
        assert np.allclose(losses1, losses2, atol=1e-4), (losses1, losses2)
        for p1, p2 in zip(net1.parameters(), net2.parameters()):
            assert np.allclose(_np(p1), _np(p2), atol=1e-4)

    def test_fsdp_step_runs_sharded(self):
        model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                              nn.Linear(128, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        mesh = dist.ProcessMesh(shape=[8], dim_names=["sharding"])
        from paddle_tpu.distributed.fleet.sharding import apply_sharding_specs
        apply_sharding_specs(model, stage=3, min_size_to_shard=64)
        dist.shard_model_state(model, mesh)
        # params physically sharded
        w = model[0].weight
        assert "sharding" in str(w._value.sharding.spec)
        step = dist.DistTrainStep(
            model, opt,
            lambda m, a, b: F.cross_entropy(m(a), b), mesh, donate=False)
        x = paddle.randn([16, 64])
        y = paddle.to_tensor(np.random.randint(0, 8, (16,)))
        l0 = float(step(x, y))
        for _ in range(5):
            l = float(step(x, y))
        assert l < l0


class TestMoE:
    def test_moe_layer_forward_backward(self):
        d = 16
        experts = [nn.Sequential(nn.Linear(d, 32), nn.ReLU(),
                                 nn.Linear(32, d)) for _ in range(4)]
        moe = dist.fleet.MoELayer(d_model=d, experts=experts,
                                  gate={"type": "gshard", "top_k": 2})
        x = paddle.randn([2, 6, d])
        x.stop_gradient = False
        out = moe(x)
        assert out.shape == [2, 6, d]
        loss = paddle.sum(out ** 2) + moe.l_aux
        loss.backward()
        # gate + experts must receive gradient
        assert moe.gate.gate.weight.grad is not None
        assert experts[0][0].weight.grad is not None

    def test_moe_routes_tokens(self):
        """With an identity-ish single expert dominating, output is close to
        that expert's transform."""
        d = 8
        experts = [nn.Linear(d, d, bias_attr=False) for _ in range(2)]
        moe = dist.fleet.MoELayer(d_model=d, experts=experts, top_k=1,
                                  capacity_factor=4.0)
        # force router to expert 0
        gate_w = np.zeros((d, 2), np.float32)
        moe.gate.gate.weight.set_value(gate_w)
        moe.gate.gate.bias.set_value(np.array([100.0, -100.0], np.float32))
        x = paddle.randn([1, 4, d])
        out = moe(x)
        ref = F.linear(x, experts[0].weight)
        assert np.allclose(_np(out), _np(ref), atol=1e-4)

    def test_gshard_random_second_expert(self):
        """GShard gate: at train time the 2nd choice is kept with
        probability min(1, 2*g2) — a near-zero g2 must (almost) always be
        dropped, a dominant g2 kept."""
        from paddle_tpu.distributed.fleet.moe import GShardGate
        paddle.seed(0)
        gate = GShardGate(8, 4, topk=2)
        # logits with overwhelming expert 0, negligible everything else:
        # g2 ~ 0 -> drop mask ~ all True
        logits = np.full((64, 4), -20.0, np.float32)
        logits[:, 0] = 20.0
        drop = np.asarray(gate.second_expert_drop(logits, training=True))
        assert drop.mean() > 0.95
        # two equally strong experts: g2 = 0.5 -> 2*g2 = 1 -> never drop
        logits2 = np.full((64, 4), -20.0, np.float32)
        logits2[:, :2] = 20.0
        drop2 = np.asarray(gate.second_expert_drop(logits2, training=True))
        assert drop2.mean() < 0.05
        assert gate.second_expert_drop(logits, training=False) is None

    def test_switch_gate_train_jitter(self):
        from paddle_tpu.distributed.fleet.moe import SwitchGate
        paddle.seed(0)
        g = SwitchGate(8, 4, switch_eps=0.3)
        x = paddle.randn([16, 8])
        a = _np(g(x))
        b = _np(g(x))
        assert not np.allclose(a, b)  # jitter resampled per call
        g.eval()
        c = _np(g(x))
        d2 = _np(g(x))
        np.testing.assert_allclose(c, d2)


class TestSpmdPipeline:
    def test_pipeline_matches_sequential(self):
        """2-stage compiled pipeline over the pp axis == running both stages
        sequentially."""
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed.fleet.pipeline import spmd_pipeline
        n_stages, n_mb, mb, d = 2, 4, 3, 8
        mesh = dist.ProcessMesh(shape=[2], dim_names=["pp"]).jax_mesh
        rng = np.random.RandomState(0)
        w = rng.randn(n_stages, d, d).astype(np.float32) * 0.3
        x = rng.randn(n_mb, mb, d).astype(np.float32)

        def stage_fn(wi, xi):
            return jnp.tanh(xi @ wi[0])

        pipe = spmd_pipeline(stage_fn, n_stages, n_mb, axis_name="pp")
        f = shard_map(pipe, mesh=mesh, in_specs=(P("pp"), P()),
                      out_specs=P())
        out = np.asarray(f(jnp.asarray(w), jnp.asarray(x)))
        ref = np.tanh(np.tanh(x @ w[0]) @ w[1])
        assert np.allclose(out, ref, atol=1e-5)

    def test_pipeline_layer_segmentation(self):
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(6)]
        pp = PipelineLayer(descs, num_stages=3)
        assert pp.segment_parts == [0, 2, 4, 6]
        assert pp.get_stage_from_index(3) == 1
        out = pp(paddle.randn([2, 8]))
        assert out.shape == [2, 8]


class TestShardedCheckpoint:
    def test_save_load_reshard(self, tmp_path):
        mesh1 = dist.ProcessMesh(shape=[8], dim_names=["x"])
        model = nn.Linear(32, 16)
        model.weight._dist_spec = ("x", None)
        dist.shard_model_state(model, mesh1)
        ref = _np(model.weight)
        path = str(tmp_path / "ckpt")
        dist.save_state_dict(model.state_dict(), path)
        # perturb then reload with a DIFFERENT placement
        model.weight.set_value(np.zeros_like(ref))
        model.weight._dist_spec = (None, "x")
        dist.shard_model_state(model, mesh1)
        dist.load_state_dict(model.state_dict(), path)
        assert np.allclose(_np(model.weight), ref)

    def test_pdparams_suffix_forces_pickle_format(self, tmp_path):
        """The on-disk format is explicit by suffix (r5): .pdparams is
        always the host-pickle file, round-tripping even with orbax
        installed; a missing path raises FileNotFoundError, not a wrong
        'orbax artifact' diagnosis."""
        import os
        import pytest
        model = nn.Linear(4, 2)
        ref = _np(model.weight)
        path = str(tmp_path / "state.pdparams")
        dist.save_state_dict(model.state_dict(), path)
        assert os.path.isfile(path)          # a file, not an orbax dir
        model.weight.set_value(np.zeros_like(ref))
        dist.load_state_dict(model.state_dict(), path)
        assert np.allclose(_np(model.weight), ref)
        with pytest.raises(FileNotFoundError):
            dist.load_state_dict(model.state_dict(),
                                 str(tmp_path / "nope"))


class TestBaselineConfig4SFT:
    """BASELINE config 4 end to end: Qwen2 SFT under ZeRO-3 (GroupSharded
    Stage3 analogue) with cross-topology checkpoint reshard — train,
    snapshot, relaunch on a DIFFERENT mesh, resume, keep training."""

    def test_qwen2_zero3_sft_checkpoint_cross_topology(self, tmp_path):
        from paddle_tpu.distributed.fleet.sharding import (
            apply_sharding_specs)
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_loss_fn)
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))

        # phase 1: mesh A (dp4 x mp2), ZeRO-3 over dp
        paddle.seed(8)
        m1 = LlamaForCausalLM("qwen2-debug")
        o1 = paddle.optimizer.AdamW(learning_rate=1e-3,
                                    parameters=m1.parameters())
        apply_sharding_specs(m1, stage=3, axis="dp", min_size_to_shard=64)
        meshA = dist.ProcessMesh(shape=[4, 1, 1, 1, 2],
                                 dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(m1, meshA)
        step1 = dist.DistTrainStep(m1, o1, llama_loss_fn, meshA,
                                   donate=False)
        losses1 = [float(step1(ids, ids)) for _ in range(3)]
        assert losses1[-1] < losses1[0]
        path = str(tmp_path / "sft")
        state1 = {f"model.{k}": v for k, v in m1.state_dict().items()}
        for k, v in o1.state_dict().items():          # ZeRO-3's point:
            if hasattr(v, "_value"):                  # sharded moments
                state1[f"opt.{k}"] = v                # must survive too
        dist.save_state_dict(state1, path)
        w_ref = _np(m1._parameters["wq"])
        mom_ref = np.asarray(o1._accumulators["moment1"][0])

        # phase 2: fresh model on mesh B (dp2 x mp4) — reshard on load
        paddle.seed(99)  # different init proves the load works
        m2 = LlamaForCausalLM("qwen2-debug")
        o2 = paddle.optimizer.AdamW(learning_rate=1e-3,
                                    parameters=m2.parameters())
        apply_sharding_specs(m2, stage=3, axis="dp", min_size_to_shard=64)
        meshB = dist.ProcessMesh(shape=[2, 1, 1, 1, 4],
                                 dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(m2, meshB)
        o2._ensure_state()
        state2 = {f"model.{k}": v for k, v in m2.state_dict().items()}
        opt_wrap = {}
        for k, v in o2.state_dict().items():
            if hasattr(v, "_value"):
                state2[f"opt.{k}"] = v
                opt_wrap[k] = v
        dist.load_state_dict(state2, path)
        o2.set_state_dict(opt_wrap)                   # wrappers -> slots
        np.testing.assert_allclose(_np(m2._parameters["wq"]), w_ref,
                                   atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(o2._accumulators["moment1"][0]), mom_ref,
            atol=1e-6)
        step2 = dist.DistTrainStep(m2, o2, llama_loss_fn, meshB,
                                   donate=False)
        l = float(step2(ids, ids))
        assert np.isfinite(l) and l < losses1[0]

    def test_ernie_moe_preset_trains(self):
        """BASELINE config 4's ERNIE-4.5 anchor: llama-family decoder
        with MoE FFN — debug-scale train step descends with the router
        aux loss in the objective."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_loss_fn)
        paddle.seed(0)
        m = LlamaForCausalLM("ernie-debug")
        o = paddle.optimizer.AdamW(learning_rate=3e-3,
                                   parameters=m.parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))
        first = None
        for _ in range(6):
            loss = llama_loss_fn(m, ids, ids)
            if first is None:
                first = float(loss)
            loss.backward()
            o.step()
            o.clear_grad()
        assert float(loss) < first

    def test_shared_experts_active_and_trained(self):
        """VERDICT r3 #5: ERNIE-4.5/DeepSeekMoE shared experts — the
        always-on dense FFN beside the routed experts. The ernie preset
        now carries them; they must change the forward and receive
        gradients."""
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             llama_loss_fn)
        paddle.seed(1)
        m = LlamaForCausalLM("ernie-debug")
        assert m.config.moe_num_shared_experts == 1
        assert any(n.endswith("ws_gate") for n, _ in m.named_parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16), dtype=np.int32))
        loss = llama_loss_fn(m, ids, ids)
        loss.backward()
        grads = {n: p.grad for n, p in m.named_parameters()}
        for nm in ("ws_gate", "ws_up", "ws_down"):
            g = next(g for n, g in grads.items() if n.endswith(nm))
            assert g is not None and float(paddle.abs(g).sum()) > 0, nm
        # ablation: zeroing the shared experts changes the logits
        before = np.asarray(m(ids)._value)
        for n, p in m.named_parameters():
            if n.endswith(("ws_gate", "ws_up", "ws_down")):
                p._in_place_update(p._value * 0)
        after = np.asarray(m(ids)._value)
        assert not np.allclose(before, after)

    def test_dropless_matches_capacity_when_nothing_drops(self):
        """VERDICT r3 #5: dropless training (ragged grouped GEMMs via
        lax.ragged_dot). With capacity >= N*k the capacity path drops
        nothing, so both dispatches must agree; under a tight capacity
        they diverge (capacity really truncates) while dropless still
        serves every token."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        ids = np.random.randint(0, 128, (2, 16), dtype=np.int32)

        def build(dropless, cap=8.0):
            paddle.seed(3)
            cfg = dict(vocab_size=128, hidden_size=64,
                       intermediate_size=172, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       max_position_embeddings=256, num_experts=4,
                       num_experts_per_tok=2, moe_capacity_factor=cap,
                       moe_dropless=dropless)
            return LlamaForCausalLM(LlamaConfig(**cfg))

        out_cap = np.asarray(build(False)(paddle.to_tensor(ids))._value)
        out_drop = np.asarray(build(True)(paddle.to_tensor(ids))._value)
        np.testing.assert_allclose(out_drop, out_cap, atol=2e-4)
        out_tight = np.asarray(
            build(False, cap=0.3)(paddle.to_tensor(ids))._value)
        assert not np.allclose(out_tight, out_drop, atol=2e-4)


class TestBaselineConfig5MoE:
    def test_config5_presets_shapes(self):
        """BASELINE config-5 anchors exist as faithful presets: Mixtral
        8x7B (8 routed, top-2, wide experts) and DeepSeekMoE-16B (64
        routed + 2 shared, top-6, narrow experts)."""
        from paddle_tpu.models.llama import LLAMA_PRESETS, LlamaConfig
        mx = LlamaConfig(**LLAMA_PRESETS["mixtral-8x7b"])
        assert (mx.num_experts, mx.num_experts_per_tok,
                mx.moe_intermediate_size) == (8, 2, 14336)
        ds = LlamaConfig(**LLAMA_PRESETS["deepseek-moe-16b"])
        assert (ds.num_experts, ds.num_experts_per_tok,
                ds.moe_num_shared_experts,
                ds.moe_intermediate_size) == (64, 6, 2, 1408)
        # a scaled-down deepseek-shape model trains (same arch knobs)
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        paddle.seed(0)
        # NB: adding this train loop originally tipped the suite into
        # an XLA-CPU-compiler segfault in LATER unrelated tests — the
        # cause turned out to be CUMULATIVE per-process compile pressure
        # (crash followed total compile count, not this test's shapes or
        # top_k), fixed structurally by pytest.ini's process sharding.
        # Lane-aligned dims kept anyway as good hygiene.
        tiny = LlamaConfig(**{**LLAMA_PRESETS["deepseek-moe-16b"],
                              "vocab_size": 128, "hidden_size": 64,
                              "intermediate_size": 176,
                              "num_hidden_layers": 2,
                              "num_attention_heads": 4,
                              "num_key_value_heads": 4,
                              "num_experts": 8, "num_experts_per_tok": 2,
                              "moe_intermediate_size": 48,
                              "max_position_embeddings": 256})
        m = LlamaForCausalLM(tiny)
        o = paddle.optimizer.AdamW(learning_rate=3e-3,
                                   parameters=m.parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))
        first = None
        for _ in range(5):
            loss = llama_loss_fn(m, ids, ids)
            if first is None:
                first = float(loss)
            loss.backward()
            o.step()
            o.clear_grad()
        assert float(loss) < first

    def test_dropless_trains(self):
        """Dropless gradients flow through the ragged dispatch and the
        step descends."""
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             llama_loss_fn)
        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=172, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=256, num_experts=4,
                          num_experts_per_tok=2, moe_dropless=True)
        m = LlamaForCausalLM(cfg)
        o = paddle.optimizer.AdamW(learning_rate=3e-3,
                                   parameters=m.parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))
        first = None
        for _ in range(6):
            loss = llama_loss_fn(m, ids, ids)
            if first is None:
                first = float(loss)
            loss.backward()
            o.step()
            o.clear_grad()
        assert float(loss) < first


class TestZeroStage12:
    """ZeRO-1/2: optimizer state sharded over 'sharding' while params stay
    replicated (reference dygraph_sharding_optimizer.py:39,
    group_sharded_optimizer_stage2.py:53)."""

    def _run(self, stage):
        paddle.seed(33)
        model = nn.Sequential(nn.Linear(64, 64), nn.ReLU(),
                              nn.Linear(64, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        from paddle_tpu.distributed.fleet.sharding import apply_sharding_specs
        apply_sharding_specs(model, stage=stage)
        mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "sharding"])
        dist.shard_model_state(model, mesh)
        step = dist.DistTrainStep(
            model, opt, lambda m, a, b: F.cross_entropy(m(a), b), mesh,
            donate=False)
        x = np.random.RandomState(5).randn(16, 64).astype(np.float32)
        y = np.random.RandomState(6).randint(0, 8, (16,))
        losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
                  for _ in range(3)]
        return model, opt, losses

    def _reference(self):
        paddle.seed(33)
        model = nn.Sequential(nn.Linear(64, 64), nn.ReLU(),
                              nn.Linear(64, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        x = np.random.RandomState(5).randn(16, 64).astype(np.float32)
        y = np.random.RandomState(6).randint(0, 8, (16,))
        losses = []
        for _ in range(3):
            loss = F.cross_entropy(model(paddle.to_tensor(x)),
                                   paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return model, losses

    @pytest.mark.parametrize("stage", [1, 2])
    def test_opt_state_sharded_param_replicated(self, stage):
        model, opt, _ = self._run(stage)
        w = model[0].weight  # 64x64 >= min_size_to_shard
        # param replicated
        assert "sharding" not in str(w._value.sharding.spec)
        # its moments sharded over the sharding axis
        idx = [id(p) for p in opt._parameter_list].index(id(w))
        m1 = opt._accumulators["moment1"][idx]
        assert "sharding" in str(m1.sharding.spec), m1.sharding
        m2 = opt._accumulators["moment2"][idx]
        assert "sharding" in str(m2.sharding.spec)

    @pytest.mark.parametrize("stage", [1, 2])
    def test_numeric_parity_vs_single_device(self, stage):
        ref_model, ref_losses = self._reference()
        model, _, losses = self._run(stage)
        assert np.allclose(ref_losses, losses, atol=1e-4), (ref_losses,
                                                            losses)
        for p1, p2 in zip(ref_model.parameters(), model.parameters()):
            assert np.allclose(_np(p1), _np(p2), atol=1e-4)

    def test_shard_optimizer_api(self):
        model = nn.Sequential(nn.Linear(64, 64))
        opt = paddle.optimizer.AdamW(parameters=model.parameters())
        opt = dist.shard_optimizer(opt)
        w = model[0].weight
        assert "sharding" in str(w._opt_shard_spec)


class TestSepAttention:
    """Ring / all-to-all attention over the sep axis (distributed/sep.py;
    SURVEY §5 long-context mandate — reference ships the sep axis with no
    library attention op, four_directions_p2p_communication.py)."""

    def _qkv(self, b=2, s=32, h=4, hkv=2, d=8):
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d),
                              jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                              jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                              jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_matches_gathered(self, causal):
        from paddle_tpu.distributed.sep import ring_attention
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        q, k, v = self._qkv()
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()).reshape(2, 4), ("dp", "sep"))
        ref = _sdpa_reference(q, k, v, causal)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=causal, axis_name="sep", mesh=mesh))(q, k, v)
        assert np.allclose(out, ref, atol=1e-5)

    def test_ring_grads_match(self):
        from paddle_tpu.distributed.sep import ring_attention
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        q, k, v = self._qkv()
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()).reshape(2, 4), ("dp", "sep"))
        gr = jax.grad(lambda q, k, v: (_sdpa_reference(q, k, v, True) ** 2
                                       ).sum(), argnums=(0, 1, 2))(q, k, v)
        go = jax.jit(jax.grad(
            lambda q, k, v: (ring_attention(q, k, v, True, "sep", mesh) ** 2
                             ).sum(), argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(go, gr):
            assert np.allclose(a, b, atol=1e-4)

    def test_ulysses_matches_gathered(self):
        from paddle_tpu.distributed.sep import ulysses_attention
        from paddle_tpu.kernels.flash_attention import _sdpa_reference
        q, k, v = self._qkv()
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()).reshape(4, 2), ("dp", "sep"))
        ref = _sdpa_reference(q, k, v, True)
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, True, "sep", mesh))(q, k, v)
        assert np.allclose(out, ref, atol=1e-5)
        go = jax.jit(jax.grad(
            lambda q, k, v: (ulysses_attention(q, k, v, True, "sep",
                                               mesh) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(lambda q, k, v: (_sdpa_reference(q, k, v, True) ** 2
                                       ).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(go, gr):
            assert np.allclose(a, b, atol=1e-4)

    def test_ulysses_rejects_indivisible_heads(self):
        from paddle_tpu.distributed.sep import ulysses_attention
        q, k, v = self._qkv(hkv=2)
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()).reshape(2, 4), ("dp", "sep"))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, True, "sep", mesh)

    def test_llama_forward_sep_sharded_matches_single(self):
        """Flagship integration: llama forward on a sep>1 mesh (ring
        attention path) matches the meshless forward."""
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(3)
        model = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 32), dtype=np.int32))
        ref = _np(model(ids))
        mesh = dist.ProcessMesh(shape=[1, 1, 4, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(model, mesh)
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        with sharding_ctx(mesh.jax_mesh):
            out = _np(model(ids))
        assert np.allclose(out, ref, atol=1e-4), np.abs(out - ref).max()


class TestWrapperShardingVisibility:
    def test_zero_stage_seen_through_wrapper(self):
        """group_sharded_parallel returns a wrapper; DistTrainStep must
        still see the inner layer's stage (regression: stage-2 grad
        reduce-scatter was silently skipped for wrapped models)."""
        from paddle_tpu.distributed.fleet.sharding import (
            group_sharded_parallel)
        from paddle_tpu.distributed.parallelize import _resolve_zero_stage
        model = nn.Sequential(nn.Linear(64, 64))
        opt = paddle.optimizer.AdamW(parameters=model.parameters())
        wrapped, opt, _ = group_sharded_parallel(model, opt, "os_g")
        assert _resolve_zero_stage(wrapped) == 2


class TestPipelineParallelFlagship:
    """Real pipeline schedule wired into the flagship (VERDICT #3): when the
    mesh has pp>1, the decoder stack runs through spmd_pipeline inside
    shard_map (stage-local weights + microbatched ppermute), not
    scan-over-pp-sharded-weights."""

    def _mesh(self):
        return dist.ProcessMesh(shape=[2, 2, 1, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])

    def test_forward_and_grads_match_single_device(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(3)
        model = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))
        ref_out = _np(model(ids))
        mesh = self._mesh()
        dist.shard_model_state(model, mesh)
        with sharding_ctx(mesh.jax_mesh):
            out = _np(model(ids))
            loss = llama_loss_fn(model, ids, ids)
            loss.backward()
        assert np.allclose(out, ref_out, atol=1e-4)
        g_pp = {n: _np(p.grad) for n, p in model.named_parameters()
                if p.grad is not None}

        paddle.seed(3)
        ref = LlamaForCausalLM("debug")
        ref_loss = llama_loss_fn(ref, ids, ids)
        ref_loss.backward()
        assert abs(float(loss) - float(ref_loss)) < 1e-4
        for n, p in ref.named_parameters():
            if p.grad is None:
                continue
            assert np.allclose(g_pp[n], _np(p.grad), atol=1e-3), n

    def test_no_full_weight_allgather_in_hlo(self):
        """The pipelined program must not allgather the full stacked weight
        (that would be the FSDP-over-depth failure mode)."""
        import re
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(3)
        model = LlamaForCausalLM("debug")
        mesh = self._mesh()
        dist.shard_model_state(model, mesh)
        ids = np.random.randint(0, 128, (4, 32), dtype=np.int32)

        def f(ids_arr):
            with sharding_ctx(mesh.jax_mesh):
                return model(Tensor(ids_arr))._value

        txt = jax.jit(f).lower(jnp.asarray(ids)).compile().as_text()
        L = model.config.num_hidden_layers          # 2, pp-sharded to 1
        ff = model.config.intermediate_size
        # an all-gather producing a full [L, *, ff] stacked weight means
        # per-layer weight gathering; stage-local slices are [L/pp, ...]
        pat = re.compile(r"all-gather[^\n]*\[%d,\d+,%d\]" % (L, ff))
        assert not pat.search(txt), pat.search(txt).group(0)

    def test_dist_train_step_pp_matches_single(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        paddle.seed(5)
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))

        ref = LlamaForCausalLM("debug")
        ropt = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = llama_loss_fn(ref, ids, ids)
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss))

        paddle.seed(5)
        model = LlamaForCausalLM("debug")
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = self._mesh()
        dist.shard_model_state(model, mesh)
        step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh,
                                  donate=False)
        losses = [float(step(ids, ids)) for _ in range(3)]
        assert np.allclose(ref_losses, losses, atol=1e-3), (ref_losses,
                                                            losses)


class TestPipelineScheduleV2:
    """Round-3 pipeline upgrades (VERDICT #1): interleaved virtual stages,
    remat-bounded activation memory, >pp default microbatches, and mp
    propagation inside the manual-pp region."""

    def test_interleave_parity_and_grads(self):
        """v=2 virtual stages on pp=2 must match the single-device model
        bit-for-bit at fp32 tolerances (forward, loss, and every grad)."""
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             LLAMA_PRESETS, llama_loss_fn)
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(3)
        cfg = LlamaConfig(**LLAMA_PRESETS["tiny"])
        cfg.pp_interleave = 2
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.randint(0, 1024, (4, 32), dtype=np.int32))
        ref_out = _np(model(ids))
        mesh = dist.ProcessMesh(shape=[2, 2, 1, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(model, mesh)
        with sharding_ctx(mesh.jax_mesh):
            out = _np(model(ids))
            loss = llama_loss_fn(model, ids, ids)
            loss.backward()
        assert np.allclose(out, ref_out, atol=1e-4)
        g_pp = {n: _np(p.grad) for n, p in model.named_parameters()
                if p.grad is not None}
        paddle.seed(3)
        ref = LlamaForCausalLM(LlamaConfig(**LLAMA_PRESETS["tiny"]))
        ref_loss = llama_loss_fn(ref, ids, ids)
        ref_loss.backward()
        assert abs(float(loss) - float(ref_loss)) < 1e-4
        for n, p in ref.named_parameters():
            if p.grad is None:
                continue
            assert np.allclose(g_pp[n], _np(p.grad), atol=1e-3), n

    def test_remat_bounds_activation_memory(self):
        """jax.checkpoint around each chunk call must shrink the compiled
        temp footprint of the backward: without it every tick's stage
        internals stay live (unbounded in n_mb)."""
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pipeline import spmd_pipeline
        pp, n_mb, mb, d = 2, 8, 4, 128
        devs = np.array(jax.devices()[:pp])
        mesh = Mesh(devs, ("pp",))
        params = jnp.ones((pp * 4, d, d), jnp.float32) * 0.01
        x = jnp.ones((n_mb, mb, d), jnp.float32)

        def stage_fn(sp, xm):
            def body(c, w):
                return jnp.tanh(c @ w), None
            out, _ = jax.lax.scan(body, xm, sp)
            return out

        def build(remat):
            from paddle_tpu.utils.compat import shard_map
            apply = spmd_pipeline(stage_fn, pp, n_mb, axis_name="pp",
                                  remat=remat)
            sm = shard_map(apply, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           axis_names={"pp"})

            def loss(p, xx):
                return sm(p, xx).sum()

            return jax.jit(jax.grad(loss)).lower(params, x).compile()

        temp_remat = build(True).memory_analysis().temp_size_in_bytes
        temp_plain = build(False).memory_analysis().temp_size_in_bytes
        # the remat backward stores boundary activations only; the plain
        # backward stores every tick's scan internals as stacked residuals
        assert temp_remat < temp_plain * 0.7, (temp_remat, temp_plain)

    def test_grads_match_with_and_without_remat(self):
        from jax.sharding import Mesh
        from paddle_tpu.distributed.fleet.pipeline import spmd_pipeline
        pp, n_mb, mb, d = 2, 4, 2, 16
        mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))
        key = jax.random.PRNGKey(0)
        params = jax.random.normal(key, (pp * 2, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (n_mb, mb, d))

        def stage_fn(sp, xm):
            def body(c, w):
                return jnp.tanh(c @ w), None
            out, _ = jax.lax.scan(body, xm, sp)
            return out

        grads = []
        for remat in (True, False):
            from paddle_tpu.utils.compat import shard_map
            apply = spmd_pipeline(stage_fn, pp, n_mb, axis_name="pp",
                                  remat=remat)
            sm = shard_map(apply, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           axis_names={"pp"})
            grads.append(jax.jit(jax.grad(lambda p: sm(p, x).sum()))(params))
        np.testing.assert_allclose(np.asarray(grads[0]),
                                   np.asarray(grads[1]), atol=1e-5)

    def test_mp_is_manual_inside_pp_region(self):
        """VERDICT weak #6: GSPMD propagation does NOT shard mp activations
        inside the manual-pp region (measured: temps GROW with mp), so TP
        there is explicit Megatron SPMD — mp-local weight shards + psum
        over mp in _decoder_layer. Evidence: compiled temp bytes shrink
        ~proportionally when mp grows."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        ids = np.random.randint(0, 1024, (8, 128), dtype=np.int32)

        def temp_bytes(mp):
            paddle.seed(3)
            cfg = LlamaConfig(vocab_size=1024, hidden_size=512,
                              intermediate_size=1376, num_hidden_layers=4,
                              num_attention_heads=8, num_key_value_heads=4)
            model = LlamaForCausalLM(cfg)
            mesh = dist.ProcessMesh(
                shape=[1, 2, 1, 1, mp],
                dim_names=["dp", "pp", "sep", "ep", "mp"])
            dist.shard_model_state(model, mesh)

            def f(ids_arr):
                with sharding_ctx(mesh.jax_mesh):
                    return model(Tensor(ids_arr))._value

            c = jax.jit(f).lower(jnp.asarray(ids)).compile()
            return c.memory_analysis().temp_size_in_bytes

        t1, t4 = temp_bytes(1), temp_bytes(4)
        assert t4 < t1 * 0.6, (t1, t4)

    def test_manual_mp_parity_inside_pp(self):
        """pp=2 x mp=2 manual TP must reproduce single-device numerics."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_loss_fn)
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(7)
        model = LlamaForCausalLM("tiny")
        ids = paddle.to_tensor(
            np.random.randint(0, 1024, (4, 32), dtype=np.int32))
        ref_out = _np(model(ids))
        mesh = dist.ProcessMesh(shape=[1, 2, 1, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(model, mesh)
        with sharding_ctx(mesh.jax_mesh):
            out = _np(model(ids))
            loss = llama_loss_fn(model, ids, ids)
            loss.backward()
        assert np.allclose(out, ref_out, atol=1e-4)
        assert model._parameters["wq"].grad is not None

    def test_default_microbatches_above_pp(self):
        """VERDICT #1: default microbatch count must exceed pp when the
        batch allows (bubble (pp-1)/(n_mb+pp-1))."""
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.fleet import pipeline as plmod
        cfg = LlamaConfig()
        assert cfg.pp_num_microbatches == 0  # auto
        # the auto rule: 2*pp when divisible (asserted indirectly through
        # interleave_permutation used by the schedule builder)
        perm = plmod.interleave_permutation(8, 2, 2)
        # rank 0 holds stages 0 and 2 (layers 0,1 + 4,5); rank 1 holds
        # stages 1 and 3 (layers 2,3 + 6,7)
        assert perm == [0, 1, 4, 5, 2, 3, 6, 7]

    def test_interleave_wrapper_sets_config(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallelWithInterleave)
        model = LlamaForCausalLM("tiny")
        wrapped = PipelineParallelWithInterleave(
            model, num_virtual_pipeline_stages=2)
        assert model.config.pp_interleave == 2
        assert wrapped.virtual_pp_degree == 2

    def test_train_batch_returns_detached_loss(self):
        """VERDICT weak #8: the returned total must not pin the first
        microbatch's graph."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel)
        model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 1))
        model._loss_fn = lambda out, y: ((out - y) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())

        class S:
            pipeline_configs = {"accumulate_steps": 2}
        pipe = PipelineParallel(model, strategy=S())
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(np.random.randn(4, 1).astype("float32"))
        total = pipe.train_batch((x, y), opt)
        assert total.stop_gradient  # detached
        # eval_batch honors compute_loss=False: concatenated outputs
        out = pipe.eval_batch((x, y), compute_loss=False)
        assert out.shape[0] == 4
        loss = pipe.eval_batch((x, y), compute_loss=True)
        assert loss.shape in ([], [1])


class TestStrategyDrivenCompilation:
    """VERDICT #8: DistributedStrategy knobs must ALTER the compiled
    DistTrainStep, not just be stored."""

    def _recipe(self):
        """A PaddleNLP-style llama recipe dict, used unmodified."""
        return {
            "dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
            "amp": {"use_pure_fp16": False,
                    "custom_black_list": ["softmax"]},
            "recompute": {"granularity": "core_attn"},
            "gradient_merge": {"k_steps": 2, "avg": True},
            "pipeline": {"accumulate_steps": 4, "virtual_pp_degree": 2},
        }

    def _strategy(self, recipe):
        st = dist.fleet.DistributedStrategy()
        st.hybrid_configs = {**st.hybrid_configs,
                             "dp_degree": recipe["dp_degree"],
                             "mp_degree": recipe["mp_degree"],
                             "pp_degree": recipe["pp_degree"]}
        st.amp = True
        st.amp_configs.update(recipe["amp"])
        st.recompute = True
        st.recompute_configs.update(recipe["recompute"])
        st.gradient_merge = True
        st.gradient_merge_configs.update(recipe["gradient_merge"])
        st.pipeline = True
        st.pipeline_configs.update(recipe["pipeline"])
        return st

    def test_recipe_runs_and_steers_model_config(self):
        from paddle_tpu.models.llama import LlamaConfig, LLAMA_PRESETS, \
            LlamaForCausalLM, llama_loss_fn
        paddle.seed(2)
        cfg = LlamaConfig(**LLAMA_PRESETS["tiny"])
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        st = self._strategy(self._recipe())
        step = dist.DistTrainStep.from_strategy(
            model, opt, llama_loss_fn, st, donate=False)
        # knobs landed in the model config (observable compiled effects)
        assert cfg.recompute and cfg.recompute_granularity == "core_attn"
        assert cfg.pp_num_microbatches == 4
        assert cfg.pp_interleave == 2
        assert step.mesh.shape == [2, 2, 1, 1, 2]
        ids = paddle.to_tensor(
            np.random.randint(0, 1024, (8, 32), dtype=np.int32))
        l1 = float(step(ids, ids))
        l2 = float(step(ids, ids))
        assert np.isfinite(l1) and l2 < l1

    def test_gradient_merge_matches_manual_accumulation(self):
        """k_steps=2 inside the jitted step == two manual half-batch
        backwards with averaged grads + one update."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        mesh = dist.ProcessMesh(shape=[1, 1, 1, 1, 1],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 16), dtype=np.int32))

        paddle.seed(5)
        ref = LlamaForCausalLM("debug")
        ropt = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=ref.parameters())
        for sl in (slice(0, 2), slice(2, 4)):
            sub = paddle.to_tensor(np.asarray(ids._value)[sl])
            (llama_loss_fn(ref, sub, sub) / 2).backward()
        ropt.step()
        ropt.clear_grad()

        paddle.seed(5)
        model = LlamaForCausalLM("debug")
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        st = dist.fleet.DistributedStrategy()
        st.gradient_merge = True
        st.gradient_merge_configs.update({"k_steps": 2, "avg": True})
        step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh,
                                  donate=False, strategy=st)
        step(ids, ids)
        for (n, p), (_, rp) in zip(model.named_parameters(),
                                   ref.named_parameters()):
            assert np.allclose(_np(p), _np(rp), atol=1e-5), n

    def test_amp_knob_changes_compiled_dtypes(self):
        """strategy.amp must put bf16 matmuls into the compiled program."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        mesh = dist.ProcessMesh(shape=[1, 1, 1, 1, 1],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16), dtype=np.int32))

        def lowered_text(amp_on):
            paddle.seed(5)
            model = LlamaForCausalLM("debug")
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters())
            st = dist.fleet.DistributedStrategy()
            st.amp = amp_on
            step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh,
                                      donate=False, strategy=st)
            step(ids, ids)
            return step._jitted.lower(
                [p._value for p in step._params],
                [b._value for b in step._buffers],
                {k: list(v) for k, v in opt._accumulators.items()},
                jax.random.PRNGKey(0), jnp.asarray(0, jnp.int32),
                jnp.asarray(0.1, jnp.float32),
                (ids._value, ids._value)).as_text()

        assert "bf16" in lowered_text(True)
        assert "bf16" not in lowered_text(False)

    def test_inert_knob_warns_once(self):
        """VERDICT r3 weak #8: a stored-but-unconsumed knob set to a
        non-default value produces a one-time warning when the strategy
        is applied; consumed knobs never warn."""
        st = dist.fleet.DistributedStrategy()
        st.pipeline = True
        st.pipeline_configs = {"accumulate_steps": 2,
                               "schedule_mode": "FThenB"}
        st.sharding = True
        st.sharding_configs = {"stage": 2, "optimize_offload": True}
        with pytest.warns(RuntimeWarning, match="NOT consumed") as rec:
            st._warn_inert_knobs()
        msg = str(rec[0].message)
        assert "pipeline_configs.schedule_mode" in msg
        assert "sharding_configs.optimize_offload" in msg
        assert "accumulate_steps" not in msg
        import warnings as _w
        with _w.catch_warnings(record=True) as again:
            _w.simplefilter("always")
            st._warn_inert_knobs()
        assert not again

        clean = dist.fleet.DistributedStrategy()
        clean.gradient_merge = True
        clean.gradient_merge_configs = {"k_steps": 2}
        with _w.catch_warnings(record=True) as none:
            _w.simplefilter("always")
            clean._warn_inert_knobs()
        assert not none

    def test_proto_surface_accepts_reference_recipe_keys(self):
        st = dist.fleet.DistributedStrategy()
        # a sample of proto fields reference recipes set
        st.amp_configs["use_dynamic_loss_scaling"] = False
        st.sharding_configs["sharding_segment_strategy"] = "segment_anchors"
        st.pipeline_configs["enable_partial_send_recv"] = False
        st.hybrid_configs["pp_configs"]["dp_comm_overlap"] = True
        st.downpour_table_param["accessor"]["embedx_dim"] = 16
        st.trainer_desc_configs["dump_fields"] = ["loss"]
        assert st.hybrid_configs["pp_configs"]["dp_comm_overlap"]


class TestPipelineSepComposition:
    def test_pp_sep_mp_ring_inside_pipeline(self):
        """pp>1 + sep>1 + mp>1 (VERDICT weak #6 closed): the sequence
        stays SHARDED inside the manual-pp region and attention runs the
        ring body over the sep axis — forward, loss, and grads must match
        the single-device model."""
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_loss_fn)
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(4)
        model = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32), dtype=np.int32))
        ref_out = _np(model(ids))
        mesh = dist.ProcessMesh(shape=[1, 2, 2, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(model, mesh)
        with sharding_ctx(mesh.jax_mesh):
            out = _np(model(ids))
            loss = llama_loss_fn(model, ids, ids)
            loss.backward()
        assert np.allclose(out, ref_out, atol=1e-4)
        g = {n: _np(p.grad) for n, p in model.named_parameters()
             if p.grad is not None}
        paddle.seed(4)
        ref = LlamaForCausalLM("debug")
        rl = llama_loss_fn(ref, ids, ids)
        rl.backward()
        assert abs(float(loss) - float(rl)) < 1e-4
        for n, p in ref.named_parameters():
            if p.grad is None:
                continue
            assert np.allclose(g[n], _np(p.grad), atol=1e-3), n

    def test_pp_sep_moe_runs(self):
        """pp x sep with MoE layers: local-per-shard routing + pp aux
        accumulation compiles and produces a finite loss."""
        from paddle_tpu.models.llama import (LlamaConfig, LLAMA_PRESETS,
                                             LlamaForCausalLM,
                                             llama_loss_fn)
        from paddle_tpu.distributed.fleet.mp_layers import sharding_ctx
        paddle.seed(6)
        model = LlamaForCausalLM(LlamaConfig(**LLAMA_PRESETS["tiny-moe"]))
        ids = paddle.to_tensor(
            np.random.randint(0, 1024, (4, 32), dtype=np.int32))
        mesh = dist.ProcessMesh(shape=[1, 2, 2, 1, 2],
                                dim_names=["dp", "pp", "sep", "ep", "mp"])
        dist.shard_model_state(model, mesh)
        with sharding_ctx(mesh.jax_mesh):
            loss = llama_loss_fn(model, ids, ids)
        assert np.isfinite(float(loss))


@pytest.mark.slow  # multi-process subprocess harnesses (tier-1 filters
class TestLaunchCLI:  # -m 'not slow'; run explicitly with -m slow)
    def test_two_process_rendezvous_and_comm(self, tmp_path):
        """VERDICT #7: python -m paddle_tpu.distributed.launch spawns per
        -host workers with PADDLE_TRAINER_* env; 2-process CPU rendezvous
        exercises every eager cross-host collective incl. send/recv and
        batch_isend_irecv (reference launch/main.py:18,
        test_parallel_dygraph_dataparallel.py:157 harness)."""
        import subprocess, sys, os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "launch_worker.py")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", str(tmp_path), worker],
            cwd=root, capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stdout + r.stderr
        log1 = (tmp_path / "workerlog.1").read_text()
        assert "COMM_OK" in log1, log1

    def test_three_process_subgroup_collectives(self, tmp_path):
        """VERDICT #7: a 2-of-3 eager subgroup allreduce (+ broadcast /
        all_to_all / reduce_scatter) over the per-group KV namespace —
        the non-member rank is never blocked."""
        import subprocess, sys, os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "launch_worker_subgroup.py")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "3", "--log_dir", str(tmp_path), worker],
            cwd=root, capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stdout + r.stderr
        for i in range(3):
            log = (tmp_path / f"workerlog.{i}").read_text()
            assert "SUBGROUP_OK" in log, (i, log)

    def test_two_process_compiled_gspmd_parity(self, tmp_path):
        """VERDICT r3 #2: compiled GSPMD collectives ACROSS a process
        boundary. The same worker runs (a) single-process on 8 local CPU
        devices and (b) 2 processes × 4 CPU devices under the launch CLI
        sharing ONE 8-device mesh via jax.distributed — a DistTrainStep
        with dp×mp + ZeRO-2 must produce identical losses. This is the
        one-process-per-host shape of a real v5p pod (reference
        test_parallel_dygraph_dataparallel.py:157)."""
        import json, subprocess, sys, os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "launch_worker_gspmd.py")

        def losses_from(text, tag="GSPMD_LOSSES "):
            for line in text.splitlines():
                if line.startswith(tag):
                    return json.loads(line[len(tag):])
            raise AssertionError(f"no {tag!r} in:\n{text}")

        env = dict(os.environ, GSPMD_LOCAL_DEVICES="8",
                   PYTHONPATH=root)
        single = subprocess.run([sys.executable, worker], cwd=root,
                                env=env, capture_output=True, text=True,
                                timeout=300)
        assert single.returncode == 0, single.stdout + single.stderr
        ref = losses_from(single.stdout)

        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", str(tmp_path), worker],
            cwd=root, capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        ref_local = losses_from(single.stdout, "GSPMD_LOSSES_LOCAL ")
        np.testing.assert_allclose(ref_local, ref, rtol=1e-6)
        for i in range(2):
            text = (tmp_path / f"workerlog.{i}").read_text()
            np.testing.assert_allclose(losses_from(text), ref, rtol=1e-6)
            np.testing.assert_allclose(
                losses_from(text, "GSPMD_LOSSES_LOCAL "), ref, rtol=1e-6)

    def test_launch_propagates_failure(self, tmp_path):
        import subprocess, sys
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", str(tmp_path), str(bad)],
            cwd=root, capture_output=True, text=True, timeout=120)
        assert r.returncode == 3


class TestCheckNanInf:
    def test_eager_raises(self):
        paddle.set_flags({"check_nan_inf": True})
        try:
            with pytest.raises(FloatingPointError):
                paddle.log(paddle.to_tensor([-1.0]))
        finally:
            paddle.set_flags({"check_nan_inf": False})

    def test_jit_safe(self):
        """Under a trace the check must not crash tracing (VERDICT weak #8:
        bool() on a tracer raised TracerBoolConversionError); it reports
        at runtime via debug callback."""
        paddle.set_flags({"check_nan_inf": True})
        try:
            from paddle_tpu.core.tensor import Tensor

            def f(x):
                return paddle.exp(Tensor(x))._value

            out = jax.jit(f)(jnp.zeros((2,)))  # finite: no error
            assert np.allclose(np.asarray(out), 1.0)
            with pytest.raises(Exception):
                jax.block_until_ready(jax.jit(f)(jnp.full((2,), 1e30)))
        finally:
            paddle.set_flags({"check_nan_inf": False})


class TestAutoCheckpoint:
    """VERDICT #10: async orbax save + TTL auto-checkpoint keyed to the
    elastic store; relaunch resumes from the last COMPLETE snapshot."""

    @pytest.mark.slow  # two full subprocess train runs
    def test_kill_and_relaunch_resumes_step(self, tmp_path):
        import subprocess, sys, os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(root, "tests", "autockpt_worker.py")
        # first run crashes hard at step 6 (after the step-6 snapshot)
        r1 = subprocess.run([sys.executable, worker, str(tmp_path), "6"],
                            capture_output=True, text=True, timeout=180,
                            cwd=root)
        assert r1.returncode == 101, r1.stdout + r1.stderr
        assert "RESUMED_AT 0" in r1.stdout
        # relaunch: must resume from the recorded step (6) and finish
        r2 = subprocess.run([sys.executable, worker, str(tmp_path), "-1"],
                            capture_output=True, text=True, timeout=180,
                            cwd=root)
        assert r2.returncode == 0, r2.stdout + r2.stderr
        assert "RESUMED_AT 6" in r2.stdout, r2.stdout
        assert "DONE 10" in r2.stdout

    def test_auto_checkpoint_records_only_complete_snapshots(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint
        from paddle_tpu.distributed.fleet.elastic import FileKVStore
        paddle.seed(1)
        model = nn.Linear(4, 2)
        store = FileKVStore(str(tmp_path / "store"))
        auto = AutoCheckpoint("m", model, save_dir=str(tmp_path / "ck"),
                              store=store, every_n_steps=1)
        assert auto.resume() == 0          # fresh start
        auto.step(1)
        auto.wait()
        rec = store.get("ptpu_ckpt/m")
        assert rec and rec["step"] == 1
        # mutate weights, resume, weights restored
        w0 = _np(model.weight).copy()
        with paddle.no_grad():
            model.weight.fill_(123.0)
        assert auto.resume() == 1
        np.testing.assert_allclose(_np(model.weight), w0, atol=1e-6)

    def test_adam_moments_and_scheduler_survive_relaunch(self, tmp_path):
        """Optimizer slots restore through set_state_dict into the LIVE
        accumulators (fresh wrappers from state_dict() don't reach them),
        and the LR scheduler state rides the KV record."""
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint
        from paddle_tpu.distributed.fleet.elastic import FileKVStore
        store = FileKVStore(str(tmp_path / "store"))

        def make():
            paddle.seed(3)
            m = nn.Linear(4, 2)
            sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                                  step_size=2)
            o = paddle.optimizer.Adam(learning_rate=sched,
                                      parameters=m.parameters())
            return m, o

        m1, o1 = make()
        x = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        for _ in range(3):
            (m1(x) ** 2).mean().backward()
            o1.step()
            o1.clear_grad()
            o1._lr_scheduler.step()
        auto1 = AutoCheckpoint("adam", m1, optimizer=o1,
                               save_dir=str(tmp_path / "ck"), store=store,
                               every_n_steps=1)
        auto1.step(3)
        auto1.wait()
        mom = np.asarray(o1._accumulators["moment1"][0])

        # fresh process analogue: new model + optimizer, resume
        m2, o2 = make()
        auto2 = AutoCheckpoint("adam", m2, optimizer=o2,
                               save_dir=str(tmp_path / "ck"), store=store,
                               every_n_steps=1)
        assert auto2.resume() == 3
        np.testing.assert_allclose(
            np.asarray(o2._accumulators["moment1"][0]), mom, atol=1e-7)
        assert o2._global_step == 3
        assert o2._lr_scheduler.last_epoch == o1._lr_scheduler.last_epoch

    def test_gc_keeps_last_snapshots(self, tmp_path):
        import os
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint
        from paddle_tpu.distributed.fleet.elastic import FileKVStore
        model = nn.Linear(4, 2)
        store = FileKVStore(str(tmp_path / "store"))
        auto = AutoCheckpoint("m", model, save_dir=str(tmp_path / "ck"),
                              store=store, every_n_steps=1, keep_last=2)
        for s in (1, 2, 3, 4):
            auto.step(s)
            auto.wait()
        kept = sorted(d for d in os.listdir(str(tmp_path / "ck"))
                      if d.startswith("step_"))
        assert kept == ["step_3", "step_4"], kept

    def test_hapi_callback_resumes(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import FileKVStore
        from paddle_tpu.hapi.callbacks import AutoCheckpointCallback
        import paddle_tpu.hapi as hapi

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                x = np.full((8,), float(i % 4), np.float32)
                return x, x[:1]

        store = FileKVStore(str(tmp_path / "store"))

        def run():
            paddle.seed(0)
            net = nn.Linear(8, 1)
            model = hapi.Model(net)
            model.prepare(paddle.optimizer.SGD(
                learning_rate=0.01, parameters=net.parameters()),
                nn.MSELoss())
            cb = AutoCheckpointCallback("h", every_n_steps=2,
                                        save_dir=str(tmp_path / "ck"),
                                        store=store)
            model.fit(DS(), batch_size=8, epochs=1, callbacks=[cb],
                      verbose=0)
            return cb

        cb1 = run()
        assert cb1.start_step == 0
        cb2 = run()                       # second fit resumes from store
        assert cb2.start_step > 0
        # resumed fit must SKIP completed steps, not double-train
        assert cb2._global_step == cb1._global_step


class TestReshardTaxonomy:
    """Reshard-function taxonomy (SURVEY item 16; reference
    phi/core/distributed/auto_parallel/*_reshard_function.cc: r_to_s,
    s_to_r, s_to_s, same_status, nd_mesh, cross-mesh): each conversion
    preserves the global value and lands the expected per-device shards."""

    def _x(self):
        return paddle.arange(0, 64, dtype="float32").reshape([8, 8])

    def test_r_to_s_and_back(self):
        m = dist.ProcessMesh(shape=[8], dim_names=["x"])
        x = self._x()
        xs = dist.shard_tensor(x, m, [dist.Shard(0)])       # r_to_s
        assert xs._value.addressable_shards[0].data.shape == (1, 8)
        xr = dist.reshard(xs, m, [dist.Replicate()])        # s_to_r
        assert xr._value.addressable_shards[0].data.shape == (8, 8)
        assert np.allclose(_np(xr), _np(x))

    def test_s_to_s_dim_change(self):
        m = dist.ProcessMesh(shape=[8], dim_names=["x"])
        xs = dist.shard_tensor(self._x(), m, [dist.Shard(0)])
        xt = dist.reshard(xs, m, [dist.Shard(1)])           # s0 -> s1
        assert xt._value.addressable_shards[0].data.shape == (8, 1)
        assert np.allclose(_np(xt), _np(self._x()))

    def test_nd_mesh_both_dims(self):
        m = dist.ProcessMesh(shape=[2, 4], dim_names=["a", "b"])
        xs = dist.shard_tensor(self._x(), m,
                               [dist.Shard(0), dist.Shard(1)])
        assert xs._value.addressable_shards[0].data.shape == (4, 2)
        flipped = dist.reshard(xs, m, [dist.Shard(1), dist.Shard(0)])
        assert flipped._value.addressable_shards[0].data.shape == (2, 4)
        assert np.allclose(_np(flipped), _np(self._x()))

    def test_cross_mesh(self):
        """reference nd_mesh/cross-mesh reshard: topology change 1D->2D."""
        mA = dist.ProcessMesh(shape=[8], dim_names=["x"])
        mB = dist.ProcessMesh(shape=[2, 4], dim_names=["a", "b"])
        xs = dist.shard_tensor(self._x(), mA, [dist.Shard(0)])
        xc = dist.reshard(xs, mB, [dist.Shard(1), dist.Shard(0)])
        assert xc._value.addressable_shards[0].data.shape == (2, 4)
        assert np.allclose(_np(xc), _np(self._x()))
        assert xc.dist_attr.process_mesh is mB

    def test_same_status_noop(self):
        m = dist.ProcessMesh(shape=[8], dim_names=["x"])
        xs = dist.shard_tensor(self._x(), m, [dist.Shard(0)])
        again = dist.reshard(xs, m, [dist.Shard(0)])
        assert np.allclose(_np(again), _np(self._x()))
        assert again._value.sharding == xs._value.sharding


class TestSpmdPropagationRules:
    """Per-op sharding propagation (SURVEY item 15; reference
    infermeta/spmd_rules/ matmul/elementwise/embedding/reduction/softmax/
    transpose): GSPMD must derive the canonical output shardings from the
    input shardings — the TPU substitute for hand-written InferSpmd."""

    def _mesh(self):
        return dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])

    def _spec_of(self, arr):
        return arr.sharding.spec if hasattr(arr.sharding, "spec") else None

    def _run(self, fn, *arrs_specs):
        from jax.sharding import NamedSharding
        m = self._mesh().jax_mesh
        args = [jax.device_put(a, NamedSharding(m, s))
                for a, s in arrs_specs]
        return jax.jit(fn)(*args)

    def test_matmul_rule(self):
        # [b sharded dp, k] @ [k, n sharded mp] -> [dp, mp]
        a = jnp.ones((8, 16))
        b = jnp.ones((16, 32))
        out = self._run(lambda x, w: x @ w, (a, P("dp", None)),
                        (b, P(None, "mp")))
        assert self._spec_of(out) == P("dp", "mp")

    def test_matmul_contraction_partial_resolved(self):
        # contraction over an mp-sharded dim: output must be materialized
        # (GSPMD inserts the reduction; result spec has no mp on k)
        a = jnp.ones((8, 16))
        b = jnp.ones((16, 32))
        out = self._run(lambda x, w: x @ w, (a, P(None, "mp")),
                        (b, P("mp", None)))
        assert np.allclose(np.asarray(out), 16.0)

    def test_elementwise_and_softmax_keep_sharding(self):
        a = jnp.ones((8, 32))
        out = self._run(lambda x: jax.nn.softmax(x * 2.0, axis=-1),
                        (a, P("dp", "mp")))
        assert self._spec_of(out) == P("dp", "mp")

    def test_reduction_rule(self):
        a = jnp.ones((8, 32))
        out = self._run(lambda x: x.sum(axis=1), (a, P("dp", "mp")))
        # reduced dim's sharding disappears; batch dim's stays
        assert self._spec_of(out)[:1] == P("dp")

    def test_transpose_rule(self):
        a = jnp.ones((8, 32))
        out = self._run(lambda x: x.T, (a, P("dp", "mp")))
        assert self._spec_of(out) == P("mp", "dp")

    def test_embedding_rule(self):
        # vocab-sharded table gather -> replicated-row output, correct
        # values (reference embedding.h InferSpmd)
        table = jnp.arange(64.0).reshape(32, 2)
        ids = jnp.asarray(np.array([[1, 5], [7, 31]], np.int32))
        out = self._run(lambda t, i: jnp.take(t, i, axis=0),
                        (table, P("mp", None)), (ids, P(None, None)))
        assert np.allclose(np.asarray(out),
                           np.take(np.asarray(table), np.asarray(ids), 0))


@pytest.mark.slow  # 2-process launch-CLI harnesses, minutes each
class TestMultiControllerCheckpoint:
    """VERDICT r4 #4: checkpoint/resume in the 2-process GSPMD harness —
    the one topology the v5p north star actually uses."""

    def _run(self, worker, env=None, argv=(), nproc=2, log_dir=None,
             timeout=420):
        import os, subprocess, sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nproc_per_node", str(nproc)]
        if log_dir is not None:
            cmd += ["--log_dir", str(log_dir)]
        cmd += [worker, *argv]
        return subprocess.run(cmd, cwd=root, env=dict(os.environ,
                                                      **(env or {})),
                              capture_output=True, text=True,
                              timeout=timeout)

    @staticmethod
    def _tagged(text, tag):
        import json
        for line in text.splitlines():
            if line.startswith(tag + " "):
                return json.loads(line[len(tag) + 1:])
        raise AssertionError(f"no {tag!r} in:\n{text}")

    def test_two_process_orbax_save_load_and_crosstopo(self, tmp_path):
        """Save is a collective orbax write across 2 processes sharing a
        [dp=2, mp=4] mesh; reload + replay is bit-exact; the same
        checkpoint then restores into a single-process [dp=1, mp=8]
        mesh (cross-topology reshard-on-load) with loss parity."""
        import os, subprocess, sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(root, "tests", "launch_worker_gspmd.py")
        ck = tmp_path / "ck"
        logs = tmp_path / "logs"
        r = self._run(worker, env={"GSPMD_CKPT_DIR": str(ck)},
                      log_dir=logs)
        assert r.returncode == 0, r.stdout + r.stderr
        posts = []
        for i in range(2):
            text = (logs / f"workerlog.{i}").read_text()
            post = self._tagged(text, "GSPMD_CKPT_POST")
            replay = self._tagged(text, "GSPMD_CKPT_REPLAY")
            assert post == replay, (post, replay)   # bit-exact replay
            posts.append(post)
        assert posts[0] == posts[1]                 # ranks agree

        # cross-topology: [2, 4] checkpoint -> [1, 8] mesh, 1 process
        r2 = subprocess.run(
            [sys.executable, worker], cwd=root,
            env=dict(os.environ, GSPMD_LOCAL_DEVICES="8",
                     GSPMD_LOAD_DIR=str(ck), PYTHONPATH=root),
            capture_output=True, text=True, timeout=300)
        assert r2.returncode == 0, r2.stdout + r2.stderr
        cross = self._tagged(r2.stdout, "GSPMD_CROSSTOPO_POST")
        np.testing.assert_allclose(cross, posts[0], rtol=1e-4)

    def test_kill_one_rank_relaunch_resumes_with_loss_parity(
            self, tmp_path):
        """Rank 1 dies hard (os._exit 101) at step 6; the launcher reaps
        the pod; a relaunch resumes BOTH ranks from the last advertised
        orbax snapshot and steps 7-10 match an uninterrupted run
        bit-exactly."""
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(root, "tests", "autockpt_worker_gspmd.py")

        ref = self._run(worker, argv=(str(tmp_path / "ref"), "-1"),
                        log_dir=tmp_path / "l1")
        assert ref.returncode == 0, ref.stdout + ref.stderr
        ref_losses = dict(self._tagged(
            (tmp_path / "l1" / "workerlog.0").read_text(), "LOSSES"))

        crash = self._run(worker, argv=(str(tmp_path / "wd"), "6"),
                          log_dir=tmp_path / "l2")
        assert crash.returncode == 101, crash.stdout + crash.stderr

        resume = self._run(worker, argv=(str(tmp_path / "wd"), "-1"),
                           log_dir=tmp_path / "l3")
        assert resume.returncode == 0, resume.stdout + resume.stderr
        for i in range(2):
            text = (tmp_path / "l3" / f"workerlog.{i}").read_text()
            assert "RESUMED_AT 6" in text, text
        got = dict(self._tagged(
            (tmp_path / "l3" / "workerlog.0").read_text(), "LOSSES"))
        assert set(got) == {7, 8, 9, 10}
        for s, loss in got.items():
            assert loss == ref_losses[s], (s, loss, ref_losses[s])
