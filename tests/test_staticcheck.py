"""graftcheck framework tests (ISSUE 11 tentpole + ISSUE 12 call-graph
layer): per-checker positive/negative fixtures driven through embedded
source strings (no temp files — ``SourceFile.from_source`` parses in
memory), suppression and unused-suppression behavior, CLI ``--json`` /
``--format=github`` shape, byte-equivalence of the SC01/SC02 ports
against inline reimplementations of the pre-framework lints, callgraph
resolution/reachability units, and the zero-findings gate over the real
scan set at HEAD.
"""

import ast
import json

import pytest

from paddle_tpu.staticcheck import (AdhocTimerChecker, CallGraph,
                                    DonationDisciplineChecker, Finding,
                                    HostSyncChecker,
                                    LockDisciplineChecker,
                                    MetricsSchemaChecker,
                                    RecompileHazardChecker, SourceFile,
                                    SilentExceptChecker,
                                    StepPathBlockingChecker,
                                    UNUSED_SUPPRESSION_ID,
                                    UnseededRandomChecker,
                                    all_checker_classes, checker_by_id,
                                    run)
from paddle_tpu.staticcheck.__main__ import expand_checker_ids
from paddle_tpu.staticcheck.__main__ import main as cli_main
from paddle_tpu.staticcheck import callgraph, config, host_sync, util

pytestmark = pytest.mark.staticcheck


def _check(checker_cls, text, name="fx.py"):
    """Raw checker findings over an embedded fixture (no suppression
    layer — that is run()'s job and tested separately)."""
    src = SourceFile.from_source(name, text)
    return list(checker_cls().check(src))


def _lines(findings):
    return sorted(f.line for f in findings)


# -- core: findings, registry, directives -----------------------------------

def test_finding_order_is_file_line_checker_message():
    fs = [Finding("b.py", 1, "SC02", "m"),
          Finding("a.py", 9, "SC05", "m"),
          Finding("a.py", 2, "SC03", "z"),
          Finding("a.py", 2, "SC03", "a")]
    assert sorted(fs) == [Finding("a.py", 2, "SC03", "a"),
                          Finding("a.py", 2, "SC03", "z"),
                          Finding("a.py", 9, "SC05", "m"),
                          Finding("b.py", 1, "SC02", "m")]
    assert fs[0].render() == "b.py:1: SC02 m"


def test_registry_has_the_nine_checkers():
    ids = [c.id for c in all_checker_classes()]
    assert ids == ["SC01", "SC02", "SC03", "SC04", "SC05",
                   "SC06", "SC07", "SC08", "SC09"]
    assert checker_by_id("SC03") is HostSyncChecker
    assert checker_by_id("SC07") is StepPathBlockingChecker
    with pytest.raises(KeyError):
        checker_by_id("SC99")
    # the interprocedural layer is explicit about which checkers ride
    # the shared CallGraph
    proj = {c.id for c in all_checker_classes() if c.project}
    assert proj == {"SC07", "SC08"}


def test_sourcefile_parses_comment_directives():
    src = SourceFile.from_source("d.py", (
        "x = 1  # staticcheck: disable=SC04, SC05\n"
        "self._m = {}   # guarded-by: _lock\n"
        "def f(self):   # staticcheck: holds=_mu\n"
        "    pass\n"))
    assert src.suppressions == {1: {"SC04", "SC05"}}
    assert src.guarded_by == {2: "_lock"}
    assert src.holds == {3: "_mu"}
    assert src.virtual


# -- SC01 no-adhoc-timers ---------------------------------------------------

def test_sc01_flags_both_spellings_and_exempts_alias_def():
    fs = _check(AdhocTimerChecker, (
        "t0 = time.perf_counter()\n"
        "t1 = time.monotonic()\n"
        "now = time.perf_counter\n"       # the alias definition itself
        "dt = now() - t0\n"))
    assert _lines(fs) == [1, 2]
    assert all(f.checker_id == "SC01" for f in fs)
    assert "observability.now" in fs[0].message


def test_sc01_inference_tier_allows_monotonic():
    """The historic two-tier rule: inference/ bans only perf_counter;
    observability/+watchdog ban monotonic too."""
    chk = AdhocTimerChecker()
    serving = config.PKG / "inference" / "serving.py"
    src = SourceFile.from_path(serving, config.REPO_ROOT)
    banned, allow_alias = chk._banned(src)
    assert banned == ("time.perf_counter",) and not allow_alias
    metrics = config.PKG / "observability" / "metrics.py"
    src = SourceFile.from_path(metrics, config.REPO_ROOT)
    banned, allow_alias = chk._banned(src)
    assert banned == ("time.perf_counter", "time.monotonic")
    assert allow_alias


# -- SC02 no-silent-except --------------------------------------------------

def test_sc02_flags_silent_and_exempts_loud_and_narrow():
    fs = _check(SilentExceptChecker, (
        "try:\n"
        "    pass\n"
        "except ValueError:\n"
        "    pass\n"                       # narrow: exempt
        "except Exception:\n"
        "    pass\n"                       # broad + silent: finding (5)
        "try:\n"
        "    pass\n"
        "except Exception as e:\n"
        "    log_kv(_log, 'x', err=e)\n"   # loud: exempt
        "try:\n"
        "    pass\n"
        "except BaseException:\n"
        "    raise\n"                      # re-raise: exempt
        "try:\n"
        "    pass\n"
        "except Exception:\n"
        "    self._c_errors.inc()\n"       # error counter: exempt
        "try:\n"
        "    pass\n"
        "except Exception:\n"
        "    req.error = 'boom'\n"))       # surfaced on request: exempt
    assert _lines(fs) == [5]
    assert fs[0].checker_id == "SC02"


def test_sc02_records_examined_handlers():
    chk = SilentExceptChecker()
    src = SourceFile.from_source("h.py", (
        "try:\n    pass\nexcept Exception:\n    raise\n"
        "try:\n    pass\nexcept KeyError:\n    pass\n"))
    assert not list(chk.check(src))
    assert chk.broad_handlers == [("h.py", 3)]   # narrow not recorded


# -- SC03 host-sync-in-traced-code ------------------------------------------

SC03_FIXTURE = """\
import jax, functools
import numpy as np

def step(tok, lens):
    if lens > 0:                 # finding: dynamic `if`
        x = float(tok)           # finding: host cast
    y = tok.item()               # finding: device->host copy
    z = np.asarray(lens)         # finding: host materialization
    if tok is None:              # exempt: identity test
        pass
    if tok.shape[0] > 1:         # exempt: trace-static attr
        pass
    if len(lens) > 2:            # exempt: trace-static call
        pass
    return tok

prog = jax.jit(step)
"""


def test_sc03_flags_host_syncs_in_jitted_function():
    fs = _check(HostSyncChecker, SC03_FIXTURE)
    assert _lines(fs) == [5, 6, 7, 8]
    assert all("'step'" in f.message for f in fs)


def test_sc03_untraced_function_is_exempt():
    fs = _check(HostSyncChecker, (
        "def plain(a):\n"
        "    if a:\n"
        "        return float(a)\n"
        "    return 0\n"))
    assert fs == []


def test_sc03_decorator_forms():
    fs = _check(HostSyncChecker, (
        "import jax, functools\n"
        "@jax.jit\n"
        "def f(a):\n"
        "    return bool(a)\n"             # finding (4)
        "@functools.partial(jax.jit, static_argnames=('n',))\n"
        "def g(x, n):\n"
        "    if n:\n"                      # exempt: static_argnames
        "        pass\n"
        "    assert x\n"                   # finding (9)
        "@jax.jit\n"
        "def h(x, m):\n"
        "    return x if m else -x\n"))    # finding (12): ternary
    assert _lines(fs) == [4, 9, 12]


def test_sc03_static_argnums_and_partial_positionals():
    fs = _check(HostSyncChecker, (
        "import jax, functools\n"
        "def gen(cfg, n, x):\n"
        "    if n > 1:\n"                  # exempt: partial-bound
        "        pass\n"
        "    while x:\n"                   # finding (5)
        "        break\n"
        "f = jax.jit(functools.partial(gen, None, 5))\n"
        "def k(a, b):\n"
        "    return a and b\n"             # finding (9), b only
        "g = jax.jit(k, static_argnums=(0,))\n"))
    assert _lines(fs) == [5, 9]
    msgs = "\n".join(f.message for f in fs)
    assert "'x'" in msgs and "'b'" in msgs and "'a'" not in msgs


def test_sc03_factory_returned_program_is_traced():
    fs = _check(HostSyncChecker, (
        "import jax\n"
        "def make_decode(n):\n"
        "    def decode_chunk(state, tok):\n"
        "        if tok:\n"                # finding (4)
        "            return state\n"
        "        return state\n"
        "    return decode_chunk\n"
        "prog = jax.jit(make_decode(4))\n"))
    assert _lines(fs) == [4]
    assert "'decode_chunk'" in fs[0].message


def test_sc03_pallas_partial_kernel_and_control_hofs():
    fs = _check(HostSyncChecker, (
        "import functools\n"
        "from jax.experimental import pallas as pl\n"
        "import jax.lax as lax\n"
        "def _kern(q_ref, o_ref, *, bs):\n"
        "    if bs:\n"                     # exempt: partial kwarg
        "        pass\n"
        "    if q_ref:\n"                  # finding (7)
        "        pass\n"
        "kernel = functools.partial(_kern, bs=8)\n"
        "pl.pallas_call(kernel)\n"
        "def body(carry, x):\n"
        "    assert carry\n"               # finding (12)
        "    return carry, x\n"
        "lax.scan(body, 0, None)\n"))
    assert _lines(fs) == [7, 12]


def test_sc03_attribute_alias_to_factory():
    fs = _check(HostSyncChecker, (
        "import jax\n"
        "def make_prefill(sc):\n"
        "    def prefill(ids, lm):\n"
        "        if lm is None:\n"         # exempt: identity
        "            lm = ids\n"
        "        return ids.tolist()\n"    # finding (6)
        "    return prefill\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._make_prefill = make_prefill\n"
        "    def compile(self, sc):\n"
        "        return jax.jit(self._make_prefill(sc))\n"))
    assert _lines(fs) == [6]


# -- SC04 unseeded-nondeterminism -------------------------------------------

def test_sc04_global_rng_and_unseeded_constructors():
    fs = _check(UnseededRandomChecker, (
        "import random\n"
        "import numpy as np\n"
        "r = random.random()\n"            # finding
        "random.shuffle(items)\n"          # finding
        "g = np.random.default_rng()\n"    # finding: unseeded ctor
        "h = np.random.default_rng(0)\n"   # exempt: seeded
        "k = np.random.rand(3)\n"          # finding
        "ok = random.Random(42)\n"         # exempt: seeded ctor
        "m = rng.random()\n"               # exempt: instance method
        "j = jax.random.normal(key)\n"))   # exempt: key-based
    assert _lines(fs) == [3, 4, 5, 7]


def test_sc04_set_iteration():
    fs = _check(UnseededRandomChecker, (
        "for x in {1, 2}:\n"               # finding
        "    pass\n"
        "for y in set(items):\n"           # finding
        "    pass\n"
        "l = list({v for v in vs})\n"      # finding
        "ok = sorted(set(items))\n"        # exempt: sorted
        "for z in [1, 2]:\n"               # exempt: list
        "    pass\n"
        "d = [k for k in set(ws)]\n"))     # finding
    assert _lines(fs) == [1, 3, 5, 9]


# -- SC05 lock-discipline ---------------------------------------------------

SC05_FIXTURE = """\
import threading

class Reg:
    def __init__(self):
        self._m = {}                       # guarded-by: _lock
        self._lock = threading.Lock()
    def get(self, k):
        return self._m.get(k)              # finding (8): read
    def put(self, k, v):
        with self._lock:
            self._m[k] = v                 # ok: lock held
    def clear(self):
        self._m = {}                       # finding (13): write
    def _sweep_locked(self):
        return len(self._m)                # ok: _locked suffix
    def peek(self, k):                     # staticcheck: holds=_lock
        return self._m[k]                  # ok: caller-holds contract
    def bind(self):
        return lambda: len(self._m)        # finding (19): deferred
    def other(self):
        return self._unrelated             # ok: not guarded
"""


def test_sc05_guarded_attr_accesses():
    fs = _check(LockDisciplineChecker, SC05_FIXTURE)
    assert _lines(fs) == [8, 13, 19]
    by_line = {f.line: f.message for f in fs}
    assert by_line[8].startswith("read of '_m'")
    assert by_line[13].startswith("write of '_m'")
    assert "bind()" in by_line[19]


def test_sc05_nested_function_does_not_inherit_lock():
    """A closure created INSIDE a with-lock block runs later (gauge
    callbacks run on the scrape thread) with no lock held — the bug
    class the fleet's fn-gauges actually had."""
    fs = _check(LockDisciplineChecker, (
        "class G:\n"
        "    def __init__(self):\n"
        "        self._n = 0            # guarded-by: _lock\n"
        "        self._lock = object()\n"
        "    def install(self, reg):\n"
        "        with self._lock:\n"
        "            reg.gauge('d', fn=lambda: self._n)\n"))
    assert _lines(fs) == [7]


def test_sc05_no_annotations_no_findings():
    fs = _check(LockDisciplineChecker, (
        "class P:\n"
        "    def __init__(self):\n"
        "        self._m = {}\n"
        "    def get(self, k):\n"
        "        return self._m.get(k)\n"))
    assert fs == []


# -- callgraph: resolution, edges, reachability (ISSUE 12 tentpole) ---------

CG_FIXTURE = """\
import jax

def make_decode(n):
    def decode_chunk(state):
        return state
    return decode_chunk

def helper(x):
    return x

class Engine:
    def __init__(self):
        self._make_decode = make_decode
    def compile(self, n):
        return jax.jit(self._make_decode(n))
    def step(self, q):
        self.tick()
        return helper(q)
    def tick(self):
        pass

def drive(e):
    e.step(None)
    w = Engine()
    return w
"""


def _graph(text, name="g.py"):
    return CallGraph([SourceFile.from_source(name, text)])


def test_callgraph_symbol_table_and_lookup():
    g = _graph(CG_FIXTURE)
    displays = {i.display for i in g.functions.values()}
    assert {"make_decode", "make_decode.decode_chunk", "helper",
            "Engine.__init__", "Engine.compile", "Engine.step",
            "Engine.tick", "drive"} <= displays
    (step,) = g.lookup("Engine.step")
    assert step.name == "step" and step.cls == "Engine"
    # bare-name fallback for plain identifiers
    assert [i.display for i in g.lookup("helper")] == ["helper"]


def test_callgraph_edges_self_import_and_ctor():
    g = _graph(CG_FIXTURE)

    def targets(display):
        (info,) = g.lookup(display)
        return {g.functions[q].display for q in g.edges[info.qualname]}

    # self.tick() binds to the OWN class's method; helper() lexically
    assert targets("Engine.step") == {"Engine.tick", "helper"}
    # attribute alias + factory: jit(self._make_decode(n)) resolves
    # through the alias to the factory AND to the def it returns
    assert {"make_decode", "make_decode.decode_chunk"} <= \
        targets("Engine.compile")
    # obj.m() over-approximates to every project fn named m, and
    # Cls(...) adds the Cls.__init__ edge
    assert {"Engine.step", "Engine.__init__"} <= targets("drive")


def test_callgraph_reachability_and_paths():
    g = _graph(CG_FIXTURE)
    reach = {i.display for i in g.reachable_from("drive")}
    assert {"drive", "Engine.step", "Engine.tick", "helper",
            "Engine.__init__"} <= reach
    chains = {info.display: chain
              for info, chain in g.paths_from("drive")}
    assert chains["drive"] == ("drive",)
    assert chains["Engine.tick"] == \
        ("drive", "Engine.step", "Engine.tick")
    # a cut prunes the node AND everything only reachable through it
    cut_reach = {i.display for i in g.reachable_from(
        "drive", cut=lambda i: i.display == "Engine.step")}
    assert "Engine.step" not in cut_reach
    assert "Engine.tick" not in cut_reach


def test_callgraph_callers_of():
    g = _graph(CG_FIXTURE)
    assert [i.display for i in g.callers_of("Engine.tick")] == \
        ["Engine.step"]
    assert "drive" in {i.display for i in g.callers_of("Engine.step")}


def test_callgraph_import_edge_across_files():
    a = SourceFile.from_source("pkg/alpha.py",
                               "def shared_helper(x):\n    return x\n")
    b = SourceFile.from_source("pkg/beta.py", (
        "from pkg.alpha import shared_helper\n"
        "def use(q):\n"
        "    return shared_helper(q)\n"))
    g = CallGraph([a, b])
    (use,) = g.lookup("use")
    assert [g.functions[q].display for q in g.edges[use.qualname]] == \
        ["shared_helper"]


def test_callgraph_is_deterministic():
    def build():
        srcs = [SourceFile.from_source("g.py", CG_FIXTURE),
                SourceFile.from_source("pkg/alpha.py",
                                       "def shared_helper(x):\n"
                                       "    return x\n")]
        return CallGraph(srcs)
    g1, g2 = build(), build()
    assert g1.edges == g2.edges
    assert list(g1.functions) == list(g2.functions)
    assert [i.qualname for i in g1.reachable_from("drive")] == \
        [i.qualname for i in g2.reachable_from("drive")]


def test_file_index_is_memoized_per_source():
    src = SourceFile.from_source("m.py", "def f():\n    pass\n")
    assert callgraph.file_index(src) is callgraph.file_index(src)


def test_sc03_rides_the_hoisted_resolver():
    """ISSUE 12 hoist regression: host_sync's resolver machinery IS
    callgraph's (aliases kept for back-compat), and SC03's verdicts
    over the real scan set are byte-identical run to run."""
    assert host_sync._Statics is callgraph.Statics
    assert host_sync._jit_statics is callgraph.jit_statics
    assert host_sync._last_name is callgraph.last_name
    assert host_sync._param_names is callgraph.param_names
    res1 = run(sources=config.scan_paths(), checkers=[HostSyncChecker])
    res2 = run(sources=config.scan_paths(), checkers=[HostSyncChecker])
    assert res1.to_json() == res2.to_json()
    assert res1.ok


# -- SC06 recompile-hazard --------------------------------------------------

SC06_FACTORY_PREFIX = """\
import jax

def _decode_for(n):
    def dec(x):
        return x
    return jax.jit(dec)

"""


def test_sc06_tainted_factory_arg():
    fs = _check(RecompileHazardChecker, SC06_FACTORY_PREFIX + (
        "def handle(req):\n"
        "    return _decode_for(len(req.tokens))\n"))
    assert _lines(fs) == [9]
    assert fs[0].checker_id == "SC06"
    assert "_decode_for" in fs[0].message
    assert "_bucket" in fs[0].message


def test_sc06_bucket_helper_sanitizes():
    fs = _check(RecompileHazardChecker, SC06_FACTORY_PREFIX + (
        "def handle(self, req):\n"
        "    w = self._bucket_window(len(req.tokens))\n"
        "    return _decode_for(w)\n"))
    assert fs == []


def test_sc06_static_argnums_position():
    fs = _check(RecompileHazardChecker, (
        "import jax\n"
        "def f(x, n):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnums=(1,))\n"
        "def step(toks):\n"
        "    n = len(toks)\n"
        "    return g(toks, n)\n"))
    assert _lines(fs) == [7]
    assert "static_argnums" in fs[0].message


def test_sc06_tainted_array_shape():
    fs = _check(RecompileHazardChecker, (
        "import jax\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    return x\n"
        "g = jax.jit(f)\n"
        "def step(toks):\n"
        "    buf = np.zeros((len(toks), 4))\n"
        "    return g(buf)\n"))
    assert _lines(fs) == [8]
    assert "shape" in fs[0].message


def test_sc06_strong_update_untaints():
    fs = _check(RecompileHazardChecker, SC06_FACTORY_PREFIX + (
        "def handle(req):\n"
        "    n = len(req.tokens)\n"
        "    n = 8\n"
        "    return _decode_for(n)\n"))
    assert fs == []


def test_sc06_jnp_array_ops_do_not_carry_int_taint():
    """jnp./lax. calls RETURN arrays — building a mask from len() is
    not an int cache key (the llama.py false-positive class)."""
    fs = _check(RecompileHazardChecker, SC06_FACTORY_PREFIX + (
        "import jax.numpy as jnp\n"
        "def handle(req):\n"
        "    mask = jnp.less(jnp.arange(8), len(req.tokens))\n"
        "    return _decode_for(mask)\n"))
    assert fs == []


# -- SC07 blocking-call-on-step-path ----------------------------------------

def _sc07(text, name="fleet.py"):
    src = SourceFile.from_source(name, text)
    g = CallGraph([src])
    return list(StepPathBlockingChecker().check_project(g, [src]))


def test_sc07_sleep_reachable_from_step_root():
    fs = _sc07(
        "import time\n"
        "class ServingFleet:\n"
        "    def step(self):\n"
        "        self._drain()\n"
        "    def _drain(self):\n"
        "        time.sleep(0.1)\n")
    assert _lines(fs) == [6]
    assert fs[0].checker_id == "SC07"
    assert "time.sleep" in fs[0].message
    assert "ServingFleet.step -> ServingFleet._drain" in fs[0].message


def test_sc07_io_boundary_cuts_the_walk():
    fs = _sc07(
        "class ServingFleet:\n"
        "    def step(self):\n"
        "        self._emit()\n"
        "    def _emit(self):  # staticcheck: io-boundary\n"
        "        open('/tmp/x', 'w')\n")
    assert fs == []


def test_sc07_off_path_io_is_not_flagged():
    fs = _sc07(
        "import time\n"
        "class ServingFleet:\n"
        "    def step(self):\n"
        "        pass\n"
        "def maintenance():\n"
        "    time.sleep(5)\n")
    assert fs == []


def test_sc07_imported_sleep_and_net_roots():
    fs = _sc07(
        "from time import sleep\n"
        "import urllib.request\n"
        "class DecodeEngine:\n"
        "    def step(self):\n"
        "        sleep(1)\n"
        "        urllib.request.urlopen('http://x')\n")
    assert _lines(fs) == [5, 6]
    msgs = "\n".join(f.message for f in fs)
    assert "time.sleep" in msgs and "urllib.request.urlopen" in msgs


# -- SC08 metrics-schema ----------------------------------------------------

def _sc08(text, name="metrics.py"):
    src = SourceFile.from_source(name, text)
    g = CallGraph([src])
    return list(MetricsSchemaChecker().check_project(g, [src]))


def test_sc08_counter_suffix_discipline():
    fs = _sc08(
        "r.counter('engine_steps', 'steps completed')\n"
        "r.gauge('queue_total', 'queued requests')\n"
        "r.counter('engine_retired_total', 'retired')\n")
    assert _lines(fs) == [1, 2]
    msgs = {f.line: f.message for f in fs}
    assert "must end '_total'" in msgs[1]
    assert "must not end '_total'" in msgs[2]


def test_sc08_kind_conflict_and_help_drift():
    fs = _sc08(
        "r.counter('steps_total', 'steps')\n"
        "q.gauge('steps_total', 'steps')\n"
        "p.counter('steps_total', 'number of steps')\n")
    msgs = "\n".join(f.message for f in fs)
    assert "registered as gauge here but as counter" in msgs
    assert "help text drifts" in msgs


def test_sc08_asserted_names_resolve_and_kinds_match():
    fs = _sc08(
        "r.gauge('queue_depth', 'queued')\n"
        "v = snap['counters']['queue_depth']\n"
        "w = snap['counters']['engine_ticks_total']\n")
    msgs = {f.line: f.message for f in fs}
    assert "asserted as counter but registered as gauge" in msgs[2]
    assert "resolves to no registration" in msgs[3]


def test_sc08_histogram_aggregates_resolve_to_base():
    fs = _sc08(
        "r.histogram('step_latency', 'seconds per step')\n"
        "b = snap['histograms'].get('step_latency')\n"
        "c = snap['counters']['step_latency_count']\n")
    assert fs == []


def test_sc08_label_keys():
    fs = _sc08(
        "r.counter('drops_total', 'drops', labels={'le': '1'})\n"
        "m.add_labels({'worker': 'w0'})\n"
        "m.add_labels({'9bad': 'x'})\n")
    msgs = {f.line: f.message for f in fs}
    assert "reserved for" in msgs[1]
    assert "must not set 'worker'" in msgs[2]
    assert "not a valid" in msgs[3]


# -- SC09 donation-discipline -----------------------------------------------

def test_sc09_range_spec_must_start_at_the_vararg():
    fs = _check(DonationDisciplineChecker, (
        "import jax\n"
        "def prog(a, b, *pool):\n"
        "    return a\n"
        "f = jax.jit(prog, donate_argnums=tuple(range(1, 3)))\n"))
    assert _lines(fs) == [4]
    assert "matches no resolved callee" in fs[0].message
    assert "prog" in fs[0].message


def test_sc09_range_spec_at_vararg_is_clean():
    fs = _check(DonationDisciplineChecker, (
        "import jax\n"
        "def prog(a, b, *pool):\n"
        "    return a\n"
        "f = jax.jit(prog, donate_argnums=tuple(range(2, 2 + n)))\n"))
    assert fs == []


def test_sc09_explicit_index_off_the_arity():
    fs = _check(DonationDisciplineChecker, (
        "import jax\n"
        "def prog(a, b):\n"
        "    return a\n"
        "f = jax.jit(prog, donate_argnums=(5,))\n"
        "g = jax.jit(prog, donate_argnums=(1,))\n"))
    assert _lines(fs) == [4]


def test_sc09_use_after_donate():
    fs = _check(DonationDisciplineChecker, (
        "import jax\n"
        "def prog(a, *pool):\n"
        "    return a\n"
        "f = jax.jit(prog, donate_argnums=tuple(range(1, 3)))\n"
        "def step(x, pool):\n"
        "    out = f(x, *pool)\n"
        "    return pool\n"))
    assert _lines(fs) == [7]
    assert "read after being donated to 'f'" in fs[0].message


def test_sc09_rebind_idiom_is_clean():
    """The engine's own shape: the donated pool is rebound from the
    call's result in the SAME statement."""
    fs = _check(DonationDisciplineChecker, (
        "import jax\n"
        "def prog(a, *pool):\n"
        "    return a\n"
        "f = jax.jit(prog, donate_argnums=tuple(range(1, 3)))\n"
        "def step(x, pool):\n"
        "    out, *pool = f(x, *pool)\n"
        "    return pool\n"))
    assert fs == []


# -- suppressions and SC00 --------------------------------------------------

def test_suppression_silences_the_finding():
    src = SourceFile.from_source("s.py", (
        "import random\n"
        "r = random.random()  # staticcheck: disable=SC04\n"))
    res = run(sources=[src], checkers=[UnseededRandomChecker])
    assert res.ok and res.findings == []


def test_unused_suppression_is_a_finding():
    src = SourceFile.from_source("s.py", (
        "x = 1  # staticcheck: disable=SC04\n"))
    res = run(sources=[src], checkers=[UnseededRandomChecker])
    assert [f.checker_id for f in res.findings] == \
        [UNUSED_SUPPRESSION_ID]
    assert res.findings[0].line == 1
    assert "unused suppression: SC04" in res.findings[0].message


def test_suppression_only_silences_the_named_checker():
    src = SourceFile.from_source("s.py", (
        "import random\n"
        "r = random.random()  # staticcheck: disable=SC03\n"))
    res = run(sources=[src],
              checkers=[UnseededRandomChecker, HostSyncChecker])
    ids = sorted(f.checker_id for f in res.findings)
    # the SC04 finding survives AND the SC03 suppression is unused
    assert ids == [UNUSED_SUPPRESSION_ID, "SC04"]


def test_sc00_itself_cannot_be_suppressed():
    src = SourceFile.from_source("s.py", (
        "x = 1  # staticcheck: disable=SC00\n"))
    res = run(sources=[src], checkers=[UnseededRandomChecker])
    assert [f.checker_id for f in res.findings] == \
        [UNUSED_SUPPRESSION_ID]
    assert "cannot be suppressed" in res.findings[0].message


def test_inactive_checker_suppression_is_not_reported_stale():
    """`--checkers SC04` must not flag a SC05 suppression as unused —
    the checker simply didn't run, which is no evidence of staleness."""
    src = SourceFile.from_source("s.py", (
        "x = self._m  # staticcheck: disable=SC05\n"))
    res = run(sources=[src], checkers=[UnseededRandomChecker])
    assert res.ok


# -- the real tree ----------------------------------------------------------

def test_scan_set_is_clean_at_head():
    """The acceptance gate: every SC01–SC09 invariant holds over the
    configured scan set (plus the SC04/SC08 test-harness group), so
    the CLI exits 0 at HEAD."""
    res = run()
    assert res.ok, "\n".join(f.render() for f in res.findings)
    assert res.files_scanned == len(config.run_paths())
    assert res.files_scanned == \
        len(config.scan_paths()) + len(config.nondet_extra_paths())


def test_report_is_deterministic():
    a, b = run(), run()
    assert a.to_json() == b.to_json()
    assert [f.render() for f in a.findings] == \
        [f.render() for f in b.findings]


def test_scan_set_covers_the_stack():
    names = {p.name for p in config.scan_paths()}
    for required in ("serving.py", "qos.py", "fleet.py", "metrics.py",
                     "watchdog.py", "llama.py", "paged_attention.py",
                     "bench.py"):
        assert required in names, f"{required} fell out of the scan set"


# -- byte-equivalence with the pre-port lints -------------------------------

def _legacy_timer_offenders(paths, banned, allow_alias_def):
    """The pre-ISSUE-11 tests/test_no_adhoc_timers.py scan, verbatim."""
    out = []
    for py in paths:
        for lineno, line in enumerate(py.read_text().splitlines(), 1):
            if allow_alias_def and \
                    line.strip() == "now = time.perf_counter":
                continue
            for token in banned:
                if token in line:
                    out.append((py.resolve(), lineno))
    return out


def test_sc01_verdicts_match_legacy_lint_byte_for_byte():
    legacy = (
        _legacy_timer_offenders(config.timer_inference_paths(),
                                ("time.perf_counter",), False)
        + _legacy_timer_offenders(config.timer_shared_clock_paths(),
                                  ("time.perf_counter",
                                   "time.monotonic"), True))
    res = run(sources=config.timer_inference_paths()
              + config.timer_shared_clock_paths(),
              checkers=[AdhocTimerChecker])
    ported = [((config.REPO_ROOT / f.file).resolve(), f.line)
              for f in res.findings]
    assert sorted(ported) == sorted(legacy)


def _legacy_broad_handlers(paths):
    """The pre-ISSUE-11 tests/test_no_silent_except.py scan, verbatim
    (classifier logic identical to util.is_broad/is_loud — asserted
    separately below)."""
    broad = {"Exception", "BaseException"}
    offenders, examined = [], []

    def names_of(node):
        if node is None:
            return []
        elts = node.elts if isinstance(node, ast.Tuple) else [node]
        out = []
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
            elif isinstance(e, ast.Attribute):
                out.append(e.attr)
        return out

    for py in paths:
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is not None and not any(
                    n in broad for n in names_of(node.type)):
                continue
            examined.append((py.resolve(), node.lineno))
            if not util.is_loud_handler(node):
                offenders.append((py.resolve(), node.lineno))
    return offenders, examined


def test_sc02_verdicts_match_legacy_lint_byte_for_byte():
    legacy_offenders, legacy_examined = _legacy_broad_handlers(
        config.silent_except_paths())
    chk = SilentExceptChecker()
    res = run(sources=config.silent_except_paths(), checkers=[chk])
    ported = [((config.REPO_ROOT / f.file).resolve(), f.line)
              for f in res.findings]
    examined = [((config.REPO_ROOT / rel).resolve(), line)
                for rel, line in chk.broad_handlers]
    assert sorted(ported) == sorted(legacy_offenders)
    # not just the (empty-at-HEAD) verdicts: the examined-handler sets
    # must match too, or equivalence would be vacuous
    assert sorted(examined) == sorted(legacy_examined)
    assert len(legacy_examined) >= 5


# -- util unit tests (satellite: dedup'd exemption logic) -------------------

def test_util_alias_def_exemption():
    assert util.is_alias_def_line("now = time.perf_counter")
    assert util.is_alias_def_line("   now = time.perf_counter   ")
    assert not util.is_alias_def_line("now2 = time.perf_counter")
    assert not util.is_alias_def_line("now = time.monotonic")


def _handler(src_text):
    tree = ast.parse(src_text)
    return next(n for n in ast.walk(tree)
                if isinstance(n, ast.ExceptHandler))


def test_util_loudness_taxonomy():
    assert util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception:\n    raise\n"))
    assert util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception:\n    log_event('x')\n"))
    assert util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception:\n"
        "    self._c_dropped_total.inc()\n"))
    assert util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception as e:\n    req.error = e\n"))
    # a counter without an error/drop/fail hint is NOT loud
    assert not util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception:\n    self._c_steps.inc()\n"))
    assert not util.is_loud_handler(_handler(
        "try:\n    pass\nexcept Exception:\n    print('x')\n"))


def test_util_broad_classifier():
    assert util.is_broad_handler(_handler(
        "try:\n    pass\nexcept:\n    pass\n"))
    assert util.is_broad_handler(_handler(
        "try:\n    pass\nexcept (OSError, Exception):\n    pass\n"))
    assert not util.is_broad_handler(_handler(
        "try:\n    pass\nexcept OSError:\n    pass\n"))


def test_util_name_helpers():
    call = ast.parse("a.b.c(1)").body[0].value
    assert util.name_parts(call.func) == ["a", "b", "c"]
    assert util.dotted_name(call.func) == "a.b.c"
    assert util.call_target(call) == "c"


# -- CLI --------------------------------------------------------------------

def test_cli_exits_zero_at_head(capsys):
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "0 findings" in out


def test_cli_json_shape(capsys):
    assert cli_main(["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    assert doc["findings"] == []
    assert doc["files_scanned"] == len(config.run_paths())
    assert [c["id"] for c in doc["checkers"]] == \
        ["SC01", "SC02", "SC03", "SC04", "SC05",
         "SC06", "SC07", "SC08", "SC09"]
    assert all(set(c) == {"id", "name"} for c in doc["checkers"])


def test_cli_list_catalog(capsys):
    assert cli_main(["--list"]) == 0
    out = capsys.readouterr().out
    for cid in ("SC01", "SC02", "SC03", "SC04", "SC05",
                "SC06", "SC07", "SC08", "SC09"):
        assert cid in out


_VIOLATIONS = {
    "SC01": "t0 = time.perf_counter()\n",
    "SC02": "try:\n    pass\nexcept Exception:\n    pass\n",
    "SC03": ("import jax\n"
             "def f(x):\n"
             "    return float(x)\n"
             "g = jax.jit(f)\n"),
    "SC04": "import random\nr = random.random()\n",
    "SC05": ("class C:\n"
             "    def __init__(self):\n"
             "        self._m = {}   # guarded-by: _lock\n"
             "        self._lock = object()\n"
             "    def get(self):\n"
             "        return self._m\n"),
    "SC06": ("import jax\n"
             "def _decode_for(n):\n"
             "    def dec(x):\n"
             "        return x\n"
             "    return jax.jit(dec)\n"
             "def handle(req):\n"
             "    return _decode_for(len(req.tokens))\n"),
    "SC07": ("import time\n"
             "class ServingFleet:\n"
             "    def step(self):\n"
             "        self._drain()\n"
             "    def _drain(self):\n"
             "        time.sleep(0.1)\n"),
    "SC08": "r.counter('engine_steps', 'steps completed')\n",
    "SC09": ("import jax\n"
             "def prog(a, b, *pool):\n"
             "    return a\n"
             "f = jax.jit(prog, donate_argnums=tuple(range(1, 3)))\n"),
}

_VIOLATION_LINES = {"SC01": 1, "SC02": 3, "SC03": 3, "SC04": 2,
                    "SC05": 6, "SC06": 7, "SC07": 6, "SC08": 1,
                    "SC09": 4}


@pytest.mark.parametrize("cid", sorted(_VIOLATIONS))
def test_cli_exits_nonzero_on_violating_fixture_module(cid, tmp_path,
                                                       capsys):
    """The acceptance criterion: the CLI run against a fixture module
    violating each checker exits nonzero with a correct file:line."""
    mod = tmp_path / f"bad_{cid.lower()}.py"
    mod.write_text(_VIOLATIONS[cid])
    assert cli_main([str(mod)]) == 1
    out = capsys.readouterr().out
    want = f"{mod.resolve().as_posix()}:{_VIOLATION_LINES[cid]}: {cid} "
    assert want in out, f"missing {want!r} in:\n{out}"


def test_cli_checker_subset(tmp_path, capsys):
    mod = tmp_path / "bad.py"
    mod.write_text("import random\nr = random.random()\n"
                   "t0 = time.perf_counter()\n")
    assert cli_main([str(mod), "--checkers", "SC01"]) == 1
    out = capsys.readouterr().out
    assert "SC01" in out and "SC04" not in out
    capsys.readouterr()
    assert cli_main([str(mod), "--checkers", "SC03"]) == 0


def test_expand_checker_ids_range_syntax():
    assert expand_checker_ids("SC01,SC06-SC09") == \
        ["SC01", "SC06", "SC07", "SC08", "SC09"]
    assert expand_checker_ids("SC06-09") == \
        ["SC06", "SC07", "SC08", "SC09"]
    assert expand_checker_ids("SC03") == ["SC03"]
    with pytest.raises(ValueError):
        expand_checker_ids("SC09-SC06")


def test_cli_checker_range(tmp_path, capsys):
    mod = tmp_path / "bad.py"
    mod.write_text(_VIOLATIONS["SC09"])
    assert cli_main([str(mod), "--checkers", "SC06-SC09"]) == 1
    out = capsys.readouterr().out
    assert "SC09" in out
    capsys.readouterr()
    # the SC01-SC05 slice does not see the donation hazard
    assert cli_main([str(mod), "--checkers", "SC01-SC05"]) == 0


def test_cli_github_format(tmp_path, capsys):
    mod = tmp_path / "bad.py"
    mod.write_text(_VIOLATIONS["SC04"])
    assert cli_main([str(mod), "--format=github"]) == 1
    out = capsys.readouterr().out
    want = f"::error file={mod.resolve().as_posix()},line=2::SC04 "
    assert want in out, f"missing {want!r} in:\n{out}"
    capsys.readouterr()
    # clean tree -> no annotation lines at all
    assert cli_main(["--format=github"]) == 0
    assert capsys.readouterr().out == ""
