"""No-silent-except lint (ISSUE 9 satellite, ported to graftcheck by
ISSUE 11): a self-healing fleet is only debuggable if every swallowed
fault leaves a trace. Every BROAD exception handler (bare ``except:``,
``except Exception``, ``except BaseException`` — alone or in a tuple)
in ``paddle_tpu/inference/`` and ``paddle_tpu/observability/`` must be
LOUD in at least one of the sanctioned ways:

- re-raise (``raise`` anywhere in the handler),
- route through a structured logger (``log_kv`` / ``log_event``),
- fail the work loudly (``_fail_request`` / ``_fail_row_paged`` /
  ``_shed_request`` / ``_poison_request`` / ``_park_locked``),
- flag the worker (``_mark_unhealthy``),
- count it (``.inc()`` on an attribute whose name mentions error/
  drop/fail), or
- surface it on the request (assignment to an ``.error`` attribute).

NARROW handlers (``except queue.Empty``, ``except
NoHealthyWorkersError`` …) are exempt — catching a specific type is
already a statement about what can happen there.

ISSUE 11: the classifier lives in
:mod:`paddle_tpu.staticcheck.util` (``is_broad_handler`` /
``is_loud_handler``), the scan walk in
:class:`paddle_tpu.staticcheck.silent_except.SilentExceptChecker`
(SC02), and the scan-set list in
:mod:`paddle_tpu.staticcheck.config`; this file is a thin wrapper
keeping the historic test names alive. Byte-equivalence of the
verdicts against the pre-port lint is asserted in
``tests/test_staticcheck.py``.
"""

import ast

from paddle_tpu.staticcheck import SilentExceptChecker, run
from paddle_tpu.staticcheck.config import silent_except_paths
from paddle_tpu.staticcheck.util import (is_broad_handler,
                                         is_loud_handler)


def _run_sc02():
    chk = SilentExceptChecker()
    res = run(sources=silent_except_paths(), checkers=[chk])
    return res, chk


def test_every_broad_except_is_loud():
    res, _ = _run_sc02()
    assert res.ok, (
        "silent broad exception handler(s) — re-raise, log via "
        "log_kv/log_event, fail the request, mark the worker "
        "unhealthy, or bump an error counter:\n  "
        + "\n  ".join(f.render() for f in res.findings))


def test_lint_scan_is_meaningful():
    """The lint must actually be seeing the handlers it polices — an
    import-path or glob change that empties the scan would make the
    lint above pass vacuously. The checker instance records every
    broad handler it examined for exactly this purpose."""
    _, chk = _run_sc02()
    handlers = chk.broad_handlers
    assert len(handlers) >= 5, (
        f"only {len(handlers)} broad handlers found — scan set broken?")
    files = {rel.rsplit("/", 1)[-1] for rel, _ in handlers}
    for required in ("serving.py", "fleet.py", "export.py"):
        assert required in files, (
            f"{required} has no broad handlers in the scan — it "
            f"historically does; did the glob or the file move?")
    scanned = {p.name for p in silent_except_paths()}
    assert "sharding.py" in scanned, (
        "ISSUE 10's sharding.py fell out of the no-silent-except scan "
        "set — mesh/spec construction must stay under the lint")


def test_narrow_handlers_are_exempt():
    """Sanity-check the classifier itself on synthetic handlers."""
    tree = ast.parse(
        "try:\n    pass\n"
        "except queue.Empty:\n    pass\n"
        "except (ValueError, KeyError):\n    pass\n"
        "except (OSError, Exception):\n    pass\n"
        "except BaseException:\n    raise\n"
        "except:\n    pass\n")
    handlers = [n for n in ast.walk(tree)
                if isinstance(n, ast.ExceptHandler)]
    assert [is_broad_handler(h) for h in handlers] == \
        [False, False, True, True, True]
    assert is_loud_handler(handlers[3]) and not is_loud_handler(handlers[4])
