"""No-silent-except lint (ISSUE 9 satellite): a self-healing fleet is
only debuggable if every swallowed fault leaves a trace. This AST scan
walks ``paddle_tpu/inference/`` and ``paddle_tpu/observability/`` and
requires every BROAD exception handler (bare ``except:``, ``except
Exception``, ``except BaseException`` — alone or in a tuple) to be
LOUD in at least one of the sanctioned ways:

- re-raise (``raise`` anywhere in the handler),
- route through a structured logger (``log_kv`` / ``log_event``),
- fail the work loudly (``_fail_request`` / ``_fail_row_paged`` /
  ``_shed_request`` / ``_poison_request`` / ``_park_locked``),
- flag the worker (``_mark_unhealthy``),
- count it (``.inc()`` on an attribute whose name mentions error/
  drop/fail), or
- surface it on the request (assignment to an ``.error`` attribute).

NARROW handlers (``except queue.Empty``, ``except
NoHealthyWorkersError`` …) are exempt — catching a specific type is
already a statement about what can happen there. The lint is
deliberately syntactic: it cannot prove the log line is *useful*, only
that the failure isn't silently discarded, which is the failure mode
chaos testing keeps finding in real fleets."""

import ast
import pathlib

_ROOT = pathlib.Path(__file__).resolve().parent.parent / "paddle_tpu"
SCAN = sorted((_ROOT / "inference").glob("*.py")) \
    + sorted((_ROOT / "observability").glob("*.py"))

_BROAD = {"Exception", "BaseException"}
_LOUD_CALLS = {"log_kv", "log_event", "_fail_request", "_fail_row_paged",
               "_mark_unhealthy", "_shed_request", "_poison_request",
               "_park_locked"}
_COUNTER_HINTS = ("error", "drop", "fail")


def _names_of(node):
    """Exception-type names in a handler's ``type`` expression."""
    if node is None:
        return []
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True                     # bare except:
    return any(n in _BROAD for n in _names_of(handler.type))


def _call_target(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_loud(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _call_target(node)
            if name in _LOUD_CALLS:
                return True
            if name == "inc" and isinstance(node.func, ast.Attribute):
                base = node.func.value
                attr = base.attr if isinstance(base, ast.Attribute) \
                    else (base.id if isinstance(base, ast.Name) else "")
                if any(h in attr for h in _COUNTER_HINTS):
                    return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "error":
                    return True
    return False


def _broad_handlers():
    out = []
    for py in SCAN:
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node):
                out.append((py, node))
    return out


def test_every_broad_except_is_loud():
    offenders = [f"{py.name}:{h.lineno}" for py, h in _broad_handlers()
                 if not _is_loud(h)]
    assert not offenders, (
        "silent broad exception handler(s) — re-raise, log via "
        "log_kv/log_event, fail the request, mark the worker "
        "unhealthy, or bump an error counter:\n  "
        + "\n  ".join(offenders))


def test_lint_scan_is_meaningful():
    """The lint must actually be seeing the handlers it polices — an
    import-path or glob change that empties the scan would make the
    lint above pass vacuously."""
    handlers = _broad_handlers()
    assert len(handlers) >= 5, (
        f"only {len(handlers)} broad handlers found — scan set broken?")
    files = {py.name for py, _ in handlers}
    for required in ("serving.py", "fleet.py", "export.py"):
        assert required in files, (
            f"{required} has no broad handlers in the scan — it "
            f"historically does; did the glob or the file move?")
    scanned = {py.name for py in SCAN}
    assert "sharding.py" in scanned, (
        "ISSUE 10's sharding.py fell out of the no-silent-except scan "
        "set — mesh/spec construction must stay under the lint")


def test_narrow_handlers_are_exempt():
    """Sanity-check the classifier itself on synthetic handlers."""
    tree = ast.parse(
        "try:\n    pass\n"
        "except queue.Empty:\n    pass\n"
        "except (ValueError, KeyError):\n    pass\n"
        "except (OSError, Exception):\n    pass\n"
        "except BaseException:\n    raise\n"
        "except:\n    pass\n")
    handlers = [n for n in ast.walk(tree)
                if isinstance(n, ast.ExceptHandler)]
    assert [_is_broad(h) for h in handlers] == \
        [False, False, True, True, True]
    assert _is_loud(handlers[3]) and not _is_loud(handlers[4])
