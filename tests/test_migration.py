"""Cross-worker KV page transplant + prefill/decode disaggregation
(ISSUE 14): the transplant primitive's conservation and fidelity
contracts (fp and int8 pools, tp-sharded pools on shared and disjoint
placements), its failure modes (stale chain, full destination), and
the fleet paths built on it — warm-prefix migration on route and the
role-split handoff — each pinned to strict BIT-parity of greedy
tokens against the solo oracle. Migration disabled (the default) must
leave the r14 fleet byte-identical."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.fleet import ServingFleet
from paddle_tpu.inference.migration import (MigrationResult,
                                            transplant_prefix)
from paddle_tpu.inference.serving import DecodeEngine

ENGINE_KW = dict(capacity=2, s_max=64, chunk=4, block_size=8)


def _model():
    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    m = LlamaForCausalLM("debug")
    m.eval()
    return m


def _solo(m, p, mn):
    return np.asarray(m.generate(
        paddle.to_tensor(p[None, :]), max_new_tokens=mn,
        temperature=0.0)._value)[0]


def _drain(eng):
    for _ in range(10000):
        eng.admit([])
        if eng.idle():
            break
        eng.decode_once()


def _run_one(eng, p, mn=8):
    r = eng.submit(p, max_new_tokens=mn)
    _drain(eng)
    return np.asarray(r.wait(timeout=120)).reshape(-1)


def _conserved(*engines):
    for e in engines:
        assert e._alloc.conservation_ok, \
            f"conservation broken on {e.worker_id}: {e._alloc.stats()}"


class TestTransplantPrimitive:
    def test_warm_replay_bit_identical(self):
        """A transplanted chain serves the destination engine's own
        admission: the replayed prompt matches the migrated pages and
        decodes bit-identically to the source run (and the oracle)."""
        m = _model()
        rng = np.random.RandomState(3)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        res = transplant_prefix(src, dst, out)
        assert res.reason == "ok" and res.moved
        assert res.pages == len(res.pages_dst) == len(res.pages_src)
        assert res.tokens == res.pages * ENGINE_KW["block_size"]
        assert res.fused          # same default device placement
        _conserved(src, dst)
        # destination admission must HIT the transplanted chain
        out2 = _run_one(dst, p)
        np.testing.assert_array_equal(out, out2)
        np.testing.assert_array_equal(out, _solo(m, p, 8).reshape(-1))
        assert dst._cache.hit_tokens > 0
        _conserved(src, dst)

    def test_source_chain_stays_published(self):
        """Migration COPIES — the source keeps serving its own chain
        warm afterwards (this is replication, not theft)."""
        m = _model()
        rng = np.random.RandomState(4)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        transplant_prefix(src, dst, out)
        hits0 = src._cache.hit_tokens
        out2 = _run_one(src, p)
        np.testing.assert_array_equal(out, out2)
        assert src._cache.hit_tokens > hits0

    def test_int8_scale_fidelity(self):
        """int8 pools move codes AND per-page scales: destination
        pages carry the source's running-max scales bit-exactly, not
        the eps floor a fresh allocation would have (the drain-before-
        copy ordering under test)."""
        m = _model()
        rng = np.random.RandomState(5)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(m, kv_dtype="int8", worker_id="src",
                           **ENGINE_KW)
        dst = DecodeEngine(m, kv_dtype="int8", worker_id="dst",
                           **ENGINE_KW)
        out = _run_one(src, p)
        res = transplant_prefix(src, dst, out)
        assert res.reason == "ok"
        from paddle_tpu.kernels.paged_attention import KV_SCALE_EPS
        for s_arr, d_arr in ((src._kscale, dst._kscale),
                             (src._vscale, dst._vscale)):
            s = np.asarray(s_arr)[:, res.pages_src]
            d = np.asarray(d_arr)[:, res.pages_dst]
            np.testing.assert_array_equal(s, d)
            # a drain-after-copy bug would leave every lane at eps
            assert not np.all(d == np.float32(KV_SCALE_EPS))
        out2 = _run_one(dst, p)
        np.testing.assert_array_equal(out, out2)
        _conserved(src, dst)

    def test_tp2_same_mesh_fused(self):
        """tp=2 pools over the SAME submesh ride the fused launch (the
        page axis is unsharded, so the gather/scatter is
        spec-preserving) and replay bit-identically."""
        import jax
        from paddle_tpu.inference.sharding import make_tp_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        m = _model()
        rng = np.random.RandomState(6)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        mesh = make_tp_mesh(2, devices=jax.devices()[:2])
        src = DecodeEngine(m, mesh=mesh, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, mesh=mesh, worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        res = transplant_prefix(src, dst, out)
        assert res.reason == "ok" and res.fused
        out2 = _run_one(dst, p)
        np.testing.assert_array_equal(out, out2)
        np.testing.assert_array_equal(out, _solo(m, p, 8).reshape(-1))
        _conserved(src, dst)

    def test_tp2_disjoint_submeshes_host_bounce(self):
        """Fleet-shaped placement: two tp=2 workers on DISJOINT
        submeshes. The copy bounces through host (the in-process
        stand-in for the multi-host ICI/RDMA hop) and still replays
        bit-identically."""
        import jax
        from paddle_tpu.inference.sharding import make_tp_mesh
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        m = _model()
        rng = np.random.RandomState(7)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(
            m, mesh=make_tp_mesh(2, devices=jax.devices()[0:2]),
            worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(
            m, mesh=make_tp_mesh(2, devices=jax.devices()[2:4]),
            worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        res = transplant_prefix(src, dst, out)
        assert res.reason == "ok" and not res.fused
        out2 = _run_one(dst, p)
        np.testing.assert_array_equal(out, out2)
        _conserved(src, dst)

    def test_racing_eviction_yields_stale(self):
        """The directory-staleness race: the chain was evicted between
        the caller's hint and the transplant. The owner's match
        refutes the hint — reason ``stale``, ZERO allocator movement
        on either end (one cold prefill, never a wrong answer)."""
        m = _model()
        rng = np.random.RandomState(8)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        src._cache.evict(10**6)         # the race, made deterministic
        before = (src._alloc.stats(), dst._alloc.stats())
        res = transplant_prefix(src, dst, out)
        assert res.reason == "stale" and not res.moved
        assert (src._alloc.stats(), dst._alloc.stats()) == before
        _conserved(src, dst)

    def test_pinned_chain_survives_eviction(self):
        """Mid-migration safety: pages pinned by the transplant's own
        match are refcount>=2, so a concurrent evict sweep cannot free
        them (evict only drops refcount-1 childless nodes)."""
        m = _model()
        rng = np.random.RandomState(9)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        out = _run_one(src, p)
        mm = src._cache.match([int(t) for t in out], len(out) - 1)
        assert mm.pages
        src._cache.evict(10**6)         # sweeps everything unpinned
        for pg in mm.pages:             # pinned pages still allocated
            assert src._alloc.refcount(pg) >= 1
        src._cache.release(mm)
        src._cache.release_cow(mm)
        _conserved(src)

    def test_dst_full_aborts_clean(self):
        """All-or-nothing: a destination pool that cannot fund the
        chain (even after its own LRU eviction) aborts with nothing
        changed on either allocator."""
        m = _model()
        rng = np.random.RandomState(10)
        p = rng.randint(1, 128, (30,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        kw = dict(ENGINE_KW, n_blocks=3)    # 2 allocatable pages
        dst = DecodeEngine(m, worker_id="dst", **kw)
        out = _run_one(src, p)
        before = (src._alloc.stats(), dst._alloc.stats())
        res = transplant_prefix(src, dst, out)   # needs 4 pages
        assert res.reason == "dst_full" and not res.moved
        assert (src._alloc.stats(), dst._alloc.stats()) == before
        _conserved(src, dst)

    def test_budget_caps_pages(self):
        m = _model()
        rng = np.random.RandomState(11)
        p = rng.randint(1, 128, (30,)).astype(np.int32)
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, worker_id="dst", **ENGINE_KW)
        out = _run_one(src, p)
        res = transplant_prefix(src, dst, out, max_pages=2)
        assert res.reason == "ok" and res.pages == 2
        _conserved(src, dst)

    def test_no_chain_and_same_engine(self):
        m = _model()
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        dst = DecodeEngine(m, worker_id="dst", **ENGINE_KW)
        assert transplant_prefix(src, dst, [1, 2, 3]).reason \
            == "no_chain"                   # under one full page
        assert transplant_prefix(src, src, list(range(20))).reason \
            == "no_chain"
        assert transplant_prefix(
            src, dst, list(range(20)), max_pages=0).reason == "no_chain"

    def test_layout_mismatch_raises(self):
        m = _model()
        src = DecodeEngine(m, worker_id="src", **ENGINE_KW)
        kw = dict(ENGINE_KW, block_size=16)
        dst = DecodeEngine(m, worker_id="dst", **kw)
        with pytest.raises(ValueError):
            transplant_prefix(src, dst, list(range(32)))
        q = DecodeEngine(m, kv_dtype="int8", worker_id="q",
                         **ENGINE_KW)
        with pytest.raises(ValueError):
            transplant_prefix(src, q, list(range(32)))

    def test_result_shape(self):
        r = MigrationResult()
        assert r.reason == "ok" and r.pages == 0 and not r.moved


class TestFleetRouteMigration:
    def _warm(self, fleet, p, mn=8):
        r = fleet.submit(p, max_new_tokens=mn)
        fleet.run_until_drained()
        return np.asarray(r.wait(timeout=120)).reshape(-1)

    def test_route_migration_bit_identical(self):
        """A directory hit that loses the route to its own load
        penalty moves the chain to the winner; the re-submitted prompt
        decodes bit-identically warm."""
        m = _model()
        rng = np.random.RandomState(12)
        A = rng.randint(1, 128, (24,)).astype(np.int32)
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             migration_budget_pages=8,
                             load_penalty=100.0)
        out1 = self._warm(fleet, A)
        # pile load on the cached worker so affinity loses the route
        for n in (16, 16, 16):
            fleet.submit(rng.randint(1, 128, (n,)).astype(np.int32),
                         max_new_tokens=4)
        r2 = fleet.submit(A, max_new_tokens=8)
        st = fleet.stats()
        assert st["migrations"] >= 1
        assert st["migrated_pages"] >= 1
        fleet.run_until_drained()
        out2 = np.asarray(r2.wait(timeout=120)).reshape(-1)
        np.testing.assert_array_equal(out1, out2)
        np.testing.assert_array_equal(out1, _solo(m, A, 8).reshape(-1))
        ev = [e for e in fleet.flight.snapshot()["events"]
              if e.get("kind") == "kv_migrated"]
        assert ev and ev[0]["pages"] >= 1
        for w in fleet.workers:
            assert w.engine._alloc.conservation_ok
        fleet.close()

    def test_stale_hint_counted_and_survived(self):
        """A stale directory hint (owner evicted since on_insert) is
        refuted by the owner's match: the stale-hint counter moves and
        the request cold-prefills correctly on its routed worker."""
        m = _model()
        rng = np.random.RandomState(13)
        A = rng.randint(1, 128, (24,)).astype(np.int32)
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             migration_budget_pages=8,
                             load_penalty=100.0)
        # plant a hint the owner does not hold (hint-only consistency:
        # the directory may always run ahead of the caches)
        fleet.directory.on_insert("w0", [int(t) for t in A])
        for n in (16, 16, 16):
            fleet.submit(rng.randint(1, 128, (n,)).astype(np.int32),
                         max_new_tokens=4)
        r = fleet.submit(A, max_new_tokens=8)
        st = fleet.stats()
        assert st["stale_hints"] >= 1
        assert st["migrations"] == 0
        fleet.run_until_drained()
        out = np.asarray(r.wait(timeout=120)).reshape(-1)
        np.testing.assert_array_equal(out, _solo(m, A, 8).reshape(-1))
        fleet.close()

    def test_migration_off_is_baseline(self):
        """Default knobs (roles=None, migration_budget_pages unset)
        keep the r14 fleet: zero migrations, zero migration debt, and
        bit-identical outputs vs the oracle."""
        m = _model()
        rng = np.random.RandomState(14)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (24, 18, 30, 12)]
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW))
        reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
        fleet.run_until_drained()
        st = fleet.stats()
        assert st["migrations"] == 0 and st["migrated_pages"] == 0
        assert st["roles"] is None
        for w in fleet.workers:
            assert w.engine._mig_debt == 0
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=120)).reshape(-1),
                _solo(m, p, 8).reshape(-1))
        fleet.close()

    def test_roles_validation(self):
        m = _model()
        with pytest.raises(ValueError):
            ServingFleet(m, n_workers=2, roles=("prefill",))
        with pytest.raises(ValueError):
            ServingFleet(m, n_workers=2, roles=("prefill", "oracle"))
        with pytest.raises(ValueError):
            ServingFleet(m, n_workers=2, roles=("decode", "decode"))


class TestRoleSplitFleet:
    def test_role_split_bit_identical(self):
        """The full disaggregated path: prompts prefill on the prefill
        worker (forced chunked), finished rows hand off over the
        transplant, decode workers resume — and every output matches
        the solo oracle bit-for-bit, with the ``migrated`` hop on the
        traces and conservation on every pool."""
        m = _model()
        rng = np.random.RandomState(15)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (24, 18, 30, 12)]
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             roles=("prefill", "decode"))
        assert fleet.workers[0].role == "prefill"
        assert fleet.workers[0].engine.chunked_prefill
        assert fleet.workers[1].role == "decode"
        reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
        fleet.run_until_drained()
        st = fleet.stats()
        assert st["migrations"] >= 1
        assert st["roles"] == {"w0": "prefill", "w1": "decode"}
        hopped = 0
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                np.asarray(r.wait(timeout=120)).reshape(-1),
                _solo(m, p, 8).reshape(-1))
            hops = [h for h in getattr(r.trace, "hops", [])
                    if h.get("reason") == "migrated"]
            hopped += bool(hops)
        assert hopped >= 1
        for w in fleet.workers:
            assert w.engine._alloc.conservation_ok
        fleet.close()

    def test_role_split_repeat_bit_for_bit(self):
        """Same seed, run twice: the disaggregated fleet is
        deterministic end to end."""
        m = _model()

        def run():
            rng = np.random.RandomState(16)
            prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                       for n in (26, 14, 22)]
            fleet = ServingFleet(m, n_workers=2,
                                 engine_kwargs=dict(ENGINE_KW),
                                 roles=("prefill", "decode"))
            reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
            fleet.run_until_drained()
            outs = [np.asarray(r.wait(timeout=120)).reshape(-1)
                    for r in reqs]
            st = fleet.stats()
            fleet.close()
            return outs, st["migrations"]

        o1, m1 = run()
        o2, m2 = run()
        assert m1 == m2
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(a, b)

    def test_prefill_worker_down_degrades(self):
        """With the only prefill worker dead, the router falls back to
        any healthy worker — a degraded fleet beats a dead one."""
        m = _model()
        rng = np.random.RandomState(17)
        p = rng.randint(1, 128, (20,)).astype(np.int32)
        fleet = ServingFleet(m, n_workers=2,
                             engine_kwargs=dict(ENGINE_KW),
                             roles=("prefill", "decode"))
        fleet.kill_worker("w0")
        r = fleet.submit(p, max_new_tokens=8)
        fleet.run_until_drained()
        np.testing.assert_array_equal(
            np.asarray(r.wait(timeout=120)).reshape(-1),
            _solo(m, p, 8).reshape(-1))
        fleet.close()
