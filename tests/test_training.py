"""End-to-end training tests — BASELINE config 1 analogue: LeNet on synthetic
MNIST-shaped data must converge (reference test/book golden-value tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


def make_blobs(n=64, d=4, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, classes).astype(np.float32) * 3
    y = (x @ w).argmax(1).astype(np.int64)
    return x, y


class MLP(nn.Layer):
    def __init__(self, d=4, h=16, c=3):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, c)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


class TestOptimizers:
    @pytest.mark.parametrize("opt_name", ["SGD", "Momentum", "Adam", "AdamW",
                                          "RMSProp", "Adagrad", "Lamb"])
    def test_optimizer_reduces_loss(self, opt_name):
        x, y = make_blobs()
        model = MLP()
        kwargs = {"learning_rate": 0.1 if opt_name in ("SGD", "Momentum")
                  else 0.01, "parameters": model.parameters()}
        opt = getattr(paddle.optimizer, opt_name)(**kwargs)
        xt = paddle.to_tensor(x)
        yt = paddle.to_tensor(y)
        first = None
        for i in range(30):
            loss = F.cross_entropy(model(xt), yt)
            if first is None:
                first = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.7, f"{opt_name} failed to descend"

    def test_sgd_matches_manual(self):
        w = paddle.Parameter(np.array([1.0, 2.0], np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        loss = paddle.sum(w * w)
        loss.backward()
        opt.step()
        assert np.allclose(_np(w), [1 - 0.1 * 2, 2 - 0.1 * 4], atol=1e-6)

    def test_adam_state_dict_roundtrip(self):
        model = MLP()
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        x, y = make_blobs()
        loss = F.cross_entropy(model(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        sd = opt.state_dict()
        opt2 = paddle.optimizer.Adam(parameters=model.parameters())
        opt2.set_state_dict(sd)
        assert opt2._global_step == opt._global_step

    def test_lr_scheduler(self):
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        model = MLP()
        opt = paddle.optimizer.SGD(learning_rate=sched,
                                   parameters=model.parameters())
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-9

    def test_grad_clip_in_optimizer(self):
        model = MLP()
        clip = nn.ClipGradByGlobalNorm(0.01)
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=model.parameters(),
                                   grad_clip=clip)
        x, y = make_blobs()
        before = [_np(p).copy() for p in model.parameters()]
        loss = F.cross_entropy(model(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        delta = sum(((b - _np(p)) ** 2).sum()
                    for b, p in zip(before, model.parameters()))
        assert np.sqrt(delta) <= 0.011


class TestLeNetMNIST:
    """BASELINE config 1: LeNet-5 forward/backward/convergence."""

    def _lenet(self):
        from paddle_tpu.vision.models import LeNet
        return LeNet(num_classes=10)

    def test_lenet_shapes(self):
        net = self._lenet()
        out = net(paddle.randn([2, 1, 28, 28]))
        assert out.shape == [2, 10]

    def test_lenet_convergence_synthetic(self):
        rng = np.random.RandomState(0)
        # 10 distinguishable synthetic digit patterns
        protos = rng.rand(10, 1, 28, 28).astype(np.float32)
        xs, ys = [], []
        for i in range(10):
            for _ in range(8):
                xs.append(protos[i] + 0.05 * rng.randn(1, 28, 28).astype(np.float32))
                ys.append(i)
        x = np.stack(xs)
        y = np.asarray(ys, np.int64)
        net = self._lenet()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        for _ in range(25):
            loss = paddle.nn.functional.cross_entropy(net(xt), yt)
            loss.backward()
            opt.step()
            opt.clear_grad()
        pred = _np(net(xt)).argmax(1)
        acc = (pred == y).mean()
        assert acc > 0.9, f"LeNet failed to fit synthetic digits: acc={acc}"


class TestDataLoader:
    def test_basic_iteration(self):
        from paddle_tpu.io import Dataset, DataLoader

        class DS(Dataset):
            def __getitem__(self, i):
                return np.full((3,), i, np.float32), np.int64(i % 2)

            def __len__(self):
                return 10

        loader = DataLoader(DS(), batch_size=4, shuffle=False)
        batches = list(loader)
        assert len(batches) == 3
        xb, yb = batches[0]
        assert xb.shape == (4, 3)

    def test_shuffle_drop_last(self):
        from paddle_tpu.io import Dataset, DataLoader

        class DS(Dataset):
            def __getitem__(self, i):
                return np.float32(i)

            def __len__(self):
                return 10

        loader = DataLoader(DS(), batch_size=4, shuffle=True, drop_last=True)
        batches = list(loader)
        assert len(batches) == 2

    def test_multiprocess_workers(self):
        from paddle_tpu.io import Dataset, DataLoader

        class DS(Dataset):
            def __getitem__(self, i):
                return np.full((2,), i, np.float32)

            def __len__(self):
                return 20

        loader = DataLoader(DS(), batch_size=5, num_workers=2)
        batches = list(loader)
        assert len(batches) == 4
        all_vals = sorted(int(v) for b in batches for v in b[:, 0])
        assert all_vals == list(range(20))

    def test_tensor_dataset_and_random_split(self):
        from paddle_tpu.io import TensorDataset, random_split
        ds = TensorDataset([paddle.randn([10, 2]), paddle.arange(10)])
        a, b = random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3


class TestSaveLoad:
    def test_layer_checkpoint(self, tmp_path):
        model = MLP()
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        x, y = make_blobs()
        loss = F.cross_entropy(model(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        p = str(tmp_path / "model.pdparams")
        po = str(tmp_path / "model.pdopt")
        paddle.save(model.state_dict(), p)
        paddle.save(opt.state_dict(), po)

        model2 = MLP()
        model2.set_state_dict(paddle.load(p))
        opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
        opt2.set_state_dict(paddle.load(po))
        xt = paddle.to_tensor(x)
        assert np.allclose(_np(model(xt)), _np(model2(xt)), atol=1e-6)


class TestAmp:
    def test_autocast_matmul_bf16(self):
        import jax.numpy as jnp
        a = paddle.randn([4, 4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.matmul(a, a)
        assert out.dtype == jnp.bfloat16
        out2 = paddle.matmul(a, a)
        assert out2.dtype == jnp.float32

    def test_blacklist_stays_fp32(self):
        import jax.numpy as jnp
        a = paddle.randn([4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.nn.functional.softmax(a)
        assert out.dtype == jnp.float32

    def test_grad_scaler_fp16_flow(self):
        # seeded, and lr kept below the oscillation threshold: the test
        # checks the scale/backward/step flow, not SGD at a hot lr
        paddle.seed(0)
        model = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.02,
                                   parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x, y = make_blobs()
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        first = None
        for _ in range(10):
            with paddle.amp.auto_cast(dtype="bfloat16"):
                loss = F.cross_entropy(model(xt), yt)
            if first is None:
                first = float(loss)
            scaled = scaler.scale(loss)
            scaled.backward()
            scaler.step(opt)
            opt.clear_grad()
        assert float(loss) < first

    def test_training_with_amp_converges(self):
        x, y = make_blobs()
        model = MLP()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=model.parameters())
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        for _ in range(30):
            with paddle.amp.auto_cast():
                loss = F.cross_entropy(model(xt), yt)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < 0.9


class TestPyLayer:
    def test_custom_forward_backward(self):
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor()
                return grad * 2

        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = Double.apply(x)
        paddle.sum(y * y).backward()
        # d/dx (2x)^2 = 8x = 24
        assert np.allclose(_np(x.grad), [24.0])
