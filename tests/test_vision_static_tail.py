"""Vision transforms/ops/datasets + static compat + namespace shims
(reference: vision/transforms, vision/ops.py detection ops, static/)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t._value)


class TestTransformsExtra:
    def test_flips_crops_pad(self):
        import paddle_tpu.vision.transforms as T
        img = (np.random.rand(16, 20, 3) * 255).astype(np.uint8)
        np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(T.vflip(img), img[::-1])
        assert T.crop(img, 2, 3, 10, 12).shape == (10, 12, 3)
        assert T.center_crop(img, 8).shape == (8, 8, 3)
        assert T.pad(img, 2).shape == (20, 24, 3)

    def test_geometric_identity(self):
        import paddle_tpu.vision.transforms as T
        img = (np.random.rand(16, 20, 3) * 255).astype(np.uint8)
        pts = [(0, 0), (19, 0), (19, 15), (0, 15)]
        assert np.abs(T.perspective(img, pts, pts).astype(float)
                      - img.astype(float)).mean() < 0.5
        assert np.abs(T.affine(img, 0, (0, 0), 1.0, 0).astype(float)
                      - img.astype(float)).mean() < 0.5
        assert T.rotate(img, 45, expand=True).shape[0] > 16

    def test_photometric(self):
        import paddle_tpu.vision.transforms as T
        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        assert T.adjust_brightness(img, 1.5).mean() >= img.mean()
        assert np.abs(T.adjust_hue(img, 0.0).astype(float)
                      - img.astype(float)).max() <= 2.0
        assert T.to_grayscale(img).shape == (8, 8, 1)
        assert T.ColorJitter(0.4, 0.4, 0.4, 0.2)(img).shape == img.shape
        e = T.erase(img, 1, 2, 3, 4, 0)
        assert (e[1:4, 2:6] == 0).all()

    def test_random_classes(self):
        import paddle_tpu.vision.transforms as T
        img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
        for t in [T.RandomRotation(30),
                  T.RandomAffine(10, translate=(0.1, 0.1)),
                  T.RandomPerspective(prob=1.0),
                  T.RandomErasing(prob=1.0), T.Grayscale(3)]:
            assert t(img).shape[:2] == (16, 16)


class TestVisionOpsExtra:
    def test_deform_conv_zero_offsets_equals_conv(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.vision import ops as V
        x = paddle.to_tensor(np.random.randn(1, 4, 8, 8).astype("float32"))
        w = paddle.to_tensor(np.random.randn(6, 4, 3, 3).astype("float32"))
        off = paddle.to_tensor(np.zeros((1, 18, 8, 8), "float32"))
        out = V.deform_conv2d(x, off, w, padding=1)
        ref = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(_np(out), _np(ref), atol=1e-3)

    def test_psroi_prior_matrixnms(self):
        from paddle_tpu.vision import ops as V
        xp = paddle.to_tensor(np.random.randn(1, 8, 16, 16).astype(
            "float32"))
        boxes = paddle.to_tensor(np.array([[0., 0., 8., 8.]], "float32"))
        pool = V.psroi_pool(xp, boxes, paddle.to_tensor(np.array([1])), 2)
        assert pool.shape == [1, 2, 2, 2]
        feat = paddle.to_tensor(np.zeros((1, 3, 4, 4), "float32"))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), "float32"))
        pb, pv = V.prior_box(feat, img, min_sizes=[8.], aspect_ratios=[2.],
                             flip=True)
        assert pb.shape[:2] == [4, 4] and pb.shape[3] == 4
        bb = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                        [20, 20, 30, 30]]], "float32")
        sc = np.array([[[0., 0., 0.], [0.9, 0.85, 0.8]]], "float32")
        out, num = V.matrix_nms(paddle.to_tensor(bb), paddle.to_tensor(sc),
                                0.1)
        assert int(_np(num)[0]) >= 2

    def test_yolo_box_and_loss(self):
        from paddle_tpu.vision import ops as V
        S, C = 3, 5
        xin = paddle.to_tensor(
            np.random.randn(1, S * (5 + C), 4, 4).astype("float32"),
            stop_gradient=False)
        boxes, scores = V.yolo_box(
            xin.detach(), paddle.to_tensor(np.array([[128, 128]])),
            [10, 13, 16, 30, 33, 23], C, 0.01, 32)
        assert boxes.shape == [1, S * 16, 4]
        gt_box = paddle.to_tensor(np.array(
            [[[0.5, 0.5, 0.2, 0.3]]], "float32"))
        gt_label = paddle.to_tensor(np.array([[1]]))
        loss = V.yolo_loss(xin, gt_box, gt_label,
                           [10, 13, 16, 30, 33, 23], [0, 1, 2], C, 0.7, 32)
        assert np.isfinite(_np(loss)).all()
        loss.sum().backward()
        assert xin.grad is not None and np.isfinite(_np(xin.grad)).all()

    def test_generate_and_distribute_proposals(self):
        from paddle_tpu.vision import ops as V
        an = np.random.rand(4 * 4 * 3, 4).astype("float32") * 8
        an[:, 2:] += an[:, :2] + 4
        rois, probs = V.generate_proposals(
            paddle.to_tensor(np.random.rand(1, 3, 4, 4).astype("float32")),
            paddle.to_tensor((np.random.randn(1, 12, 4, 4) * 0.1).astype(
                "float32")),
            paddle.to_tensor(np.array([[32., 32.]], "float32")),
            paddle.to_tensor(an.reshape(4, 4, 3, 4)),
            paddle.to_tensor(np.full((4, 4, 3, 4), 0.1, "float32")),
            pre_nms_top_n=20, post_nms_top_n=5)
        assert rois.shape[1] == 4 and rois.shape[0] <= 5
        multi, restore = V.distribute_fpn_proposals(
            paddle.to_tensor(np.array(
                [[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 300, 300]],
                "float32")), 2, 5, 4, 224)
        assert len(multi) == 4


class TestFolderDatasets:
    def test_dataset_and_image_folder(self, tmp_path):
        from PIL import Image
        import paddle_tpu.vision.datasets as D
        root = str(tmp_path)
        for cls in ["cat", "dog"]:
            os.makedirs(f"{root}/{cls}", exist_ok=True)
            for i in range(2):
                Image.fromarray((np.random.rand(8, 8, 3) * 255).astype(
                    "uint8")).save(f"{root}/{cls}/{i}.png")
        ds = D.DatasetFolder(root)
        assert len(ds) == 4 and ds.classes == ["cat", "dog"]
        img, lbl = ds[0]
        assert img.shape == (8, 8, 3) and lbl == 0
        assert len(D.ImageFolder(root)) == 4


class TestAudioIO:
    def test_wav_roundtrip_and_dataset(self, tmp_path):
        import paddle_tpu.audio as A
        sr = 8000
        wav = np.sin(np.linspace(0, 100, 2000)).astype("float32")[None]
        path = str(tmp_path / "t.wav")
        A.save(path, paddle.to_tensor(wav), sr)
        back, sr2 = A.load(path)
        assert sr2 == sr
        np.testing.assert_allclose(_np(back), wav, atol=1e-3)
        assert A.info(path).num_channels == 1
        from paddle_tpu.audio.datasets import AudioClassificationDataset
        ds = AudioClassificationDataset([path], [3], feat_type="mfcc")
        feat, lbl = ds[0]
        assert feat.ndim == 2 and lbl == 3


class TestTextDatasets:
    def test_wmt_and_movielens(self, tmp_path):
        import paddle_tpu.text.datasets as TD
        src, trg = tmp_path / "s.txt", tmp_path / "t.txt"
        src.write_text("hello world\nfoo bar\n")
        trg.write_text("bonjour monde\nfu ba\n")
        ds = TD.WMT16(src_file=str(src), trg_file=str(trg))
        s, t_in, t_out = ds[0]
        assert s[0] == 0 and s[-1] == 1 and len(ds) == 2
        ml = tmp_path / "ml"
        ml.mkdir()
        (ml / "users.dat").write_text("1::M::25::4::z\n")
        (ml / "movies.dat").write_text("10::A::Drama\n")
        (ml / "ratings.dat").write_text("1::10::5::1\n")
        m = TD.Movielens(data_file=str(ml), test_ratio=0.0)
        assert len(m) == 1


class TestStaticCompat:
    def test_builders_and_ema(self):
        import paddle_tpu.static as st
        x = paddle.to_tensor(np.random.randn(2, 8).astype("float32"))
        out = st.nn.fc(x, 4, activation="relu")
        assert out.shape == [2, 4] and (_np(out) >= 0).all()
        img = paddle.to_tensor(np.random.randn(1, 3, 8, 8).astype(
            "float32"))
        assert st.nn.conv2d(img, 6, 3).shape[1] == 6
        w = paddle.to_tensor(np.ones(3, "float32"), stop_gradient=False)
        ema = st.ExponentialMovingAverage(0.9)
        ema.update([w])
        with paddle.no_grad():
            w.fill_(5.0)
        ema.update([w])
        with ema.apply():
            assert _np(w)[0] < 5.0
        assert _np(w)[0] == 5.0
        # first update() with nothing to track must fail loudly, not no-op
        import pytest as _pytest
        with st.program_guard(st.Program()):
            with _pytest.raises(ValueError, match="no parameters"):
                st.ExponentialMovingAverage(0.9).update()

    def test_control_flow_and_gradients(self):
        import paddle_tpu.static as st
        assert st.nn.cond(paddle.to_tensor(np.array(True)),
                          lambda: 1, lambda: 2) == 1
        xx = paddle.to_tensor(np.random.randn(3).astype("float32"),
                              stop_gradient=False)
        g = st.gradients((xx * xx).sum(), xx)
        np.testing.assert_allclose(_np(g[0]), 2 * _np(xx), atol=1e-5)
        out = st.nn.while_loop(
            lambda v: paddle.to_tensor(np.array(v.item() < 3)),
            lambda v: [paddle.to_tensor(np.array(v.item() + 1))],
            [paddle.to_tensor(np.array(0))])
        assert out[0].item() == 3


class TestNamespaceShims:
    def test_reader_decorators(self):
        r = paddle.reader.shuffle(lambda: iter(range(10)), 4)
        assert sorted(r()) == list(range(10))
        c = paddle.reader.cache(lambda: iter(range(3)))
        assert list(c()) == [0, 1, 2] and list(c()) == [0, 1, 2]

    def test_distributed_namespaces(self):
        import paddle_tpu.distributed as d
        pm = d.passes.PassManager([d.passes.new_pass("auto_parallel_amp")])
        assert pm.names == ["auto_parallel_amp"]
        with pytest.raises(ValueError):
            d.passes.new_pass("not_a_pass")
        assert hasattr(d.sharding, "group_sharded_parallel")
        import paddle_tpu.distributed.io as dio
        assert hasattr(dio, "save_persistables")

    def test_onnx_requires_input_spec_without_p2o(self):
        # r5: onnx.export is a StableHLO bridge (tests/test_inference.py
        # TestOnnxBridge covers the artifact); without input_spec it
        # still fails loudly, not silently
        with pytest.raises(ValueError, match="input_spec"):
            paddle.onnx.export(None, "/tmp/m")
