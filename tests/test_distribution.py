"""paddle.distribution tests (reference: test/distribution/ —
per-distribution parameterized cases checking moments, log_prob vs scipy,
sampling statistics, KL closed forms vs Monte Carlo, transform bijection
and jacobian consistency)."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def _np(t):
    return np.asarray(t._value)


# (ctor, scipy frozen dist, support sampler for log_prob probes)
CASES = [
    ("Normal", lambda: D.Normal(1.0, 2.0), st.norm(1.0, 2.0),
     lambda: np.linspace(-4, 6, 11)),
    ("Uniform", lambda: D.Uniform(-1.0, 3.0), st.uniform(-1.0, 4.0),
     lambda: np.linspace(-0.9, 2.9, 7)),
    ("Bernoulli", lambda: D.Bernoulli(0.3), st.bernoulli(0.3),
     lambda: np.array([0.0, 1.0])),
    ("Beta", lambda: D.Beta(2.0, 3.0), st.beta(2.0, 3.0),
     lambda: np.linspace(0.1, 0.9, 7)),
    ("Gumbel", lambda: D.Gumbel(0.5, 1.5), st.gumbel_r(0.5, 1.5),
     lambda: np.linspace(-2, 5, 7)),
    ("Laplace", lambda: D.Laplace(0.5, 1.5), st.laplace(0.5, 1.5),
     lambda: np.linspace(-3, 4, 7)),
    ("LogNormal", lambda: D.LogNormal(0.2, 0.5), st.lognorm(0.5, 0,
                                                            np.exp(0.2)),
     lambda: np.linspace(0.3, 4.0, 7)),
    ("Geometric", lambda: D.Geometric(0.4),
     st.geom(0.4, loc=-1),  # scipy counts from 1; paddle from 0
     lambda: np.arange(0, 6, dtype=np.float64)),
    ("Cauchy", lambda: D.Cauchy(0.0, 1.0), st.cauchy(0.0, 1.0),
     lambda: np.linspace(-4, 4, 9)),
    ("Exponential", lambda: D.Exponential(1.5), st.expon(scale=1 / 1.5),
     lambda: np.linspace(0.1, 3.0, 7)),
    ("Gamma", lambda: D.Gamma(2.0, 1.5), st.gamma(2.0, scale=1 / 1.5),
     lambda: np.linspace(0.2, 4.0, 7)),
    ("Poisson", lambda: D.Poisson(3.0), st.poisson(3.0),
     lambda: np.arange(0, 9, dtype=np.float64)),
    ("StudentT", lambda: D.StudentT(5.0, 0.5, 2.0), st.t(5.0, 0.5, 2.0),
     lambda: np.linspace(-4, 5, 9)),
    ("Binomial", lambda: D.Binomial(10, 0.3), st.binom(10, 0.3),
     lambda: np.arange(0, 11, dtype=np.float64)),
    ("Chi2", lambda: D.Chi2(4.0), st.chi2(4.0),
     lambda: np.linspace(0.5, 9.0, 7)),
]


@pytest.mark.parametrize("name,mk,ref,vals", CASES,
                         ids=[c[0] for c in CASES])
def test_log_prob_matches_scipy(name, mk, ref, vals):
    d = mk()
    v = vals()
    lp = _np(d.log_prob(paddle.to_tensor(v.astype(np.float32))))
    want = ref.logpmf(v) if hasattr(ref.dist, "pmf") else ref.logpdf(v)
    np.testing.assert_allclose(lp, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name,mk,ref,vals", CASES,
                         ids=[c[0] for c in CASES])
def test_sampling_moments(name, mk, ref, vals):
    paddle.seed(0)
    d = mk()
    s = _np(d.sample((20000,))).astype(np.float64)
    assert s.shape[0] == 20000
    m_ref, v_ref = ref.stats("mv")
    if name == "Cauchy":
        return  # no moments
    np.testing.assert_allclose(s.mean(0), m_ref, rtol=0.1, atol=0.05)
    np.testing.assert_allclose(s.var(0), v_ref, rtol=0.2, atol=0.1)


@pytest.mark.parametrize("name,mk,ref,vals", CASES,
                         ids=[c[0] for c in CASES])
def test_entropy(name, mk, ref, vals):
    d = mk()
    try:
        ent = float(_np(d.entropy()))
    except NotImplementedError:
        pytest.skip("no entropy")
    want = float(ref.entropy())
    tol = 0.15 if name in ("Multinomial", "Binomial", "Poisson") else 2e-3
    assert abs(ent - want) <= tol * max(1.0, abs(want)), (ent, want)


class TestCategoricalAndFriends:
    def test_categorical(self):
        paddle.seed(0)
        probs = np.array([0.2, 0.5, 0.3], np.float32)
        d = D.Categorical(probs=paddle.to_tensor(probs))
        s = _np(d.sample((20000,)))
        freq = np.bincount(s.astype(int), minlength=3) / 20000
        np.testing.assert_allclose(freq, probs, atol=0.02)
        lp = _np(d.log_prob(paddle.to_tensor(np.array([0, 1, 2]))))
        np.testing.assert_allclose(lp, np.log(probs), rtol=1e-5)
        ent = float(_np(d.entropy()))
        assert abs(ent - st.entropy(probs)) < 1e-5

    def test_dirichlet(self):
        paddle.seed(0)
        conc = np.array([2.0, 3.0, 5.0], np.float32)
        d = D.Dirichlet(paddle.to_tensor(conc))
        s = _np(d.sample((20000,)))
        np.testing.assert_allclose(s.mean(0), conc / conc.sum(), atol=0.01)
        v = np.array([0.2, 0.3, 0.5], np.float32)
        lp = float(_np(d.log_prob(paddle.to_tensor(v))))
        assert abs(lp - st.dirichlet(conc).logpdf(v)) < 1e-3
        ent = float(_np(d.entropy()))
        assert abs(ent - st.dirichlet(conc).entropy()) < 1e-3

    def test_multinomial(self):
        paddle.seed(0)
        probs = np.array([0.3, 0.7], np.float32)
        d = D.Multinomial(10, paddle.to_tensor(probs))
        s = _np(d.sample((5000,)))
        assert np.all(s.sum(-1) == 10)
        np.testing.assert_allclose(s.mean(0), 10 * probs, atol=0.15)
        v = np.array([4.0, 6.0], np.float32)
        lp = float(_np(d.log_prob(paddle.to_tensor(v))))
        assert abs(lp - st.multinomial(10, probs).logpmf(v)) < 1e-4

    def test_continuous_bernoulli(self):
        paddle.seed(0)
        d = D.ContinuousBernoulli(paddle.to_tensor([0.3, 0.5]))
        s = _np(d.rsample((20000,)))
        assert np.all((s >= 0) & (s <= 1))
        np.testing.assert_allclose(s.mean(0), _np(d.mean), atol=0.02)
        # density integrates to ~1
        xs = np.linspace(1e-3, 1 - 1e-3, 2001, dtype=np.float32)
        p = np.exp(_np(d.log_prob(paddle.to_tensor(xs[:, None]))))
        np.testing.assert_allclose(np.trapezoid(p[:, 0], xs), 1.0,
                                   atol=5e-3)


class TestKL:
    def _mc_kl(self, p, q, n=200000):
        paddle.seed(0)
        s = p.sample((n,))
        return float(np.mean(_np(p.log_prob(s)) - _np(q.log_prob(s))))

    @pytest.mark.parametrize("mkp,mkq", [
        (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(1.0, 2.0)),
        (lambda: D.Bernoulli(0.3), lambda: D.Bernoulli(0.6)),
        (lambda: D.Beta(2.0, 3.0), lambda: D.Beta(4.0, 2.0)),
        (lambda: D.Gamma(2.0, 1.0), lambda: D.Gamma(3.0, 2.0)),
        (lambda: D.Exponential(1.0), lambda: D.Exponential(2.5)),
        (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(1.0, 2.0)),
        (lambda: D.Gumbel(0.0, 1.0), lambda: D.Gumbel(0.5, 1.5)),
        (lambda: D.Poisson(3.0), lambda: D.Poisson(5.0)),
        (lambda: D.Categorical(probs=paddle.to_tensor([0.2, 0.8])),
         lambda: D.Categorical(probs=paddle.to_tensor([0.5, 0.5]))),
        (lambda: D.Dirichlet(paddle.to_tensor([2.0, 3.0])),
         lambda: D.Dirichlet(paddle.to_tensor([1.0, 1.0]))),
    ], ids=["normal", "bernoulli", "beta", "gamma", "exponential",
            "laplace", "gumbel", "poisson", "categorical", "dirichlet"])
    def test_closed_form_matches_monte_carlo(self, mkp, mkq):
        p, q = mkp(), mkq()
        kl = float(np.sum(_np(D.kl_divergence(p, q))))
        mc = self._mc_kl(p, q)
        assert abs(kl - mc) < max(0.05, 0.08 * abs(kl)), (kl, mc)

    def test_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(0.0, 1.0), D.Gumbel(0.0, 1.0))

    def test_register_kl(self):
        class MyDist(D.Normal):
            pass

        @D.register_kl(MyDist, D.Gumbel)
        def _kl(p, q):
            return paddle.to_tensor(42.0)

        out = D.kl_divergence(MyDist(0.0, 1.0), D.Gumbel(0.0, 1.0))
        assert float(out) == 42.0


class TestTransforms:
    @pytest.mark.parametrize("t,x", [
        (D.ExpTransform(), np.linspace(-2, 2, 9)),
        (D.SigmoidTransform(), np.linspace(-3, 3, 9)),
        (D.TanhTransform(), np.linspace(-2, 2, 9)),
        (D.AffineTransform(1.0, 2.5), np.linspace(-2, 2, 9)),
        (D.PowerTransform(2.0), np.linspace(0.2, 2, 9)),
    ], ids=["exp", "sigmoid", "tanh", "affine", "power"])
    def test_bijection_and_jacobian(self, t, x):
        import jax
        x = x.astype(np.float32)
        y = _np(t.forward(paddle.to_tensor(x)))
        xr = _np(t.inverse(paddle.to_tensor(y)))
        np.testing.assert_allclose(xr, x, rtol=1e-4, atol=1e-5)
        ldj = _np(t.forward_log_det_jacobian(paddle.to_tensor(x)))
        want = np.log(np.abs(jax.vmap(jax.grad(
            lambda v: t._forward(v)))(np.asarray(x))))
        np.testing.assert_allclose(ldj, want, rtol=1e-4, atol=1e-5)
        ildj = _np(t.inverse_log_det_jacobian(paddle.to_tensor(y)))
        np.testing.assert_allclose(ildj, -want, rtol=1e-4, atol=1e-5)

    def test_stickbreaking_simplex(self):
        t = D.StickBreakingTransform()
        x = np.random.RandomState(0).randn(5, 3).astype(np.float32)
        y = _np(t.forward(paddle.to_tensor(x)))
        assert y.shape == (5, 4)
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        xr = _np(t.inverse(paddle.to_tensor(y)))
        np.testing.assert_allclose(xr, x, rtol=1e-3, atol=1e-4)

    def test_chain(self):
        t = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                              D.ExpTransform()])
        x = np.array([0.5, 1.0], np.float32)
        y = _np(t.forward(paddle.to_tensor(x)))
        np.testing.assert_allclose(y, np.exp(2 * x), rtol=1e-5)
        np.testing.assert_allclose(_np(t.inverse(paddle.to_tensor(y))), x,
                                   rtol=1e-5)


class TestComposite:
    def test_transformed_matches_lognormal(self):
        base = D.Normal(0.2, 0.5)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ln = D.LogNormal(0.2, 0.5)
        v = paddle.to_tensor(np.linspace(0.3, 3.0, 7).astype(np.float32))
        np.testing.assert_allclose(_np(td.log_prob(v)), _np(ln.log_prob(v)),
                                   rtol=1e-4)

    def test_independent(self):
        base = D.Normal(paddle.to_tensor(np.zeros((3, 4), np.float32)),
                        paddle.to_tensor(np.ones((3, 4), np.float32)))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,)
        assert ind.event_shape == (4,)
        v = paddle.to_tensor(np.random.RandomState(0)
                             .randn(3, 4).astype(np.float32))
        lp = _np(ind.log_prob(v))
        assert lp.shape == (3,)
        np.testing.assert_allclose(lp, _np(base.log_prob(v)).sum(-1),
                                   rtol=1e-5)

    def test_rsample_differentiable(self):
        import jax
        loc = paddle.to_tensor(np.float32(0.5))
        loc.stop_gradient = False
        d = D.Normal(loc, paddle.to_tensor(np.float32(1.0)))
        # rsample is a deterministic fn of (params, noise): pathwise grads
        paddle.seed(0)
        s = d.rsample((64,))
        assert s._value.shape == (64,)
        # reparameterized: mean shift moves samples 1:1
        paddle.seed(0)
        d2 = D.Normal(paddle.to_tensor(np.float32(1.5)),
                      paddle.to_tensor(np.float32(1.0)))
        s2 = d2.rsample((64,))
        np.testing.assert_allclose(_np(s2) - _np(s), 1.0, rtol=1e-5)


class TestGradientsFlow:
    """VAE/RL objectives must backprop into distribution parameters (the
    package routes all math through the op dispatcher)."""

    def test_log_prob_param_grads(self):
        loc = paddle.to_tensor(np.float32(0.5))
        loc.stop_gradient = False
        scale = paddle.to_tensor(np.float32(1.5))
        scale.stop_gradient = False
        d = D.Normal(loc, scale)
        x = paddle.to_tensor(np.array([0.1, 1.2], np.float32))
        loss = -d.log_prob(x).sum()
        loss.backward()
        assert loc.grad is not None and scale.grad is not None
        # d/dloc of -sum log N = -sum (x - loc)/scale^2
        want = float(np.sum((np.array([0.1, 1.2]) - 0.5) / 1.5 ** 2))
        np.testing.assert_allclose(float(loc.grad), -want, rtol=1e-4)

    def test_rsample_pathwise_grads(self):
        loc = paddle.to_tensor(np.float32(0.0))
        loc.stop_gradient = False
        d = D.Normal(loc, paddle.to_tensor(np.float32(1.0)))
        paddle.seed(0)
        s = d.rsample((128,))
        loss = (s ** 2).mean()
        loss.backward()
        assert loc.grad is not None
        # dE[(loc+eps)^2]/dloc = 2 loc + 2 mean(eps) ~ 2*mean(sample)
        np.testing.assert_allclose(float(loc.grad),
                                   2 * float(np.mean(_np(s))), rtol=1e-4)

    def test_kl_param_grads(self):
        loc = paddle.to_tensor(np.float32(1.0))
        loc.stop_gradient = False
        kl = D.kl_divergence(D.Normal(loc, paddle.to_tensor(np.float32(1.0))),
                             D.Normal(0.0, 1.0))
        kl.backward()
        # KL = loc²/2 → dKL/dloc = loc
        np.testing.assert_allclose(float(loc.grad), 1.0, rtol=1e-5)

    def test_transform_grads(self):
        x = paddle.to_tensor(np.array([0.3, -0.2], np.float32))
        x.stop_gradient = False
        t = D.TanhTransform()
        y = t.forward(x)
        (y ** 2).sum().backward()
        assert x.grad is not None
        want = 2 * np.tanh([0.3, -0.2]) * (1 - np.tanh([0.3, -0.2]) ** 2)
        np.testing.assert_allclose(_np(x.grad), want, rtol=1e-4)
