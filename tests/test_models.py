"""Model-family tests (BASELINE configs: LeNet✓ in test_training, ResNet,
Llama dense + MoE, GPT)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t._value)


class TestResNet:
    def test_resnet18_forward(self):
        from paddle_tpu.vision.models import resnet18
        m = resnet18(num_classes=10)
        m.eval()
        out = m(paddle.randn([2, 3, 64, 64]))
        assert out.shape == [2, 10]

    def test_resnet50_forward_backward(self):
        from paddle_tpu.vision.models import resnet50
        m = resnet50(num_classes=4)
        out = m(paddle.randn([1, 3, 64, 64]))
        loss = paddle.mean(out ** 2)
        loss.backward()
        grads = [p.grad for p in m.parameters() if not p.stop_gradient]
        assert all(g is not None for g in grads)

    @pytest.mark.slow  # vision-zoo builder sweep, ~0.5 min on CPU
    def test_mobilenet_vgg_construct(self):
        from paddle_tpu.vision.models import mobilenet_v2, vgg11
        m = mobilenet_v2(num_classes=5)
        out = m(paddle.randn([1, 3, 32, 32]))
        assert out.shape == [1, 5]
        v = vgg11(num_classes=3)
        out = v(paddle.randn([1, 3, 224, 224]))
        assert out.shape == [1, 3]


class TestLlama:
    def test_forward_shapes(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
        out = m(ids)
        assert out.shape == [2, 16, 128]

    def test_training_descends(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=m.parameters())
        data = paddle.to_tensor(
            np.random.randint(0, 128, (4, 32)))
        first = None
        for _ in range(10):
            loss = llama_loss_fn(m, data, data)
            if first is None:
                first = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.9

    def test_causality(self):
        """Changing future tokens must not affect past logits."""
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        m.eval()
        ids1 = np.random.randint(0, 128, (1, 16))
        ids2 = ids1.copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % 128
        out1 = _np(m(paddle.to_tensor(ids1)))
        out2 = _np(m(paddle.to_tensor(ids2)))
        assert np.allclose(out1[0, :-1], out2[0, :-1], atol=1e-4)
        assert not np.allclose(out1[0, -1], out2[0, -1], atol=1e-4)

    def test_recompute_matches(self):
        from paddle_tpu.models.llama import (LlamaConfig, LLAMA_PRESETS,
                                             LlamaForCausalLM, llama_loss_fn)
        paddle.seed(0)
        cfg = LlamaConfig(**LLAMA_PRESETS["debug"])
        m1 = LlamaForCausalLM(cfg)
        cfg2 = LlamaConfig(**LLAMA_PRESETS["debug"], )
        cfg2.recompute = True
        m2 = LlamaForCausalLM(cfg2)
        m2.set_state_dict(m1.state_dict())
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
        l1 = llama_loss_fn(m1, ids, ids)
        l2 = llama_loss_fn(m2, ids, ids)
        assert np.allclose(float(l1), float(l2), atol=1e-5)
        l1.backward()
        l2.backward()
        g1 = _np(m1._parameters["wq"].grad)
        g2 = _np(m2._parameters["wq"].grad)
        assert np.allclose(g1, g2, atol=1e-5)

    def test_moe_variant(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_loss_fn
        m = LlamaForCausalLM("tiny-moe")
        ids = paddle.to_tensor(np.random.randint(0, 1024, (2, 16)))
        loss = llama_loss_fn(m, ids, ids)
        loss.backward()
        assert m._parameters["we_gate"].grad is not None
        assert m._parameters["router"].grad is not None

    def test_kv_cache_generate_greedy_parity(self):
        """VERDICT #5: the fused KV-cache decode must reproduce the
        re-encode oracle token-for-token under greedy decoding."""
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        m = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 12), dtype=np.int32))
        cached = _np(m.generate(ids, max_new_tokens=10, temperature=0.0))
        legacy = _np(m.generate(ids, max_new_tokens=10, temperature=0.0,
                                use_cache=False))
        assert (cached == legacy).all()
        assert cached.shape == (2, 22)

    def test_kv_cache_generate_qwen_biases_and_tied(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(1)
        m = LlamaForCausalLM("qwen2-debug")  # attention_bias + tied embed
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (1, 8), dtype=np.int32))
        cached = _np(m.generate(ids, max_new_tokens=6, temperature=0.0))
        legacy = _np(m.generate(ids, max_new_tokens=6, temperature=0.0,
                                use_cache=False))
        assert (cached == legacy).all()

    def test_kv_cache_generate_moe_and_sampling(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("tiny-moe")
        ids = paddle.to_tensor(
            np.random.randint(0, 1024, (1, 8), dtype=np.int32))
        out = _np(m.generate(ids, max_new_tokens=6, temperature=0.0))
        assert out.shape == (1, 14)
        assert ((out >= 0) & (out < 1024)).all()
        s = _np(m.generate(ids, max_new_tokens=4, temperature=0.8, top_k=5))
        assert s.shape == (1, 12)

    def test_moe_aux_loss_applied(self):
        """VERDICT #2: the GShard aux loss must reach the training
        objective — zeroing its weight changes the loss."""
        from paddle_tpu.models.llama import (LlamaConfig, LLAMA_PRESETS,
                                             LlamaForCausalLM,
                                             llama_loss_fn)
        ids = paddle.to_tensor(np.random.randint(0, 1024, (2, 32)))
        paddle.seed(0)
        m = LlamaForCausalLM("tiny-moe")
        l_with = float(llama_loss_fn(m, ids, ids))
        paddle.seed(0)
        cfg = LlamaConfig(**LLAMA_PRESETS["tiny-moe"])
        cfg.moe_aux_loss_weight = 0.0
        m0 = LlamaForCausalLM(cfg)
        l_without = float(llama_loss_fn(m0, ids, ids))
        assert l_with > l_without  # aux term is nonnegative and nonzero
        # z-loss knob has its own observable effect
        paddle.seed(0)
        cfg_z = LlamaConfig(**LLAMA_PRESETS["tiny-moe"])
        cfg_z.moe_aux_loss_weight = 0.0
        cfg_z.moe_z_loss_weight = 0.01
        mz = LlamaForCausalLM(cfg_z)
        l_z = float(llama_loss_fn(mz, ids, ids))
        assert l_z > l_without

    def test_moe_expert_balance_improves_with_aux(self):
        """Training on the aux loss alone must rebalance a router that
        starts collapsed onto one expert (GShard me*ce objective:
        minimized at uniform load)."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn as nn
        d, E = 16, 4
        paddle.seed(1)
        experts = [nn.Linear(d, d) for _ in range(E)]
        moe = dist.fleet.MoELayer(d_model=d, experts=experts, top_k=2,
                                  capacity_factor=4.0)
        # collapse: bias routes everything to expert 0
        bias = np.zeros(E, np.float32)
        bias[0] = 5.0
        moe.gate.gate.bias.set_value(bias)
        opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                     parameters=moe.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(64, d).astype("float32"))

        def max_share():
            logits = np.asarray(moe.gate(x)._value)
            top1 = np.argmax(logits, axis=-1)
            c = np.bincount(top1, minlength=E)
            return c.max() / c.sum()

        assert max_share() > 0.9  # collapsed
        for _ in range(30):
            moe(x)
            aux = moe.l_aux
            aux.backward()
            opt.step()
            opt.clear_grad()
        assert max_share() < 0.6, max_share()

    def test_tied_embeddings(self):
        from paddle_tpu.models.llama import LlamaConfig, LLAMA_PRESETS, LlamaForCausalLM
        cfg = LlamaConfig(**LLAMA_PRESETS["debug"])
        cfg.tie_word_embeddings = True
        m = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, 128, (1, 8)))
        out = m(ids)
        assert out.shape == [1, 8, 128]
        assert "lm_head" not in m._parameters


class TestGPT:
    def test_gpt_forward_backward(self):
        from paddle_tpu.models.gpt import GPTForCausalLM
        m = GPTForCausalLM("debug")
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
        out = m(ids)
        assert out.shape == [2, 16, 128]
        loss = paddle.mean(out ** 2)
        loss.backward()


class TestGeneration:
    def test_greedy_and_sampled_generate(self):
        from paddle_tpu.models.llama import LlamaForCausalLM
        paddle.seed(0)
        model = LlamaForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 8), dtype=np.int32))
        out = model.generate(ids, max_new_tokens=4, temperature=0.0)
        arr = np.asarray(out._value)
        assert arr.shape == (2, 12)
        np.testing.assert_array_equal(arr[:, :8], np.asarray(ids._value))
        # greedy is deterministic
        out2 = model.generate(ids, max_new_tokens=4, temperature=0.0)
        np.testing.assert_array_equal(arr, np.asarray(out2._value))
        # sampling with top_k stays in-vocab and differs across seeds
        s1 = model.generate(ids, max_new_tokens=4, temperature=1.0,
                            top_k=10, seed=1)
        s2 = model.generate(ids, max_new_tokens=4, temperature=1.0,
                            top_k=10, seed=2)
        assert np.asarray(s1._value).max() < 128
        assert not np.array_equal(np.asarray(s1._value),
                                  np.asarray(s2._value))


class TestInceptionFamilies:
    """GoogLeNet + InceptionV3 (reference: vision/models/googlenet.py,
    inceptionv3.py)."""

    def test_googlenet_three_heads(self):
        from paddle_tpu.vision.models import googlenet
        m = googlenet(num_classes=6)
        m.eval()
        outs = m(paddle.randn([1, 3, 192, 192]))
        assert isinstance(outs, list) and len(outs) == 3
        assert all(o.shape == [1, 6] for o in outs)

    @pytest.mark.slow  # vision-zoo builder sweep, ~0.5 min on CPU
    def test_inception_v3_forward(self):
        from paddle_tpu.vision.models import inception_v3
        m = inception_v3(num_classes=5)
        m.eval()
        out = m(paddle.randn([1, 3, 299, 299]))
        assert out.shape == [1, 5]

    @pytest.mark.slow  # vision-zoo builder sweep, ~0.5 min on CPU
    def test_new_variants_construct(self):
        from paddle_tpu.vision.models import (
            resnext50_64x4d, shufflenet_v2_x0_33, shufflenet_v2_swish,
            densenet264)
        net = shufflenet_v2_x0_33(num_classes=4)
        out = net(paddle.randn([1, 3, 64, 64]))
        assert out.shape == [1, 4]
        sw = shufflenet_v2_swish(num_classes=4)
        out = sw(paddle.randn([1, 3, 64, 64]))
        assert out.shape == [1, 4]
        rx = resnext50_64x4d(num_classes=3)
        out = rx(paddle.randn([1, 3, 64, 64]))
        assert out.shape == [1, 3]
        assert densenet264(num_classes=2) is not None

    def test_vision_models_parity_vs_reference(self):
        """Every builder in the reference vision.models __all__ exists."""
        import re, pathlib
        import paddle_tpu.vision.models as M
        if not pathlib.Path("/root/reference").exists():
            pytest.skip("reference Paddle checkout not present")
        ref = pathlib.Path("/root/reference/python/paddle/vision/models/"
                           "__init__.py").read_text()
        names = set(re.findall(r"'([A-Za-z_][A-Za-z0-9_]*)'", ref))
        names = {n for n in names if not n[0].isupper()}
        missing = [n for n in sorted(names) if not hasattr(M, n)]
        assert missing == [], missing


class TestBertAndQwen:
    """Encoder family + Qwen2-style attention-bias decoder (reference:
    PaddleNLP bert/qwen2 modeling; in-tree nn TransformerEncoder)."""

    def test_bert_mlm_descends(self):
        from paddle_tpu.models import BertForMaskedLM
        import paddle_tpu.nn.functional as F
        m = BertForMaskedLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16), dtype=np.int32))
        mask = paddle.to_tensor(np.ones((2, 16), dtype=np.int32))
        opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
        l0 = None
        for _ in range(4):
            logits = m(ids, attention_mask=mask)
            loss = F.cross_entropy(logits.reshape([-1, 128]),
                                   ids.reshape([-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            if l0 is None:
                l0 = loss.item()
        assert logits.shape == [2, 16, 128]
        assert loss.item() < l0

    def test_bert_classifier_and_pooler(self):
        from paddle_tpu.models import BertForSequenceClassification
        cls = BertForSequenceClassification("debug", num_classes=3)
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16), dtype=np.int32))
        assert cls(ids).shape == [2, 3]

    def test_qwen2_attention_bias_trainstep(self):
        from paddle_tpu.models import LlamaForCausalLM, llama_loss_fn
        qm = LlamaForCausalLM("qwen2-debug")
        names = [n for n, _ in qm.named_parameters()]
        assert "bq" in names and "bk" in names and "bv" in names
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16), dtype=np.int32))
        opt = paddle.optimizer.AdamW(1e-3, parameters=qm.parameters())
        step = paddle.jit.TrainStep(qm, opt, llama_loss_fn)
        l0 = float(step(ids, ids))
        for _ in range(3):
            l = float(step(ids, ids))
        assert l < l0


class TestGPTGenerate:
    def test_gpt_generate_greedy(self):
        from paddle_tpu.models.gpt import GPTForCausalLM
        paddle.seed(0)
        m = GPTForCausalLM("debug")
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 8), dtype=np.int32))
        out = _np(m.generate(ids, max_new_tokens=5, temperature=0.0))
        assert out.shape == (2, 13)
        np.testing.assert_array_equal(out[:, :8], _np(ids))
        # deterministic under greedy
        out2 = _np(m.generate(ids, max_new_tokens=5, temperature=0.0))
        np.testing.assert_array_equal(out, out2)

    def test_gpt_masked_generate_matches_per_row(self):
        """r5: GPT's learned ABSOLUTE positions mean the masked path
        must shift each left-padded row's position-table lookups
        pad-relative (unlike RoPE models, where only the key exclusion
        matters) — per-row solo greedy parity proves both pieces."""
        from paddle_tpu.models.gpt import GPTForCausalLM
        paddle.seed(0)
        m = GPTForCausalLM("debug")
        rng = np.random.RandomState(0)
        n1, n2 = 9, 5
        r1 = rng.randint(1, 128, (1, n1)).astype(np.int32)
        r2 = rng.randint(1, 128, (1, n2)).astype(np.int32)
        ref1 = _np(m.generate(paddle.to_tensor(r1), max_new_tokens=5,
                              temperature=0.0))
        ref2 = _np(m.generate(paddle.to_tensor(r2), max_new_tokens=5,
                              temperature=0.0))
        s0 = 12
        rows = np.zeros((2, s0), np.int32)
        mask = np.zeros((2, s0), np.int32)
        rows[0, s0 - n1:] = r1[0]
        mask[0, s0 - n1:] = 1
        rows[1, s0 - n2:] = r2[0]
        mask[1, s0 - n2:] = 1
        out = _np(m.generate(paddle.to_tensor(rows), max_new_tokens=5,
                             temperature=0.0,
                             attention_mask=paddle.to_tensor(mask)))
        np.testing.assert_array_equal(out[0, s0 - n1:], ref1[0])
        np.testing.assert_array_equal(out[1, s0 - n2:], ref2[0])
        # the serving front now batches mixed-length GPT prompts too
        from paddle_tpu.inference.serving import GenerationPredictor
        assert GenerationPredictor(m).supports_mask()
