"""Paged KV-cache serving stack: ragged paged-attention kernel parity
(interpret mode on CPU; real Mosaic on TPU), block allocator behavior,
and the paged DecodeEngine's never-reset continuous batching."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def _random_paged(seed=0, B=3, kvh=2, G=4, hd=128, n_blocks=9, bs=16,
                  max_blocks=4, lens=(37, 5, 64)):
    """Random block pool + tables with ragged per-row lengths (one row
    mid-block, one tiny, one exactly on a block boundary)."""
    rng = np.random.RandomState(seed)
    q = rng.randn(B, kvh, G, hd).astype(np.float32) * 0.5
    kp = rng.randn(n_blocks, bs, kvh, hd).astype(np.float32) * 0.5
    vp = rng.randn(n_blocks, bs, kvh, hd).astype(np.float32) * 0.5
    lens = np.asarray(lens, np.int32)
    table = np.zeros((B, max_blocks), np.int32)
    free = list(range(1, n_blocks))          # page 0 = NULL
    for b in range(B):
        for j in range(-(-int(lens[b]) // bs)):
            table[b, j] = free.pop(0)
    return (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(lens))


class TestPagedKernel:
    def test_interpret_matches_reference(self):
        """The Pallas kernel (double-buffered page DMA + online softmax)
        must match the gather-then-masked-softmax reference on ragged
        lengths — interpret mode executes the DMA faithfully on CPU."""
        from paddle_tpu.kernels.paged_attention import (
            _paged_attn_reference, paged_attention_pallas)
        q, kp, vp, table, lens = _random_paged()
        out = paged_attention_pallas(q, kp, vp, table, lens,
                                     interpret=True)
        ref = _paged_attn_reference(q, kp, vp, table, lens)
        assert np.allclose(np.asarray(out), np.asarray(ref),
                           atol=2e-5), \
            np.abs(np.asarray(out) - np.asarray(ref)).max()

    def test_reference_is_decode_attention_math(self):
        """The XLA fallback must be the EXACT math of
        llama._decode_attention over the gathered contiguous view —
        that identity is what makes paged-engine greedy outputs
        bit-match the contiguous engine on CPU."""
        from paddle_tpu.kernels.paged_attention import (
            _paged_attn_reference, gather_pages)
        from paddle_tpu.models.llama import _decode_attention
        q, kp, vp, table, lens = _random_paged(seed=3)
        out = _paged_attn_reference(q, kp, vp, table, lens)
        ck = gather_pages(kp, table)
        cv = gather_pages(vp, table)
        mask = jnp.arange(ck.shape[1])[None, :] < lens[:, None]
        ref = _decode_attention(q, ck, cv, mask)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_null_page_tail_is_ignored(self):
        """Scribbling on the NULL page (page 0) and on padded table
        entries must not change any row's output — that is the property
        that lets inactive rows and finished-mid-chunk rows write there
        with no masks in the compiled programs."""
        from paddle_tpu.kernels.paged_attention import \
            _paged_attn_reference
        q, kp, vp, table, lens = _random_paged(seed=7)
        ref = _paged_attn_reference(q, kp, vp, table, lens)
        kp2 = kp.at[0].set(1e3)
        vp2 = vp.at[0].set(-1e3)
        out = _paged_attn_reference(q, kp2, vp2, table, lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_entry_gate_uses_reference_off_tpu(self):
        from paddle_tpu.kernels.paged_attention import (
            _paged_attn_reference, paged_decode_attention)
        if jax.default_backend() == "tpu":
            pytest.skip("CPU-only gate check")
        q, kp, vp, table, lens = _random_paged(seed=11)
        out = paged_decode_attention(q, kp, vp, table, lens)
        ref = _paged_attn_reference(q, kp, vp, table, lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def _random_mixed(seed=0, B=4, T=8, kvh=2, G=4, hd=128, n_blocks=13,
                  bs=16, max_blocks=4, kv_lens=(37, 24, 64, 16),
                  q_lens=(1, 8, 1, 8)):
    """Random pool + tables for a MIXED launch: decode rows (q_len 1)
    beside prefill-chunk rows (q_len up to T) at ragged positions."""
    rng = np.random.RandomState(seed)
    q = rng.randn(B, T, kvh, G, hd).astype(np.float32) * 0.5
    kp = rng.randn(n_blocks, bs, kvh, hd).astype(np.float32) * 0.5
    vp = rng.randn(n_blocks, bs, kvh, hd).astype(np.float32) * 0.5
    kv_lens = np.asarray(kv_lens, np.int32)
    q_lens = np.asarray(q_lens, np.int32)
    table = np.zeros((B, max_blocks), np.int32)
    free = list(range(1, n_blocks))          # page 0 = NULL
    for b in range(B):
        for j in range(-(-int(kv_lens[b]) // bs)):
            table[b, j] = free.pop(0)
    return (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(kv_lens),
            jnp.asarray(q_lens))


def _mixed_oracle(q, kp, vp, table, kv_lens, q_lens):
    """Straight-line numpy math: query i of row b sits at position
    kv_len - q_len + i and attends positions <= its own. Padding query
    slots are left at zero (callers ignore them)."""
    q, kp, vp = (np.asarray(a, np.float64) for a in (q, kp, vp))
    table = np.asarray(table)
    B, T, kvh, G, hd = q.shape
    out = np.zeros((B, T, kvh, G, hd), np.float64)
    for b in range(B):
        n, qn = int(kv_lens[b]), int(q_lens[b])
        if n == 0:
            continue
        keys = np.concatenate([kp[p] for p in table[b]], 0)[:n]
        vals = np.concatenate([vp[p] for p in table[b]], 0)[:n]
        for i in range(qn):
            pos = n - qn + i
            for h in range(kvh):
                s = q[b, i, h] @ keys[:pos + 1, h].T / np.sqrt(hd)
                s -= s.max(axis=-1, keepdims=True)
                p = np.exp(s)
                p /= p.sum(axis=-1, keepdims=True)
                out[b, i, h] = p @ vals[:pos + 1, h]
    return out.astype(np.float32)


class TestMixedKernel:
    """ISSUE 7 tentpole layer 1: one launch serves decode rows and
    prefill-chunk rows at arbitrary position offsets. Every case runs
    the Pallas kernel in interpret mode AND the XLA reference against
    the straight-line numpy oracle."""

    def _check(self, q, kp, vp, table, kv_lens, q_lens):
        from paddle_tpu.kernels.paged_attention import (
            _mixed_attn_reference, mixed_attention_pallas)
        oracle = _mixed_oracle(q, kp, vp, table, kv_lens, q_lens)
        ref = np.asarray(_mixed_attn_reference(q, kp, vp, table,
                                               kv_lens, q_lens))
        out = np.asarray(mixed_attention_pallas(q, kp, vp, table,
                                                kv_lens, q_lens,
                                                interpret=True))
        ql = np.asarray(q_lens)
        for b in range(q.shape[0]):          # padding slots excluded
            sl = (b, slice(0, int(ql[b])))
            assert np.allclose(ref[sl], oracle[sl], atol=2e-5), \
                np.abs(ref[sl] - oracle[sl]).max()
            assert np.allclose(out[sl], oracle[sl], atol=2e-5), \
                np.abs(out[sl] - oracle[sl]).max()

    def test_decode_only_rows(self):
        """q_len=1 everywhere: the mixed launch IS the decode kernel
        (each query at position len-1)."""
        self._check(*_random_mixed(seed=21, T=1,
                                   kv_lens=(37, 5, 64, 16),
                                   q_lens=(1, 1, 1, 1)))

    def test_decode_only_matches_decode_reference(self):
        """A q_len=1 mixed launch must agree with the single-query
        decode reference on the same pool (same masked-softmax math,
        modulo the extra query dim's reduction order)."""
        from paddle_tpu.kernels.paged_attention import (
            _mixed_attn_reference, _paged_attn_reference)
        q, kp, vp, table, kv_lens, q_lens = _random_mixed(
            seed=23, T=1, kv_lens=(37, 5, 64, 16), q_lens=(1, 1, 1, 1))
        mixed = np.asarray(_mixed_attn_reference(
            q, kp, vp, table, kv_lens, q_lens))[:, 0]
        dec = np.asarray(_paged_attn_reference(
            q[:, 0], kp, vp, table, kv_lens))
        assert np.allclose(mixed, dec, atol=2e-5)

    def test_chunk_only_rows(self):
        """Every row a prefill chunk mid-prompt: full q_len pages at
        position offsets, causal within the chunk."""
        self._check(*_random_mixed(seed=25, T=16,
                                   kv_lens=(48, 40, 16, 32),
                                   q_lens=(16, 16, 16, 16)))

    def test_interleaved_decode_and_chunks(self):
        """The serving shape: decode rows and chunk rows in ONE
        launch, ragged everything."""
        self._check(*_random_mixed(seed=27, T=8,
                                   kv_lens=(37, 24, 64, 16),
                                   q_lens=(1, 8, 1, 8)))

    def test_chunk_at_offset_zero_vs_mid_sequence(self):
        """A chunk whose queries START the sequence (kv_len == q_len:
        pure causal self-attention) beside one deep into resident
        history — the offset arithmetic must hold at both extremes."""
        self._check(*_random_mixed(seed=29, T=16,
                                   kv_lens=(16, 61, 64, 30),
                                   q_lens=(16, 16, 16, 14)))

    def test_final_partial_chunk(self):
        """The last chunk of a prompt is usually SHORTER than the
        window: q_len < T with padding query slots, and a kv_len that
        ends mid-page."""
        self._check(*_random_mixed(seed=31, T=16,
                                   kv_lens=(37, 21, 5, 50),
                                   q_lens=(5, 3, 5, 2)))

    def test_inactive_row_outputs_zeros(self):
        """kv_len=0 lanes (inactive slots in a fixed-shape launch)
        output exact zeros from BOTH the kernel and the reference —
        no NaNs leak from the empty softmax."""
        from paddle_tpu.kernels.paged_attention import (
            _mixed_attn_reference, mixed_attention_pallas)
        q, kp, vp, table, kv_lens, q_lens = _random_mixed(
            seed=33, T=8, kv_lens=(37, 0, 64, 0), q_lens=(1, 0, 8, 0))
        ref = np.asarray(_mixed_attn_reference(q, kp, vp, table,
                                               kv_lens, q_lens))
        out = np.asarray(mixed_attention_pallas(q, kp, vp, table,
                                                kv_lens, q_lens,
                                                interpret=True))
        assert np.all(np.isfinite(ref)) and np.all(np.isfinite(out))
        np.testing.assert_array_equal(ref[1], np.zeros_like(ref[1]))
        np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))

    def test_entry_gate_uses_reference_off_tpu(self):
        from paddle_tpu.kernels.paged_attention import (
            _mixed_attn_reference, mixed_paged_attention)
        if jax.default_backend() == "tpu":
            pytest.skip("CPU-only gate check")
        q, kp, vp, table, kv_lens, q_lens = _random_mixed(seed=35)
        out = mixed_paged_attention(q, kp, vp, table, kv_lens, q_lens)
        ref = _mixed_attn_reference(q, kp, vp, table, kv_lens, q_lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestBlockAllocator:
    def _alloc(self, n=9):
        from paddle_tpu.inference.paged_cache import BlockAllocator
        return BlockAllocator(n)

    def test_never_hands_out_null_page(self):
        a = self._alloc(9)
        pages = a.allocate(a.capacity)
        assert pages is not None and 0 not in pages
        assert sorted(pages) == list(range(1, 9))

    def test_all_or_nothing(self):
        a = self._alloc(9)
        assert a.allocate(9) is None          # > capacity: nothing taken
        assert a.num_free == 8
        first = a.allocate(6)
        assert a.allocate(3) is None          # only 2 left
        assert a.num_free == 2                # failed alloc took nothing
        a.free(first)
        assert a.num_free == 8

    def test_double_free_and_foreign_free_raise(self):
        a = self._alloc(5)
        pages = a.allocate(2)
        a.free(pages)
        with pytest.raises(ValueError):
            a.free(pages)                     # double free
        with pytest.raises(ValueError):
            a.free([0])                       # NULL page was never owned

    def test_fragmentation_interleaved_alloc_free(self):
        """Pages freed by interleaved retiring rows are reusable at once
        — a paged pool has no fragmentation failure mode (that is the
        point vs contiguous regions)."""
        a = self._alloc(17)                   # 16 usable
        rows = [a.allocate(4) for _ in range(4)]
        assert all(r is not None for r in rows)
        a.free(rows[0])
        a.free(rows[2])                       # free alternating rows
        again = a.allocate(8)                 # fits exactly in the holes
        assert again is not None
        assert sorted(again) == sorted(rows[0] + rows[2])
        assert a.num_free == 0
        assert a.stats() == {"capacity": 16, "used": 16, "free": 0,
                             "high_watermark": 16,
                             "total_allocated": 24, "total_freed": 8}

    def test_rejects_degenerate_pool(self):
        from paddle_tpu.inference.paged_cache import BlockAllocator
        with pytest.raises(ValueError):
            BlockAllocator(1)                 # only the NULL page


class TestPagedEngine:
    """The tentpole acceptance: paged DecodeEngine greedy outputs
    bit-match the contiguous engine AND solo generation, and sustained
    mixed arrivals never hit a reset."""

    def _model(self):
        paddle.seed(0)
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        m.eval()
        return m

    @staticmethod
    def _drive(eng, pending, iters=200):
        for _ in range(iters):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                return
        raise AssertionError("engine did not drain the workload")

    def _workload(self, rng):
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (8, 10, 5, 6, 7, 5, 6, 4)]
        max_news = [16, 16, 4, 4, 4, 4, 4, 4]
        return prompts, max_news

    def test_paged_matches_contiguous_and_solo(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(1)
        prompts, max_news = self._workload(rng)
        solo = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=mn,
            temperature=0.0)._value)[0]
            for p, mn in zip(prompts, max_news)]

        def run(**kw):
            eng = DecodeEngine(m, capacity=4, s_max=96, chunk=4, **kw)
            reqs = [_Request(p, mn)
                    for p, mn in zip(prompts, max_news)]
            pending = list(reqs)
            self._drive(eng, pending)
            return eng, [r.wait(timeout=1) for r in reqs]

        paged_eng, paged_out = run(paged=True, block_size=16)
        contig_eng, contig_out = run(paged=False)
        for po, co, so in zip(paged_out, contig_out, solo):
            np.testing.assert_array_equal(po, so)
            np.testing.assert_array_equal(po, co)
        assert paged_eng.resets == 1          # construction only

    def test_sustained_admission_never_resets(self):
        """Continuous mixed arrivals far past the contiguous engine's
        global-fill horizon: the paged engine keeps admitting into freed
        pages and NEVER resets (the contiguous engine's failure mode
        this PR removes)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(2)
        eng = DecodeEngine(m, capacity=3, s_max=64, chunk=4,
                           block_size=8)
        solo = {}
        reqs, pending = [], []
        for i in range(12):                  # 12 staggered arrivals,
            n = int(rng.randint(3, 10))      # mixed lengths/max_new
            mn = int(rng.choice([3, 5, 9]))
            p = rng.randint(1, 128, (n,)).astype(np.int32)
            r = _Request(p, mn)
            solo[id(r)] = np.asarray(m.generate(
                paddle.to_tensor(p[None, :]), max_new_tokens=mn,
                temperature=0.0)._value)[0]
            reqs.append(r)
        # feed 2 per iteration: admission happens while earlier rows
        # are mid-generation, the continuous-batching shape
        queue = list(reqs)
        for _ in range(400):
            while queue and len(pending) < 2:
                pending.append(queue.pop(0))
            eng.admit(pending)
            eng.decode_once()
            if not queue and not pending and eng.idle():
                break
        else:
            raise AssertionError("engine did not drain")
        total_new = sum(r.max_new for r in reqs)
        assert total_new > eng.s_max         # past the global-fill horizon
        assert eng.resets == 1               # construction only — no reset
        for r in reqs:
            np.testing.assert_array_equal(r.wait(timeout=1),
                                          solo[id(r)])

    def test_admission_waits_for_pages_then_serves(self):
        """A pool too small for the whole wave: admission defers (no
        error) until retiring rows free pages; every request still
        serves with solo-parity tokens."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 128, (12,)).astype(np.int32)
                   for _ in range(4)]
        solo = [np.asarray(m.generate(
            paddle.to_tensor(p[None, :]), max_new_tokens=4,
            temperature=0.0)._value)[0] for p in prompts]
        # 5 usable pages of 8 tokens: each row (prompt 12 + new 4 = 16)
        # needs exactly 2 pages at admission and never grows; 4 rows at
        # once would need 8 — admission must take turns on the pool
        eng = DecodeEngine(m, capacity=4, s_max=32, chunk=4,
                           block_size=8, n_blocks=6)
        reqs = [_Request(p, 4) for p in prompts]
        pending = list(reqs)
        self._drive(eng, pending)
        for r, s in zip(reqs, solo):
            np.testing.assert_array_equal(r.wait(timeout=1), s)
        assert eng.resets == 1

    def test_pool_exhaustion_fails_only_the_hungry_row(self):
        """When growth genuinely exhausts the pool, only a row that
        needed new pages fails; its freed pages let the others finish
        (ADVICE r5 #3 in paged form)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(4)
        p1 = rng.randint(1, 128, (7,)).astype(np.int32)
        p2 = rng.randint(1, 128, (5,)).astype(np.int32)
        solo2 = np.asarray(m.generate(
            paddle.to_tensor(p2[None, :]), max_new_tokens=3,
            temperature=0.0)._value)[0]
        # 3 usable pages of 8: row 2 (5 + 3 = 8 tokens) lives entirely
        # in its one admission page; the 40-token row grows chunk by
        # chunk, absorbs the page row 2 frees at retire, and still
        # starves — it alone gets the exhaustion error
        eng = DecodeEngine(m, capacity=2, s_max=64, chunk=4,
                           block_size=8, n_blocks=4)
        r1, r2 = _Request(p1, 40), _Request(p2, 3)
        pending = [r1, r2]
        self._drive(eng, pending)
        with pytest.raises(RuntimeError, match="exhausted|s_max"):
            r1.wait(timeout=1)
        np.testing.assert_array_equal(r2.wait(timeout=1), solo2)
        assert eng._alloc.num_used == 0      # everything returned

    def test_row_hitting_s_max_fails_alone(self):
        """A row whose generation would outgrow s_max fails at the
        boundary; its neighbor is untouched (no engine-wide error, no
        reset)."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(5)
        p1 = rng.randint(1, 128, (6,)).astype(np.int32)
        p2 = rng.randint(1, 128, (6,)).astype(np.int32)
        solo2 = np.asarray(m.generate(
            paddle.to_tensor(p2[None, :]), max_new_tokens=5,
            temperature=0.0)._value)[0]
        eng = DecodeEngine(m, capacity=2, s_max=24, chunk=4,
                           block_size=8)
        r1, r2 = _Request(p1, 64), _Request(p2, 5)
        pending = [r1, r2]
        self._drive(eng, pending)
        with pytest.raises(RuntimeError, match="s_max"):
            r1.wait(timeout=1)
        np.testing.assert_array_equal(r2.wait(timeout=1), solo2)
        assert eng.resets == 1


class TestContiguousClampedFinalChunk:
    """ADVICE r5 #3 (contiguous mode): at cache exhaustion, rows whose
    remaining max_new fits the leftover fill ride ONE clamped chunk out;
    only rows that genuinely cannot fit get the exhaustion error."""

    def _model(self):
        paddle.seed(0)
        from paddle_tpu.models.llama import LlamaForCausalLM
        m = LlamaForCausalLM("debug")
        m.eval()
        return m

    def test_near_finished_row_completes(self):
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(6)
        pa = rng.randint(1, 128, (8,)).astype(np.int32)
        pb = rng.randint(1, 128, (8,)).astype(np.int32)
        solo_b = np.asarray(m.generate(
            paddle.to_tensor(pb[None, :]), max_new_tokens=28,
            temperature=0.0)._value)[0]
        # fill walks 8 -> 32 in chunks of 8; the next chunk would cross
        # s_max=36, leaving space for 4: row B needs 3 more (fits the
        # clamp), row A needs 15 (cannot)
        eng = DecodeEngine(m, capacity=2, s_max=36, chunk=8,
                           paged=False)
        ra, rb = _Request(pa, 40), _Request(pb, 28)
        pending = [ra, rb]
        for _ in range(50):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                break
        with pytest.raises(RuntimeError, match="exhausted"):
            ra.wait(timeout=1)
        np.testing.assert_array_equal(rb.wait(timeout=1), solo_b)
        assert eng.resets >= 2               # clamp drained, then reset

    def test_no_survivors_still_resets(self):
        """Every row too hungry for the leftover fill: all fail (the
        old behavior) and the engine resets for the next burst."""
        from paddle_tpu.inference.serving import DecodeEngine, _Request
        m = self._model()
        rng = np.random.RandomState(7)
        pa = rng.randint(1, 128, (8,)).astype(np.int32)
        eng = DecodeEngine(m, capacity=2, s_max=36, chunk=8,
                           paged=False)
        ra = _Request(pa, 60)
        pending = [ra]
        for _ in range(50):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                break
        with pytest.raises(RuntimeError, match="exhausted"):
            ra.wait(timeout=1)
        assert eng.idle() and eng.resets >= 2
