"""Incubate fused-op tests (reference: test/legacy_test/
test_fused_rotary_position_embedding.py, test_rms_norm_op.py, swiglu)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import functional as IF


def _np(t):
    return np.asarray(t._value)


class TestFusedOps:
    def test_fused_rms_norm_matches_reference_math(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 5, 8).astype(np.float32)
        w = rng.rand(8).astype(np.float32)
        out = IF.fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(w),
                                epsilon=1e-5)
        want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
        np.testing.assert_allclose(_np(out), want, rtol=1e-5)

    def test_fused_rms_norm_residual(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 8).astype(np.float32)
        r = rng.randn(2, 8).astype(np.float32)
        w = np.ones(8, np.float32)
        out, res_out = IF.fused_rms_norm(
            paddle.to_tensor(x), paddle.to_tensor(w),
            residual=paddle.to_tensor(r))
        np.testing.assert_allclose(_np(res_out), x + r, rtol=1e-6)
        s = x + r
        want = s / np.sqrt((s ** 2).mean(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(_np(out), want, rtol=1e-5)

    def test_fused_layer_norm(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 8).astype(np.float32)
        w = rng.rand(8).astype(np.float32)
        b = rng.rand(8).astype(np.float32)
        out = IF.fused_layer_norm(paddle.to_tensor(x), paddle.to_tensor(w),
                                  paddle.to_tensor(b))
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        want = (x - mu) / np.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)

    def test_fused_rope_matches_llama_kernel(self):
        """The public op and the flagship's private path share numerics."""
        from paddle_tpu.models.llama import _rope
        rng = np.random.RandomState(2)
        b, s, h, d = 2, 6, 4, 8
        q = rng.randn(b, s, h, d).astype(np.float32)
        pos = np.broadcast_to(np.arange(s)[None], (b, s))
        want = _rope(q, pos, 10000.0, d)
        qo, ko, vo = IF.fused_rotary_position_embedding(
            paddle.to_tensor(q), paddle.to_tensor(q))
        np.testing.assert_allclose(_np(qo), np.asarray(want), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(_np(ko), np.asarray(want), rtol=1e-5,
                                   atol=1e-6)
        assert vo is None

    def test_swiglu(self):
        rng = np.random.RandomState(3)
        x = rng.randn(4, 6).astype(np.float32)
        y = rng.randn(4, 6).astype(np.float32)
        out = IF.swiglu(paddle.to_tensor(x), paddle.to_tensor(y))
        sil = x / (1 + np.exp(-x))
        np.testing.assert_allclose(_np(out), sil * y, rtol=1e-5)
        out2 = IF.swiglu(paddle.to_tensor(np.concatenate([x, y], -1)))
        np.testing.assert_allclose(_np(out2), sil * y, rtol=1e-5)

    def test_masked_multihead_attention_decode(self):
        rng = np.random.RandomState(4)
        b, h, d, t = 2, 3, 4, 5
        x = rng.randn(b, 3 * h * d).astype(np.float32)
        cache = rng.randn(2, b, h, t, d).astype(np.float32)
        out, new_cache = IF.masked_multihead_attention(
            paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache))
        assert _np(out).shape == (b, h * d)
        assert _np(new_cache).shape == (2, b, h, t + 1, d)
        # reference math for one (b,h)
        qkv = x.reshape(b, 3, h, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        ks = np.concatenate([cache[0], k[:, :, None]], axis=2)
        vs = np.concatenate([cache[1], v[:, :, None]], axis=2)
        s = np.einsum("bhd,bhtd->bht", q, ks) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bht,bhtd->bhd", p, vs).reshape(b, h * d)
        np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)

    def test_grads_flow(self):
        x = paddle.to_tensor(np.random.RandomState(5)
                             .randn(2, 8).astype(np.float32))
        x.stop_gradient = False
        w = paddle.to_tensor(np.ones(8, np.float32))
        w.stop_gradient = False
        out = IF.fused_rms_norm(x, w)
        out.sum().backward()
        assert x.grad is not None and w.grad is not None


class TestFleetWrappers:
    def test_hybrid_clip_applies_global_norm(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.hybrid_optimizer import (
            HybridParallelClipGrad, HybridParallelOptimizer)
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=net.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(0.5))
        hopt = HybridParallelOptimizer(opt)
        assert isinstance(opt._grad_clip, HybridParallelClipGrad)
        x = paddle.randn([8, 4])
        loss = (net(x) ** 2).sum() * 100  # big grads
        loss.backward()
        hopt.step()
        # after clip the applied update magnitude is bounded
        hopt.clear_grad()

    def test_meta_parallel_wrappers_forward(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_parallel import (
            DataParallelModel, SegmentParallel, TensorParallel)
        net = nn.Linear(4, 2)
        x = paddle.randn([4, 4])
        want = _np(net(x))
        for cls in (DataParallelModel, TensorParallel):
            np.testing.assert_allclose(_np(cls(net)(x)), want, rtol=1e-6)
        sp = SegmentParallel(net)
        np.testing.assert_allclose(_np(sp(x)), want, rtol=1e-6)


class TestIncubateOptimizers:
    def _net_and_data(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        net = nn.Linear(4, 2)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(8, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.randint(0, 2, (8,)))
        return net, x, y

    def test_lookahead_syncs_slow_weights(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate import LookAhead
        net, x, y = self._net_and_data()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=2)
        w0 = np.asarray(net.weight._value).copy()
        losses = []
        for _ in range(4):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # after k-multiples the fast weights equal the slow weights
        np.testing.assert_allclose(np.asarray(net.weight._value),
                                   opt._slow[id(net.weight)])
        assert not np.allclose(np.asarray(net.weight._value), w0)

    def test_model_average_apply_restore(self):
        from paddle_tpu.incubate import ModelAverage
        net, x, y = self._net_and_data()
        ma = ModelAverage(parameters=net.parameters())
        vals = []
        for i in range(3):
            net.weight._in_place_update(net.weight._value + 1.0)
            ma.step()
            vals.append(np.asarray(net.weight._value).copy())
        cur = np.asarray(net.weight._value).copy()
        ma.apply()
        np.testing.assert_allclose(np.asarray(net.weight._value),
                                   np.mean(vals, axis=0), rtol=1e-6)
        ma.restore()
        np.testing.assert_allclose(np.asarray(net.weight._value), cur)

    def test_gradient_merge_accumulates(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.incubate.optimizer import GradientMergeOptimizer
        net, x, y = self._net_and_data()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net.parameters())
        opt = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        w0 = np.asarray(net.weight._value).copy()
        loss = F.cross_entropy(net(x), y)
        loss.backward()
        opt.step()                      # step 1: no update yet
        np.testing.assert_allclose(np.asarray(net.weight._value), w0)
        loss = F.cross_entropy(net(x), y)
        loss.backward()
        opt.step()                      # step 2: merged update fires
        assert not np.allclose(np.asarray(net.weight._value), w0)
        # merged-averaged step == single step on same data (same grads)
        g_equiv = w0 - np.asarray(net.weight._value)
        assert np.abs(g_equiv).max() > 0

    def test_get_logger(self):
        from paddle_tpu.distributed.fleet.utils import get_logger
        lg = get_logger("t_unit")
        lg.info("hello")
        assert lg.name == "t_unit"
