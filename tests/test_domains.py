"""sparse / geometric / quantization tests (reference: test/legacy_test
sparse+geometric op tests; test/quantization/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import geometric, quantization, sparse


def _np(t):
    return np.asarray(t._value)


class TestSparse:
    def _coo(self):
        dense = np.array([[0, 1.5, 0], [2.0, 0, 0], [0, 0, -3.0]],
                         np.float32)
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        val = np.array([1.5, 2.0, -3.0], np.float32)
        return dense, sparse.sparse_coo_tensor(idx, val, [3, 3])

    def test_coo_roundtrip(self):
        dense, s = self._coo()
        assert s.is_sparse_coo() and s.nnz == 3
        np.testing.assert_allclose(_np(s.to_dense()), dense)
        np.testing.assert_allclose(_np(s.values()), [1.5, 2.0, -3.0])
        assert _np(s.indices()).shape == (2, 3)

    def test_dense_to_sparse_methods(self):
        dense, _ = self._coo()
        t = paddle.to_tensor(dense)
        coo = t.to_sparse_coo(2)
        assert coo.nnz == 3
        csr = t.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(_np(csr.to_dense()), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(_np(back.to_dense()), dense)

    def test_csr_accessors(self):
        dense = np.array([[1, 0, 2], [0, 0, 3]], np.float32)
        csr = paddle.to_tensor(dense).to_sparse_csr()
        np.testing.assert_array_equal(_np(csr.crows()), [0, 2, 3])
        np.testing.assert_array_equal(_np(csr.cols()), [0, 2, 2])
        np.testing.assert_allclose(_np(csr.values()), [1, 2, 3])

    def test_unary_binary(self):
        dense, s = self._coo()
        out = sparse.relu(s)
        np.testing.assert_allclose(_np(out.to_dense()),
                                   np.maximum(dense, 0))
        total = sparse.add(s, s)
        np.testing.assert_allclose(_np(total.to_dense()), 2 * dense)

    def test_matmul(self):
        dense, s = self._coo()
        y = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        out = sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(_np(out), dense @ y, rtol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        x = rng.rand(3, 5).astype(np.float32)
        y = rng.rand(5, 3).astype(np.float32)
        _, mask = self._coo()
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        want = np.where(_np(mask.to_dense()) != 0, full, 0)
        np.testing.assert_allclose(_np(out.to_dense()), want, rtol=1e-5)


class TestGeometric:
    def test_segment_ops(self):
        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                         np.float32))
        seg = np.array([0, 0, 1])
        np.testing.assert_allclose(_np(geometric.segment_sum(data, seg)),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_mean(data, seg)),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_max(data, seg)),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_min(data, seg)),
                                   [[1, 2], [5, 6]])

    def test_send_u_recv(self):
        x = paddle.to_tensor(np.array([[1.], [2.], [4.]], np.float32))
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 1, 0])
        out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
        # dst0 <- x[0]; dst1 <- x[0]+x[2]; dst2 <- x[1]
        np.testing.assert_allclose(_np(out), [[1.], [5.], [2.]])
        out = geometric.send_u_recv(x, src, dst, reduce_op="max")
        np.testing.assert_allclose(_np(out), [[1.], [4.], [2.]])

    def test_send_ue_recv_and_uv(self):
        x = paddle.to_tensor(np.array([[1.], [2.]], np.float32))
        e = paddle.to_tensor(np.array([[10.], [20.]], np.float32))
        src = np.array([0, 1])
        dst = np.array([1, 0])
        out = geometric.send_ue_recv(x, e, src, dst, "add", "sum")
        np.testing.assert_allclose(_np(out), [[22.], [11.]])
        uv = geometric.send_uv(x, x, src, dst, "mul")
        np.testing.assert_allclose(_np(uv), [[2.], [2.]])

    def test_send_u_recv_grad(self):
        x = paddle.to_tensor(np.array([[1.], [2.], [4.]], np.float32))
        x.stop_gradient = False
        out = geometric.send_u_recv(x, np.array([0, 0, 1]),
                                    np.array([1, 2, 0]), "sum")
        out.sum().backward()
        np.testing.assert_allclose(_np(x.grad), [[2.], [1.], [0.]])

    def test_reindex_graph(self):
        x = np.array([5, 9])
        neighbors = np.array([9, 7, 5, 7])
        count = np.array([2, 2])
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(_np(nodes), [5, 9, 7])
        np.testing.assert_array_equal(_np(src), [1, 2, 0, 2])
        np.testing.assert_array_equal(_np(dst), [0, 0, 1, 1])

    def test_sample_neighbors(self):
        # CSC: node0 -> {1,2}, node1 -> {2}, node2 -> {}
        row = np.array([1, 2, 2])
        colptr = np.array([0, 2, 3, 3])
        nbr, cnt = geometric.sample_neighbors(row, colptr, np.array([0, 1]))
        np.testing.assert_array_equal(_np(cnt), [2, 1])
        assert set(_np(nbr)[:2]) == {1, 2}


class TestQuantization:
    def test_fake_quant_roundtrip_and_ste(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 9).astype(np.float32))
        x.stop_gradient = False
        q = quantization.quant(x, 1.0, bits=8)
        err = np.abs(_np(q) - _np(x)).max()
        assert err <= 1.0 / 127 + 1e-6
        q.sum().backward()
        np.testing.assert_allclose(_np(x.grad), np.ones(9))  # STE

    def test_qat_wraps_and_trains(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = quantization.QuantConfig(
            activation=quantization.FakeQuanterWithAbsMaxObserver(),
            weight=quantization.FakeQuanterWithAbsMaxObserver())
        qat = quantization.QAT(cfg)
        qnet = qat.quantize(net, inplace=False)
        assert isinstance(qnet[0], quantization.QuantedLinear)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=qnet.parameters())
        x = paddle.randn([16, 8])
        y = paddle.to_tensor(np.random.randint(0, 2, (16,)))
        l0 = None
        for _ in range(5):
            loss = nn.functional.cross_entropy(qnet(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            l0 = l0 or float(loss)
        assert float(loss) < l0
        converted = qat.convert(qnet, inplace=False)
        out = converted(x)
        assert np.all(np.isfinite(_np(out)))

    def test_ptq_observes(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 2))
        cfg = quantization.QuantConfig(
            activation=quantization.FakeQuanterWithAbsMaxObserver(),
            weight=quantization.FakeQuanterWithAbsMaxObserver())
        ptq = quantization.PTQ(cfg)
        qnet = ptq.quantize(net, inplace=False)
        for _ in range(3):
            qnet(paddle.randn([8, 4]))  # calibration
        final = ptq.convert(qnet, inplace=False)
        out = final(paddle.randn([8, 4]))
        assert np.all(np.isfinite(_np(out)))

    def test_observer(self):
        obs = quantization.AbsmaxObserver()
        obs.observe(paddle.to_tensor([1.0, -3.0]))
        obs.observe(paddle.to_tensor([2.0]))
        assert obs.scale() == 3.0


class TestAudio:
    def test_spectrogram_matches_numpy_stft(self):
        from paddle_tpu import audio
        rng = np.random.RandomState(0)
        x = rng.randn(1, 1024).astype(np.float32)
        spec = audio.Spectrogram(n_fft=256, hop_length=128)(
            paddle.to_tensor(x))
        got = _np(spec)[0]
        # numpy reference STFT (hann, centered, power 2)
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(256) / 256)
        xp = np.pad(x[0], 128, mode="reflect")
        frames = np.stack([xp[i * 128:i * 128 + 256] * w
                           for i in range(1 + (len(xp) - 256) // 128)])
        want = np.abs(np.fft.rfft(frames, axis=-1)) ** 2
        np.testing.assert_allclose(got, want.T, rtol=1e-3, atol=1e-3)

    def test_mel_and_mfcc_shapes(self):
        from paddle_tpu import audio
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 2048).astype(np.float32))
        mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert _np(mel).shape[:2] == (2, 40)
        logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert np.all(np.isfinite(_np(logmel)))
        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
        assert _np(mfcc).shape[:2] == (2, 13)

    def test_functional_parity(self):
        from paddle_tpu.audio import functional as AF
        # librosa-documented fixed points of the slaney scale
        assert abs(AF.hz_to_mel(1000.0) - 15.0) < 1e-4
        assert abs(AF.mel_to_hz(15.0) - 1000.0) < 1e-2
        assert abs(AF.hz_to_mel(AF.mel_to_hz(27.3)) - 27.3) < 1e-3
        fb = _np(AF.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257) and fb.min() >= 0
        dct = _np(AF.create_dct(13, 40))
        assert dct.shape == (40, 13)
        # DCT-II ortho columns are orthonormal
        np.testing.assert_allclose(dct.T @ dct, np.eye(13), atol=1e-4)

    def test_spectrogram_grad(self):
        from paddle_tpu import audio
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(1, 512).astype(np.float32))
        x.stop_gradient = False
        spec = audio.Spectrogram(n_fft=128, hop_length=64)(x)
        spec.sum().backward()
        assert x.grad is not None
        assert np.all(np.isfinite(_np(x.grad)))
