"""sparse / geometric / quantization tests (reference: test/legacy_test
sparse+geometric op tests; test/quantization/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import geometric, quantization, sparse


def _np(t):
    return np.asarray(t._value)


class TestSparse:
    def _coo(self):
        dense = np.array([[0, 1.5, 0], [2.0, 0, 0], [0, 0, -3.0]],
                         np.float32)
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        val = np.array([1.5, 2.0, -3.0], np.float32)
        return dense, sparse.sparse_coo_tensor(idx, val, [3, 3])

    def test_coo_roundtrip(self):
        dense, s = self._coo()
        assert s.is_sparse_coo() and s.nnz == 3
        np.testing.assert_allclose(_np(s.to_dense()), dense)
        np.testing.assert_allclose(_np(s.values()), [1.5, 2.0, -3.0])
        assert _np(s.indices()).shape == (2, 3)

    def test_dense_to_sparse_methods(self):
        dense, _ = self._coo()
        t = paddle.to_tensor(dense)
        coo = t.to_sparse_coo(2)
        assert coo.nnz == 3
        csr = t.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(_np(csr.to_dense()), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(_np(back.to_dense()), dense)

    def test_csr_accessors(self):
        dense = np.array([[1, 0, 2], [0, 0, 3]], np.float32)
        csr = paddle.to_tensor(dense).to_sparse_csr()
        np.testing.assert_array_equal(_np(csr.crows()), [0, 2, 3])
        np.testing.assert_array_equal(_np(csr.cols()), [0, 2, 2])
        np.testing.assert_allclose(_np(csr.values()), [1, 2, 3])

    def test_unary_binary(self):
        dense, s = self._coo()
        out = sparse.relu(s)
        np.testing.assert_allclose(_np(out.to_dense()),
                                   np.maximum(dense, 0))
        total = sparse.add(s, s)
        np.testing.assert_allclose(_np(total.to_dense()), 2 * dense)

    def test_matmul(self):
        dense, s = self._coo()
        y = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        out = sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(_np(out), dense @ y, rtol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        x = rng.rand(3, 5).astype(np.float32)
        y = rng.rand(5, 3).astype(np.float32)
        _, mask = self._coo()
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        want = np.where(_np(mask.to_dense()) != 0, full, 0)
        np.testing.assert_allclose(_np(out.to_dense()), want, rtol=1e-5)


class TestGeometric:
    def test_segment_ops(self):
        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                         np.float32))
        seg = np.array([0, 0, 1])
        np.testing.assert_allclose(_np(geometric.segment_sum(data, seg)),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_mean(data, seg)),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_max(data, seg)),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(_np(geometric.segment_min(data, seg)),
                                   [[1, 2], [5, 6]])

    def test_send_u_recv(self):
        x = paddle.to_tensor(np.array([[1.], [2.], [4.]], np.float32))
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 1, 0])
        out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
        # dst0 <- x[0]; dst1 <- x[0]+x[2]; dst2 <- x[1]
        np.testing.assert_allclose(_np(out), [[1.], [5.], [2.]])
        out = geometric.send_u_recv(x, src, dst, reduce_op="max")
        np.testing.assert_allclose(_np(out), [[1.], [4.], [2.]])

    def test_send_ue_recv_and_uv(self):
        x = paddle.to_tensor(np.array([[1.], [2.]], np.float32))
        e = paddle.to_tensor(np.array([[10.], [20.]], np.float32))
        src = np.array([0, 1])
        dst = np.array([1, 0])
        out = geometric.send_ue_recv(x, e, src, dst, "add", "sum")
        np.testing.assert_allclose(_np(out), [[22.], [11.]])
        uv = geometric.send_uv(x, x, src, dst, "mul")
        np.testing.assert_allclose(_np(uv), [[2.], [2.]])

    def test_send_u_recv_grad(self):
        x = paddle.to_tensor(np.array([[1.], [2.], [4.]], np.float32))
        x.stop_gradient = False
        out = geometric.send_u_recv(x, np.array([0, 0, 1]),
                                    np.array([1, 2, 0]), "sum")
        out.sum().backward()
        np.testing.assert_allclose(_np(x.grad), [[2.], [1.], [0.]])

    def test_reindex_graph(self):
        x = np.array([5, 9])
        neighbors = np.array([9, 7, 5, 7])
        count = np.array([2, 2])
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(_np(nodes), [5, 9, 7])
        np.testing.assert_array_equal(_np(src), [1, 2, 0, 2])
        np.testing.assert_array_equal(_np(dst), [0, 0, 1, 1])

    def test_sample_neighbors(self):
        # CSC: node0 -> {1,2}, node1 -> {2}, node2 -> {}
        row = np.array([1, 2, 2])
        colptr = np.array([0, 2, 3, 3])
        nbr, cnt = geometric.sample_neighbors(row, colptr, np.array([0, 1]))
        np.testing.assert_array_equal(_np(cnt), [2, 1])
        assert set(_np(nbr)[:2]) == {1, 2}


class TestQuantization:
    def test_fake_quant_roundtrip_and_ste(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 9).astype(np.float32))
        x.stop_gradient = False
        q = quantization.quant(x, 1.0, bits=8)
        err = np.abs(_np(q) - _np(x)).max()
        assert err <= 1.0 / 127 + 1e-6
        q.sum().backward()
        np.testing.assert_allclose(_np(x.grad), np.ones(9))  # STE

    def test_qat_wraps_and_trains(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = quantization.QuantConfig(
            activation=quantization.FakeQuanterWithAbsMaxObserver(),
            weight=quantization.FakeQuanterWithAbsMaxObserver())
        qat = quantization.QAT(cfg)
        qnet = qat.quantize(net, inplace=False)
        assert isinstance(qnet[0], quantization.QuantedLinear)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=qnet.parameters())
        x = paddle.randn([16, 8])
        y = paddle.to_tensor(np.random.randint(0, 2, (16,)))
        l0 = None
        for _ in range(5):
            loss = nn.functional.cross_entropy(qnet(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            l0 = l0 or float(loss)
        assert float(loss) < l0
        converted = qat.convert(qnet, inplace=False)
        out = converted(x)
        assert np.all(np.isfinite(_np(out)))

    def test_ptq_observes(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 2))
        cfg = quantization.QuantConfig(
            activation=quantization.FakeQuanterWithAbsMaxObserver(),
            weight=quantization.FakeQuanterWithAbsMaxObserver())
        ptq = quantization.PTQ(cfg)
        qnet = ptq.quantize(net, inplace=False)
        for _ in range(3):
            qnet(paddle.randn([8, 4]))  # calibration
        final = ptq.convert(qnet, inplace=False)
        out = final(paddle.randn([8, 4]))
        assert np.all(np.isfinite(_np(out)))

    def test_observer(self):
        obs = quantization.AbsmaxObserver()
        obs.observe(paddle.to_tensor([1.0, -3.0]))
        obs.observe(paddle.to_tensor([2.0]))
        assert obs.scale() == 3.0


class TestAudio:
    def test_spectrogram_matches_numpy_stft(self):
        from paddle_tpu import audio
        rng = np.random.RandomState(0)
        x = rng.randn(1, 1024).astype(np.float32)
        spec = audio.Spectrogram(n_fft=256, hop_length=128)(
            paddle.to_tensor(x))
        got = _np(spec)[0]
        # numpy reference STFT (hann, centered, power 2)
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(256) / 256)
        xp = np.pad(x[0], 128, mode="reflect")
        frames = np.stack([xp[i * 128:i * 128 + 256] * w
                           for i in range(1 + (len(xp) - 256) // 128)])
        want = np.abs(np.fft.rfft(frames, axis=-1)) ** 2
        np.testing.assert_allclose(got, want.T, rtol=1e-3, atol=1e-3)

    def test_mel_and_mfcc_shapes(self):
        from paddle_tpu import audio
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 2048).astype(np.float32))
        mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert _np(mel).shape[:2] == (2, 40)
        logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
        assert np.all(np.isfinite(_np(logmel)))
        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
        assert _np(mfcc).shape[:2] == (2, 13)

    def test_functional_parity(self):
        from paddle_tpu.audio import functional as AF
        # librosa-documented fixed points of the slaney scale
        assert abs(AF.hz_to_mel(1000.0) - 15.0) < 1e-4
        assert abs(AF.mel_to_hz(15.0) - 1000.0) < 1e-2
        assert abs(AF.hz_to_mel(AF.mel_to_hz(27.3)) - 27.3) < 1e-3
        fb = _np(AF.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257) and fb.min() >= 0
        dct = _np(AF.create_dct(13, 40))
        assert dct.shape == (40, 13)
        # DCT-II ortho columns are orthonormal
        np.testing.assert_allclose(dct.T @ dct, np.eye(13), atol=1e-4)

    def test_spectrogram_grad(self):
        from paddle_tpu import audio
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(1, 512).astype(np.float32))
        x.stop_gradient = False
        spec = audio.Spectrogram(n_fft=128, hop_length=64)(x)
        spec.sum().backward()
        assert x.grad is not None
        assert np.all(np.isfinite(_np(x.grad)))


class TestVisionOps:
    def test_nms_matches_reference_algorithm(self):
        from paddle_tpu.vision import ops as V
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60],
                          [0, 0, 5, 5]], np.float32)
        scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
        keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                                paddle.to_tensor(scores))._value)
        # box1 overlaps box0 (iou>0.5) -> suppressed; others kept
        kept = [i for i in keep.tolist() if i >= 0]
        assert kept == [0, 2, 3]

    def test_nms_category_aware(self):
        from paddle_tpu.vision import ops as V
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1])
        keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                                paddle.to_tensor(scores),
                                category_idxs=paddle.to_tensor(cats),
                                categories=[0, 1])._value)
        kept = [i for i in keep.tolist() if i >= 0]
        assert kept == [0, 1]   # different categories: no suppression

    def test_roi_align_uniform_region(self):
        from paddle_tpu.vision import ops as V
        x = paddle.to_tensor(np.full((1, 1, 8, 8), 3.0, np.float32))
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], np.float32))
        out = V.roi_align(x, boxes, [1], output_size=2, aligned=False)
        arr = np.asarray(out._value)
        assert arr.shape == (1, 1, 2, 2)
        np.testing.assert_allclose(arr, 3.0, rtol=1e-5)

    def test_roi_align_differentiable(self):
        from paddle_tpu.vision import ops as V
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(1, 2, 8, 8).astype(np.float32))
        x.stop_gradient = False
        boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
        out = V.roi_align(x, boxes, [1], output_size=3)
        out.sum().backward()
        assert x.grad is not None
        assert np.abs(np.asarray(x.grad._value)).sum() > 0

    def test_roi_pool_max_semantics(self):
        from paddle_tpu.vision import ops as V
        img = np.zeros((1, 1, 8, 8), np.float32)
        img[0, 0, 2, 2] = 9.0
        out = V.roi_pool(paddle.to_tensor(img),
                         paddle.to_tensor(np.array([[0, 0, 7, 7]],
                                                   np.float32)),
                         [1], output_size=2)
        arr = np.asarray(out._value)
        assert arr.max() == 9.0 and arr.shape == (1, 1, 2, 2)

    def test_box_coder_roundtrip(self):
        from paddle_tpu.vision import ops as V
        prior = np.array([[0, 0, 10, 10], [5, 5, 15, 25]], np.float32)
        target = np.array([[1, 1, 9, 11], [6, 4, 14, 28]], np.float32)
        enc = V.box_coder(paddle.to_tensor(prior), None,
                          paddle.to_tensor(target),
                          code_type="encode_center_size")
        dec = V.box_coder(paddle.to_tensor(prior), None, enc,
                          code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec._value), target,
                                   rtol=1e-4, atol=1e-4)

    def test_box_iou(self):
        from paddle_tpu.vision import ops as V
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]],
                     np.float32)
        iou = np.asarray(V.box_iou(paddle.to_tensor(a),
                                   paddle.to_tensor(b))._value)
        np.testing.assert_allclose(iou[0], [1.0, 25 / 175, 0.0], rtol=1e-5)


class TestMoreVisionModels:
    def test_alexnet_and_squeezenet_forward(self):
        from paddle_tpu.vision.models import alexnet, squeezenet1_1
        paddle.seed(0)
        net = alexnet(num_classes=10)
        x = paddle.randn([1, 3, 224, 224])
        net.eval()
        out = net(x)
        assert list(out.shape) == [1, 10]
        sq = squeezenet1_1(num_classes=7)
        sq.eval()
        out2 = sq(x)
        assert list(out2.shape) == [1, 7]

    def test_roi_pool_large_roi_exact_max(self):
        """Regression: fixed 4-samples/bin missed maxima in large ROIs."""
        from paddle_tpu.vision import ops as V
        img = np.zeros((1, 1, 64, 64), np.float32)
        img[0, 0, 3, 5] = 9.0
        out = V.roi_pool(paddle.to_tensor(img),
                         paddle.to_tensor(np.array([[0, 0, 63, 63]],
                                                   np.float32)),
                         [1], output_size=2)
        assert np.asarray(out._value).max() == 9.0

    @pytest.mark.slow  # vision-zoo builder sweep, ~0.5 min on CPU
    def test_mobilenetv1_and_densenet_forward(self):
        from paddle_tpu.vision.models import densenet121, mobilenet_v1
        paddle.seed(0)
        m = mobilenet_v1(scale=0.25, num_classes=6)
        m.eval()
        out = m(paddle.randn([1, 3, 64, 64]))
        assert list(out.shape) == [1, 6]
        d = densenet121(num_classes=5)
        d.eval()
        out2 = d(paddle.randn([1, 3, 64, 64]))
        assert list(out2.shape) == [1, 5]

    def test_channel_shuffle_and_shufflenet(self):
        import paddle_tpu.nn.functional as F
        x = np.arange(1 * 4 * 1 * 1, dtype=np.float32).reshape(1, 4, 1, 1)
        out = np.asarray(F.channel_shuffle(paddle.to_tensor(x), 2)._value)
        # [0,1,2,3] grouped as (2,2) -> transposed -> [0,2,1,3]
        np.testing.assert_array_equal(out.reshape(-1), [0, 2, 1, 3])
        from paddle_tpu.vision.models import shufflenet_v2_x0_25
        paddle.seed(0)
        net = shufflenet_v2_x0_25(num_classes=4)
        net.eval()
        out2 = net(paddle.randn([1, 3, 64, 64]))
        assert list(out2.shape) == [1, 4]

    @pytest.mark.slow  # vision-zoo builder sweep, ~0.5 min on CPU
    def test_mobilenetv3_forward(self):
        from paddle_tpu.vision.models import (mobilenet_v3_large,
                                              mobilenet_v3_small)
        paddle.seed(0)
        m = mobilenet_v3_small(scale=0.5, num_classes=3)
        m.eval()
        assert list(m(paddle.randn([1, 3, 64, 64])).shape) == [1, 3]
        lg = mobilenet_v3_large(scale=0.35, num_classes=2)
        lg.eval()
        assert list(lg(paddle.randn([1, 3, 64, 64])).shape) == [1, 2]


class TestPTQCalibration:
    """PTQ calibration (inventory item 33 depth): observe-only
    calibration, frozen scales at convert, and outlier-robust observers."""

    def _model(self):
        paddle.seed(0)
        return paddle.nn.Sequential(paddle.nn.Linear(16, 32),
                                    paddle.nn.ReLU(),
                                    paddle.nn.Linear(32, 4))

    def test_ptq_calibrate_freeze_convert(self):
        from paddle_tpu.quantization import (PTQ, QuantConfig, EMAObserver,
                                             FakeQuanterWithAbsMaxObserver,
                                             QuantedLinear)
        from paddle_tpu.quantization import _CalibrationQuanter
        m = self._model()
        q = PTQ(QuantConfig(activation=EMAObserver(),
                            weight=FakeQuanterWithAbsMaxObserver()))
        qm = q.quantize(m)
        x = paddle.randn([8, 16])
        ref = _np(m(x))
        # calibration forwards: weights fake-quanted (8-bit error only),
        # activations OBSERVE-only (raw float through the matmuls)
        out_cal = _np(qm(x))
        assert np.abs(out_cal - ref).max() < 0.2
        q.calibrate(qm, [(x,)] * 3)
        qm = q.convert(qm)
        for layer in qm.sublayers(include_self=True):
            if isinstance(layer, QuantedLinear):
                aq = layer.activation_quanter
                assert isinstance(aq, _CalibrationQuanter)
                assert aq.frozen_scale is not None and aq.frozen_scale > 0
                assert layer.weight_quanter is None  # baked
        # converted model still close to float reference (8-bit error)
        out_q = _np(qm(x))
        assert np.abs(out_q - ref).max() < 0.35

    def test_percentile_observer_robust_to_outliers(self):
        from paddle_tpu.quantization import (AbsmaxObserver,
                                             PercentileObserver)
        rng = np.random.RandomState(0)
        data = rng.randn(4096).astype(np.float32)
        data[0] = 1000.0                       # one spike
        t = paddle.to_tensor(data)
        absx = AbsmaxObserver()
        absx.observe(t)
        pct = PercentileObserver(percentile=99.0)
        pct.observe(t)
        # absmax range is blown up by the outlier; percentile is not
        assert absx.scale() > 500.0
        assert pct.scale() < 5.0
        # and the percentile range quantizes the BULK better
        def err(rng_):
            q = np.clip(np.round(data / rng_ * 127), -127, 127) * rng_ / 127
            return np.abs(q - data)[1:].mean()  # exclude the spike
        assert err(pct.scale()) < err(absx.scale()) / 10


class TestSparseDepth:
    """Sparse depth (SURVEY item 34): attention, conv, norm, pooling,
    low-rank and complex unary parity with the reference surface."""

    def test_sparse_attention_matches_masked_dense(self):
        from paddle_tpu.sparse.nn_functional import attention
        rng = np.random.RandomState(0)
        b, h, s, d = 1, 2, 8, 4
        q = paddle.to_tensor(rng.randn(b, h, s, d).astype("float32"))
        k = paddle.to_tensor(rng.randn(b, h, s, d).astype("float32"))
        v = paddle.to_tensor(rng.randn(b, h, s, d).astype("float32"))
        mask = np.tril(np.ones((s, s), np.float32))
        smask = paddle.to_tensor(mask).to_sparse_csr()
        out = attention(q, k, v, smask)
        # dense oracle
        sc = np.einsum("bhsd,bhtd->bhst", _np(q), _np(k)) / np.sqrt(d)
        sc = np.where(mask[None, None] != 0, sc, -np.inf)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = np.einsum("bhst,bhtd->bhsd", p, _np(v))
        np.testing.assert_allclose(_np(out), want, atol=1e-5)

    def test_subm_conv_keeps_sparsity_pattern(self):
        from paddle_tpu.sparse import nn as snn
        paddle.seed(0)
        x = np.zeros((1, 6, 6, 2), np.float32)
        x[0, 1, 1] = 1.0
        x[0, 4, 3] = 2.0
        coo = paddle.to_tensor(x).to_sparse_coo()
        conv = snn.SubmConv2D(2, 3, kernel_size=3, padding=1)
        out = conv(coo)
        dense = _np(out.to_dense())
        active_in = (x != 0).any(-1)
        active_out = (dense != 0).any(-1)
        # submanifold: no dilation of the active set
        assert (active_out <= active_in).all()
        # regular conv DOES dilate
        conv2 = snn.Conv2D(2, 3, kernel_size=3, padding=1)
        d2 = _np(conv2(coo).to_dense())
        assert ((d2 != 0).any(-1).sum() > active_in.sum())

    def test_sparse_batchnorm_active_stats(self):
        from paddle_tpu.sparse import nn as snn
        x = np.zeros((2, 4, 4, 4, 3), np.float32)
        x[0, 0, 0, 0] = [1.0, 2.0, 3.0]
        x[1, 1, 2, 3] = [3.0, 4.0, 5.0]
        coo = paddle.to_tensor(x).to_sparse_coo()
        bn = snn.BatchNorm(3)
        out = _np(bn(coo).to_dense())
        active = (x != 0).any(-1)
        assert (out[~active] == 0).all()
        vals = out[active]
        np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-4)

    def test_max_pool3d(self):
        from paddle_tpu.sparse import nn as snn
        x = np.zeros((1, 4, 4, 4, 1), np.float32)
        x[0, 0, 0, 0, 0] = 5.0
        x[0, 3, 3, 3, 0] = 7.0
        coo = paddle.to_tensor(x).to_sparse_coo()
        out = _np(snn.MaxPool3D(2, stride=2)(coo).to_dense())
        assert out.shape == (1, 2, 2, 2, 1)
        assert out[0, 0, 0, 0, 0] == 5.0 and out[0, 1, 1, 1, 0] == 7.0

    def test_svd_lowrank_and_complex_unary(self):
        rng = np.random.RandomState(0)
        X = rng.randn(12, 6).astype(np.float32)
        u, s, v = sparse.svd_lowrank(paddle.to_tensor(X), q=3)
        s_ref = np.linalg.svd(X, compute_uv=False)[:3]
        np.testing.assert_allclose(_np(s), s_ref, rtol=1e-3)
        z = (rng.randn(3, 3) + 1j * rng.randn(3, 3)).astype("complex64")
        zc = sparse.conjugate(paddle.to_tensor(z))
        np.testing.assert_allclose(_np(zc), z.conj())
        zt = sparse.transjugate(paddle.to_tensor(z))
        np.testing.assert_allclose(_np(zt), z.conj().T)
