"""hapi Model tests (reference: test/legacy_test/test_model.py — fit/
evaluate/predict on LeNet + callbacks; hapi/model.py:1052,1754)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset


class SyntheticMnist(Dataset):
    """Linearly separable 'MNIST': images whose mean brightness by
    quadrant encodes the class — learnable by LeNet in a few steps."""

    def __init__(self, n=128, seed=0):
        rng = np.random.RandomState(seed)
        self.x = np.zeros((n, 1, 28, 28), np.float32)
        self.y = rng.randint(0, 4, (n,)).astype(np.int64)
        for i, c in enumerate(self.y):
            img = rng.rand(28, 28).astype(np.float32) * 0.1
            r, cq = divmod(int(c), 2)
            img[r * 14:(r + 1) * 14, cq * 14:(cq + 1) * 14] += 0.9
            self.x[i, 0] = img

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.flatten = nn.Flatten()
        self.fc1 = nn.Linear(784, 32)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(self.flatten(x))))


def _prepared_model():
    paddle.seed(7)
    net = SmallNet()
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy(topk=(1,)))
    return model


class TestModelFit:
    def test_fit_converges_and_callbacks_fire(self, tmp_path, capsys):
        model = _prepared_model()
        ds = SyntheticMnist(96)
        fired = []

        class Spy(paddle.callbacks.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                fired.append(("epoch_begin", epoch))

            def on_train_batch_end(self, step, logs=None):
                fired.append(("batch", step))

        hist = model.fit(ds, ds, batch_size=32, epochs=3, verbose=2,
                         save_dir=str(tmp_path / "ckpt"),
                         callbacks=[Spy()])
        out = capsys.readouterr().out
        assert "Epoch 1/3" in out            # ProgBarLogger
        assert ("epoch_begin", 0) in fired and ("batch", 0) in fired
        assert hist["loss"][-1] < hist["loss"][0]
        # checkpoint written (ModelCheckpoint via save_dir)
        assert (tmp_path / "ckpt" / "final.pdparams").exists()
        # converged enough to beat chance by a wide margin
        metrics = model.evaluate(ds, batch_size=32)
        assert metrics["acc"] > 0.8, metrics

    def test_evaluate_and_predict(self):
        model = _prepared_model()
        ds = SyntheticMnist(64)
        model.fit(ds, batch_size=32, epochs=2, verbose=0)
        metrics = model.evaluate(ds, batch_size=32, verbose=0)
        assert set(metrics) >= {"loss", "acc"}
        preds = model.predict(ds, batch_size=32, stack_outputs=True)
        assert preds[0].shape == (64, 4)
        acc = (preds[0].argmax(-1) == ds.y).mean()
        assert acc > 0.8

    def test_save_load_roundtrip(self, tmp_path):
        model = _prepared_model()
        ds = SyntheticMnist(32)
        model.fit(ds, batch_size=16, epochs=1, verbose=0)
        model.save(str(tmp_path / "m"))
        model2 = _prepared_model()
        model2.load(str(tmp_path / "m"))
        p1 = model.predict(ds, batch_size=16, stack_outputs=True)[0]
        p2 = model2.predict(ds, batch_size=16, stack_outputs=True)[0]
        np.testing.assert_allclose(p1, p2, atol=1e-6)

    def test_train_eval_predict_batch(self):
        model = _prepared_model()
        x = np.random.rand(8, 1, 28, 28).astype(np.float32)
        y = np.random.randint(0, 4, (8,)).astype(np.int64)
        losses = model.train_batch([x], [y])
        assert len(losses) == 1 and np.isfinite(losses[0])
        losses2, outs = model.eval_batch([x], [y])
        assert np.isfinite(losses2[0]) and outs._value.shape == (8, 4)
        preds = model.predict_batch([x])
        assert preds[0]._value.shape == (8, 4)

    def test_summary(self, capsys):
        model = _prepared_model()
        info = model.summary()
        out = capsys.readouterr().out
        assert "Total params" in out
        assert info["total_params"] == 784 * 32 + 32 + 32 * 4 + 4
        info2 = paddle.summary(SmallNet())
        assert info2["total_params"] == info["total_params"]


class TestCallbacks:
    def test_early_stopping(self):
        model = _prepared_model()
        ds = SyntheticMnist(64)
        es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0,
                                            mode="min", verbose=0)
        # with patience 0 and a tiny lr the eval loss plateaus fast
        model._optimizer.set_lr(0.0)
        model.fit(ds, ds, batch_size=32, epochs=6, verbose=0,
                  callbacks=[es])
        assert model.stop_training

    def test_reduce_lr_on_plateau(self):
        model = _prepared_model()
        ds = SyntheticMnist(32)
        model._optimizer.set_lr(0.1)
        cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                                patience=1, verbose=0,
                                                mode="min")
        model._optimizer.set_lr(0.1)
        # freeze learning so loss can't improve -> lr halves
        for p in model.network.parameters():
            p.stop_gradient = True
        model.fit(ds, ds, batch_size=32, epochs=4, verbose=0,
                  callbacks=[cb])
        assert float(model._optimizer.get_lr()) < 0.1

    def test_lr_scheduler_callback(self):
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=1, gamma=0.5)
        paddle.seed(1)
        net = SmallNet()
        model = paddle.Model(net)
        opt = paddle.optimizer.SGD(learning_rate=sched,
                                   parameters=net.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        ds = SyntheticMnist(16)
        model.fit(ds, batch_size=16, epochs=2, verbose=0,
                  callbacks=[paddle.callbacks.LRScheduler(by_step=False,
                                                          by_epoch=True)])
        assert float(opt.get_lr()) < 0.1


class TestHapiJitFit:
    """prepare(jit=True): the train batch compiles into ONE executable
    (TrainStep has_aux) — numerics match the eager path and metrics see
    the compiled outputs."""

    def _fit(self, jit):
        import paddle_tpu.hapi as hapi

        class DS:
            def __len__(self):
                return 32

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                x = rng.randn(8).astype(np.float32)
                return x, x[:1] * 2.0

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 1))
        model = hapi.Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=net.parameters()),
                      nn.MSELoss(), jit=jit)
        import warnings
        with warnings.catch_warnings():
            # vacuity guard: the silent eager fallback emits a
            # RuntimeWarning — promote it so a broken jit path FAILS
            # instead of comparing eager vs eager
            warnings.simplefilter("error", RuntimeWarning)
            model.fit(DS(), batch_size=8, epochs=2, verbose=0,
                      shuffle=False)
        if jit:
            assert model._jit is True, "jit fit silently fell back to eager"
            assert model._jit_steps_run == 8, \
                f"expected 8 compiled batches, ran {model._jit_steps_run}"
        return [np.asarray(p._value) for p in net.parameters()]

    def test_jit_matches_eager(self):
        eager = self._fit(False)
        jit = self._fit(True)
        for a, b in zip(eager, jit):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_untraceable_falls_back(self):
        import pytest
        import paddle_tpu.hapi as hapi

        class Weird(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x):
                if float(paddle.sum(x)) > 1e9:   # host round trip
                    return self.fc(x) * 2
                return self.fc(x)

        paddle.seed(0)
        net = Weird()
        model = hapi.Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=net.parameters()),
                      nn.MSELoss(), jit=True)
        x = np.random.randn(4, 4).astype(np.float32)
        y = np.zeros((4, 1), np.float32)
        with pytest.warns(RuntimeWarning, match="not fully traceable"):
            losses = model.train_batch([x], [y])
        assert np.isfinite(losses[0])
        assert model._jit is False               # permanent fallback
        losses2 = model.train_batch([x], [y])    # now silent eager
        assert np.isfinite(losses2[0])

    def test_jit_eval_predict_match_eager(self):
        import paddle_tpu.hapi as hapi
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 1))
        x = np.random.randn(4, 8).astype(np.float32)
        y = np.random.randn(4, 1).astype(np.float32)

        me = hapi.Model(net)
        me.prepare(loss=nn.MSELoss(), jit=False)
        l_e, o_e = me.eval_batch([x], [y])
        p_e = me.predict_batch([x])

        mj = hapi.Model(net)
        mj.prepare(loss=nn.MSELoss(), jit=True)
        l_j, o_j = mj.eval_batch([x], [y])
        p_j = mj.predict_batch([x])
        np.testing.assert_allclose(l_e, l_j, atol=1e-6)
        np.testing.assert_allclose(np.asarray(o_e._value),
                                   np.asarray(o_j._value), atol=1e-6)
        np.testing.assert_allclose(np.asarray(p_e[0]._value),
                                   np.asarray(p_j[0]._value), atol=1e-6)
