"""Benchmark: Llama pretraining tokens/sec/chip on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = achieved MFU / 0.40 (the BASELINE.md north-star target of
>=40% MFU for Llama pretraining). Runs a compiled train step (forward +
backward + AdamW, bf16 compute / fp32 master weights) on one chip.
"""

from __future__ import annotations

import json
import time

import numpy as np


def peak_flops_per_chip() -> float:
    """bf16 peak for the local chip kind."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def main():
    import jax
    on_tpu = jax.default_backend() not in ("cpu",)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_loss_fn)

    import os
    paddle.seed(0)
    preset = os.environ.get("BENCH_PRESET", "default")
    if on_tpu:
        # Two measured presets (see BASELINE.md "Measured" table):
        #   default — ~700M params at the 8B target's EXACT layer dims
        #     (hidden 4096, ff 14336, 32 heads / 8 kv heads, head_dim 128 —
        #     the llama3-8b preset), depth cut to 2 layers so fp32 master
        #     weights + Adam moments fit one v5e chip's 16G HBM. Per-layer
        #     arithmetic intensity is what the v5p-64 north star scales from.
        #   deep — 508M at d2048/ff5632/L8: validates that scan-over-layers
        #     + remat at real depth holds the MFU the 2-layer row reports.
        if preset == "deep":
            # head_dim stays 128 (16 heads at d2048) — the MXU-friendly
            # head width the 8B target uses
            dims = dict(hidden=2048, ff=5632, layers=8, batch=8, heads=16)
        else:
            dims = dict(hidden=4096, ff=14336, layers=2, batch=6, heads=32)
        cfg = LlamaConfig(
            vocab_size=int(os.environ.get("BENCH_VOCAB", 32000)),
            hidden_size=int(os.environ.get("BENCH_HIDDEN", dims["hidden"])),
            intermediate_size=int(os.environ.get("BENCH_FF", dims["ff"])),
            num_hidden_layers=int(os.environ.get("BENCH_LAYERS",
                                                 dims["layers"])),
            num_attention_heads=int(os.environ.get(
                "BENCH_HEADS", dims["heads"])), num_key_value_heads=8,
            max_position_embeddings=4096, dtype="bfloat16",
            recompute=bool(int(os.environ.get("BENCH_RECOMPUTE", 1))),
            recompute_granularity=os.environ.get("BENCH_REMAT", "core_attn"))
        batch = int(os.environ.get("BENCH_BATCH", dims["batch"]))
        seq = int(os.environ.get("BENCH_SEQ", 2048))
        iters = int(os.environ.get("BENCH_ITERS", 20))
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        batch, seq, iters = 2, 128, 3

    model = LlamaForCausalLM(cfg)
    n_params = sum(p.size for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    mesh = dist.ProcessMesh(shape=[len(jax.devices())], dim_names=["dp"])
    dist.shard_model_state(model, mesh)

    step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh, donate=True)

    # Fresh batch per step so the printed loss is a correctness signal,
    # not single-batch memorization. Sequences carry learnable structure
    # (noisy affine next-token process) so the loss FALLS from ~ln(V)
    # toward the process entropy as training proceeds — a causality or
    # optimizer bug shows up as a flat/rising loss.
    rng = np.random.default_rng(0)
    support = min(256, cfg.vocab_size)  # restricted support: the unigram
    # marginal (~ln(support)) is learnable within the bench's few steps,
    # so a falling loss is visible even in a 20-step timing run

    def fresh_batch():
        toks = np.empty((batch, seq), dtype=np.int32)
        toks[:, 0] = rng.integers(0, support, batch)
        noise = rng.integers(-2, 3, size=(batch, seq - 1))
        for t in range(1, seq):
            toks[:, t] = (toks[:, t - 1] * 5 + 17 + noise[:, t - 1]) \
                % support
        return paddle.to_tensor(toks)

    batches = [fresh_batch() for _ in range(iters + 1)]
    # compile + warmup (fetch to host: block_until_ready is a no-op through
    # the remote-TPU tunnel)
    loss_first = float(step(batches[-1], batches[-1]))
    loss = loss_first
    t0 = time.perf_counter()
    for i in range(iters):
        loss = step(batches[i], batches[i])
    float(loss)  # steps chain through donated params; fetch syncs them all
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * iters / dt
    # fwd+bwd dense approximation over MATMUL params only: the input
    # embedding is a gather, not a matmul, so counting it would inflate
    # MFU (standard MFU convention; lm_head IS a matmul and stays in)
    n_embed = cfg.vocab_size * cfg.hidden_size
    flops_per_token = 6.0 * (n_params - n_embed)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / (peak_flops_per_chip() * len(jax.devices()))
    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / len(jax.devices()), 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "params": int(n_params),
                  "batch": batch, "seq": seq, "preset": preset,
                  "loss_first": round(loss_first, 4),
                  "loss": round(float(loss), 4),
                  "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
