"""Benchmark: Llama pretraining tokens/sec/chip on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = achieved MFU / 0.40 (the BASELINE.md north-star target of
>=40% MFU for Llama pretraining). Runs a compiled train step (forward +
backward + AdamW, bf16 compute / fp32 master weights) on one chip.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Backend-init probe (VERDICT r4 weak #1): the remote-TPU tunnel is
# measurably flaky — backend init either raises UNAVAILABLE or hangs
# outright, so the probe must run in a KILLABLE subprocess with a wall
# timeout, not in-process. Bounded retry with backoff; on final failure
# emit ONE structured JSON line the driver can record as an infra-skip
# and exit 0 (a stack-trace rc=1 reads as a code regression, which this
# is not).
def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true",
                                                        "yes", "on")


_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 90))
_PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", 3))
_PROBE_BACKOFF_S = (0, 45, 90)
# Wall limit for the whole bench run: the observed hang mode is not just
# backend INIT — a collective can stall mid-bench after a clean probe.
# Must stay UNDER the driver's own ~15-min kill or the wall never fires.
_WALL_TIMEOUT_S = int(os.environ.get("BENCH_WALL_TIMEOUT", 720))

_PRESET_METRICS = {
    "flash32k": "flash_attention_32k_fwd_bwd_ms",
    "decode": "decode_tokens_per_sec",
    "engine": "engine_decode_tokens_per_sec",
    "prefix": "prefix_cached_ttft_ms",
    "fleet": "fleet_affinity_ttft_ms",
    "slo": "slo_shipper_overhead_pct",
    "overload": "overload_p99_ttft_ms",
    "mixed": "mixed_p99_ttft_ms",
    "spec": "spec_tokens_per_step",
    "chaos": "chaos_goodput_ratio",
    "disagg": "disagg_p99_ttft_ms",
    "smoke": "smoke_wall_seconds",
    "tp": "tp_device_calls_per_step",
    "cp": "cp_p99_ttft_steps",
}


def _is_infra_error_text(msg: str) -> bool:
    """Lenient matcher for PROBE-child stderr, where the only failure
    diversity is backend init."""
    needles = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "backend setup",
               "failed to connect", "Unable to initialize backend",
               "socket closed", "connection reset")
    return any(n.lower() in msg.lower() for n in needles)


def _is_infra_error(exc: BaseException) -> bool:
    """Strict matcher for in-process exceptions: anchor on grpc status
    classes case-sensitively, so a code-caused error whose message
    merely mentions 'unavailable' doesn't become a silent infra-skip."""
    msg = str(exc)
    return ("UNAVAILABLE" in msg or "DEADLINE_EXCEEDED" in msg
            or "Unable to initialize backend" in msg)


def _emit_infra_skip(detail: str) -> None:
    preset = os.environ.get("BENCH_PRESET", "default")
    print(json.dumps({
        "metric": _PRESET_METRICS.get(
            preset, "llama_pretrain_tokens_per_sec_per_chip"),
        "error": "backend_unavailable",
        "detail": detail[:400],
    }), flush=True)


_LIVE_CHILDREN: list = []   # pids a parent signal handler must reap


def _install_parent_handlers() -> None:
    """SIGTERM/SIGINT during ANY phase (probe included) must reap the
    live child process groups — a dead parent waiting on a hung probe
    would otherwise orphan a tunnel-holding subprocess."""
    import signal

    def bail(signum, frame):
        for pid in list(_LIVE_CHILDREN):
            _killpg_quietly(pid, signal.SIGKILL)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, bail)
    signal.signal(signal.SIGINT, bail)


def probe_backend() -> None:
    """Verify the accelerator backend initializes, from a subprocess.

    Retries only INFRA failures (hang / UNAVAILABLE-class stderr); a
    non-infra child failure (broken env, import error) propagates as a
    real nonzero exit. Exits rc=0 with a structured error JSON if the
    backend stays unreachable after bounded retries.
    """
    if _env_flag("BENCH_SKIP_PROBE"):
        return
    code = ("import jax; d = jax.devices(); "
            "print(d[0].platform, len(d))")
    last = "unknown"
    for attempt in range(_PROBE_ATTEMPTS):
        if attempt:
            time.sleep(_PROBE_BACKOFF_S[min(attempt,
                                            len(_PROBE_BACKOFF_S) - 1)])
        child = subprocess.Popen(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True)
        _LIVE_CHILDREN.append(child.pid)
        try:
            out, err = child.communicate(timeout=_PROBE_TIMEOUT_S)
            r = subprocess.CompletedProcess(
                code, child.returncode, stdout=out, stderr=err)
        except subprocess.TimeoutExpired:
            import signal
            _killpg_quietly(child.pid, signal.SIGKILL)
            child.wait()
            last = f"backend init hung > {_PROBE_TIMEOUT_S}s"
            continue
        finally:
            _LIVE_CHILDREN.remove(child.pid)
        if r.returncode == 0:
            platform = (r.stdout.strip().split() or ["?"])[0]
            if platform == "cpu" and not _env_flag("BENCH_ALLOW_CPU"):
                # silent jax fallback to CPU = the tunnel IS down; a
                # CPU-config number in the metric stream would be bogus
                last = "jax fell back to cpu (accelerator plugin down)"
                continue
            return
        err = (r.stderr or r.stdout).strip()
        if err and not _is_infra_error_text(err):
            sys.stderr.write(err + "\n")           # real breakage: rc!=0
            sys.exit(r.returncode)
        last = err.splitlines()[-1] if err else f"rc={r.returncode}"
    _emit_infra_skip(last)
    sys.exit(0)


def _killpg_quietly(pid: int, sig) -> None:
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError):
        pass


def run_walled(wall_s: float | None = None) -> None:
    """Re-exec the bench in a killable child bounded by a wall timeout,
    so a mid-bench tunnel stall surfaces as an infra-skip JSON (rc=0)
    instead of the driver's own rc=124 kill. The child runs in its own
    process group (so the wall kill reaps its whole tree); SIGTERM/
    SIGINT on the parent are forwarded so a driver kill can't orphan a
    TPU-holding child."""
    import signal
    import threading
    # the parent already ran the probe; re-probing in the child would
    # spend wall budget on work that's done
    env = dict(os.environ, BENCH_CHILD="1", BENCH_SKIP_PROBE="1")
    child = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             env=env, start_new_session=True,
                             stdout=subprocess.PIPE, text=True)
    _LIVE_CHILDREN.append(child.pid)
    # Forward the child's stdout live and remember whether a metric line
    # already went out: a post-result teardown stall must NOT add a
    # second, contradictory infra-skip line (one-JSON-line contract).
    saw_metric = threading.Event()

    def _pump():
        for line in child.stdout:
            sys.stdout.write(line)
            sys.stdout.flush()
            s = line.strip()
            if s.startswith("{") and '"metric"' in s:
                saw_metric.set()

    pump = threading.Thread(target=_pump, daemon=True)
    pump.start()

    def forward(signum, frame):
        _killpg_quietly(child.pid, signal.SIGKILL)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)
    wall = _WALL_TIMEOUT_S if wall_s is None else wall_s
    try:
        rc = child.wait(timeout=wall)
    except subprocess.TimeoutExpired:
        _killpg_quietly(child.pid, signal.SIGKILL)
        child.wait()
        pump.join(timeout=10)
        if not saw_metric.is_set():
            _emit_infra_skip(f"bench hung > {wall:.0f}s wall limit")
        sys.exit(0)
    pump.join(timeout=10)
    sys.exit(rc)


def peak_flops_per_chip() -> float:
    """bf16 peak for the local chip kind."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def check_bf16_psum_parity():
    """TPU-side guard for the safe_psum shim (VERDICT r3 weak #7): CPU
    tests run manual-region bf16 reductions f32-promoted (the XLA CPU
    AllReducePromotion crash workaround), so the production backend must
    demonstrate its NATIVE bf16 manual-region psum. With >= 2 chips this
    is a real numeric parity check against the promoted form (a size-1
    axis would make it vacuous — psum is the identity there); on one
    chip it degrades to a lowering check that the bf16 all-reduce
    program the CPU could not even build compiles for a 2-chip mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()
    x = jnp.asarray(np.random.RandomState(0).randn(64, 64),
                    jnp.bfloat16)
    if len(devs) >= 2:
        mesh = Mesh(np.array(devs[:2]), ("mp",))
        native = shard_map(lambda a: jax.lax.psum(a, "mp"), mesh=mesh,
                           in_specs=P("mp", None), out_specs=P())(x)
        promoted = shard_map(
            lambda a: jax.lax.psum(a.astype(jnp.float32),
                                   "mp").astype(jnp.bfloat16),
            mesh=mesh, in_specs=P("mp", None), out_specs=P())(x)
        assert np.allclose(np.asarray(native, np.float32),
                           np.asarray(promoted, np.float32),
                           rtol=7.9e-3), \
            "bf16 psum diverges from f32-promoted psum on this backend"
    else:
        from jax.sharding import AbstractMesh
        amesh = AbstractMesh((2,), ("mp",))
        fn = shard_map(lambda a: jax.lax.psum(a, "mp"), mesh=amesh,
                       in_specs=P("mp", None), out_specs=P())
        jax.jit(fn).lower(
            jax.ShapeDtypeStruct((64, 64), jnp.bfloat16))  # must build


def bench_flash_32k():
    """S=32k flash attention fwd+bwd on the real chip (VERDICT r3 #6b —
    the README long-context claim, driver-capturable)."""
    import jax
    import jax.numpy as jnp
    b = int(os.environ.get("BENCH_FLASH_BATCH", 1))
    s = int(os.environ.get("BENCH_FLASH_SEQ", 32768))
    h, hkv, d = 16, 8, 128
    iters = int(os.environ.get("BENCH_ITERS", 10))
    from paddle_tpu.kernels.flash_attention import flash_attention
    rng = np.random.default_rng(0)

    def mk(hh):
        return jnp.asarray(rng.standard_normal((b, s, hh, d)),
                           jnp.bfloat16)

    q, k, v = mk(h), mk(hkv), mk(hkv)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    float(g(q, k, v)[0].sum())                      # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = g(q, k, v)
    float(out[0].sum())                             # host sync
    dt = (time.perf_counter() - t0) / iters
    # causal attention FLOPs: fwd 2 matmuls * 2*b*h*s^2*d / 2 (causal),
    # bwd ~2.5x fwd
    fwd = 2 * 2 * b * h * s * s * d / 2
    total = fwd * 3.5
    util = total / dt / peak_flops_per_chip()
    print(json.dumps({
        "metric": "flash_attention_32k_fwd_bwd_ms",
        "value": round(dt * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(util / 0.40, 4),
        "extra": {"seq": s, "batch": b, "heads": h, "kv_heads": hkv,
                  "attn_flops_util": round(util, 4),
                  "backend": jax.default_backend()},
    }))


def bench_decode():
    """Serving decode throughput as a JSON metric (VERDICT r3 #6c — was
    prose-only in BASELINE.md)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        batch, prefill, new = 8, 128, 256
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        batch, prefill, new = 2, 16, 8
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        import jax.numpy as jnp
        for p in model.parameters():
            p._in_place_update(p._value.astype(jnp.bfloat16))
    model.eval()
    ids = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, prefill)).astype(np.int32))
    out = model.generate(ids, max_new_tokens=new, temperature=0.0)
    float(out._value.sum())                         # compile + warmup
    iters = int(os.environ.get("BENCH_ITERS", 3))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = model.generate(ids, max_new_tokens=new, temperature=0.0)
    float(out._value.sum())
    dt = (time.perf_counter() - t0) / iters
    tps = batch * new / dt
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps / 2528.0, 4),   # r3's measured decode rate
        "extra": {"batch": batch, "prefill": prefill, "new_tokens": new,
                  "ms_per_step": round(dt / new * 1e3, 3),
                  "backend": jax.default_backend()},
    }))


def _dump_metrics_snapshot(eng, preset: str,
                           snapshot=None) -> str | None:
    """Write the engine's full metrics-registry snapshot (lifecycle
    counters, TTFT/TPOT/queue-wait histograms, pool gauges) next to the
    event log so a BENCH row links to the telemetry behind its number.
    ``snapshot`` overrides the engine read for callers that already
    hold an aggregated view (the fleet preset dumps per-worker + merged
    registries). Returns the path, or None when the directory is
    unwritable (the one-JSON-line stdout contract must survive a
    read-only checkout)."""
    out_dir = os.environ.get("BENCH_METRICS_DIR", "log")
    path = os.path.join(out_dir, f"bench_metrics_{preset}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(snapshot if snapshot is not None
                      else eng.metrics.snapshot(), f, indent=1)
    except OSError:
        return None
    return path


def _dump_profile(preset: str, payload: dict) -> str | None:
    """ISSUE 13 twin of :func:`_dump_metrics_snapshot`: write the
    step-phase profiler / compile-observatory payload as
    ``bench_profile_<preset>.json`` so a BENCH row links to the phase
    breakdown behind its number. Same unwritable-directory contract."""
    out_dir = os.environ.get("BENCH_METRICS_DIR", "log")
    path = os.path.join(out_dir, f"bench_profile_{preset}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
    except OSError:
        return None
    return path


def bench_engine():
    """Continuous-batching serving throughput: staggered arrivals with
    mixed max_new through the paged DecodeEngine. tokens/s comes from
    the engine's own ``engine_chunk`` events (device-side decode windows
    only — admission prefills and compile excluded), and vs_baseline is
    the DEVICE-STEP ratio against batch-at-a-time over the identical
    FIFO workload (deterministic device-work comparison, not two wall
    clocks; >1 means the engine ran fewer decode steps)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine, _Request
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.utils.log import default_event_log
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        capacity, s_max, chunk = 8, 512, 8
        n_req, p_lo, p_hi = 32, 64, 128
        max_news = (32, 64, 128)
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        capacity, s_max, chunk = 4, 64, 4
        n_req, p_lo, p_hi = 12, 5, 16
        max_news = (4, 8, 16)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        import jax.numpy as jnp
        for p in model.parameters():
            p._in_place_update(p._value.astype(jnp.bfloat16))
    model.eval()
    rng = np.random.default_rng(0)
    eng = DecodeEngine(model, capacity=capacity, s_max=s_max,
                       chunk=chunk)

    def drive(pending, stagger=None, iters=100000):
        queue = list(pending)
        del pending[:]
        live = []
        for _ in range(iters):
            while queue and (stagger is None or len(live) < stagger):
                live.append(queue.pop(0))
            eng.admit(live)
            eng.decode_once()
            if not queue and not live and eng.idle():
                return
        raise RuntimeError("engine bench did not drain")

    # warmup: compile the prefill + chunk programs outside the window
    warm = _Request(rng.integers(
        1, cfg.vocab_size, p_hi).astype(np.int32), chunk)
    drive([warm])
    warm.wait(timeout=600)
    mark = len(default_event_log.events("engine_chunk"))
    steps0 = eng.device_steps

    reqs = [_Request(
        rng.integers(1, cfg.vocab_size,
                     int(rng.integers(p_lo, p_hi + 1))).astype(np.int32),
        int(max_news[i % len(max_news)])) for i in range(n_req)]
    drive(list(reqs), stagger=2)    # 2 FIFO arrivals per chunk tick:
    #                                 admission overlaps live decodes
    for r in reqs:
        r.wait(timeout=600)
    chunks = default_event_log.events("engine_chunk")[mark:]
    dev_tokens = sum(c["steps"] * c["rows"] for c in chunks)
    wall = sum(c["wall_s"] for c in chunks)
    tps = dev_tokens / max(wall, 1e-9)
    # batch-at-a-time baseline on the same FIFO order: each tick of
    # `capacity` requests rides to its slowest member's max_new
    baseline_steps = sum(max(r.max_new for r in reqs[i:i + capacity])
                         for i in range(0, n_req, capacity))
    engine_steps = eng.device_steps - steps0
    snap_path = _dump_metrics_snapshot(eng, "engine")
    print(json.dumps({
        "metric": "engine_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(baseline_steps / max(engine_steps, 1), 4),
        "extra": {"requests": n_req, "capacity": capacity,
                  "chunk": chunk, "s_max": s_max,
                  "engine_device_steps": int(engine_steps),
                  "batch_at_a_time_steps": int(baseline_steps),
                  "decode_chunks": len(chunks),
                  "blocks": eng._alloc.stats() if eng.paged else None,
                  "paged": bool(eng.paged),
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_prefix():
    """Prefix-sharing TTFT: every request repeats ONE system prompt and
    adds a distinct user suffix (the shared-system-prompt serving
    shape). The first request prefills cold through the full window;
    once it retires and publishes its pages, later admissions match the
    prompt in the radix cache and prefill only the suffix through the
    bucketed tail window — cached TTFT must sit strictly below
    uncached. Decode tokens/s comes from the engine's own chunk events;
    vs_baseline is uncached/cached TTFT (>1 = the prefix cache pays)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine, _Request
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.utils.log import default_event_log
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
        sys_len, suf_len, new, n_req = 256, 32, 16, 8
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 64, 4, 16
        sys_len, suf_len, new, n_req = 48, 8, 4, 6
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        import jax.numpy as jnp
        for p in model.parameters():
            p._in_place_update(p._value.astype(jnp.bfloat16))
    model.eval()
    rng = np.random.default_rng(0)
    eng = DecodeEngine(model, capacity=2, s_max=s_max, chunk=chunk,
                       block_size=bs)

    def serve(req):
        """Admit one request serially; TTFT = the admit() wall (the
        prefill runs and syncs inside it). Drain before returning so
        the retire publishes the prefix for the next request."""
        pending = [req]
        t0 = time.perf_counter()
        eng.admit(pending)
        ttft = time.perf_counter() - t0
        for _ in range(100000):
            if eng.idle():
                break
            eng.decode_once()
        req.wait(timeout=600)
        return ttft

    # warmup compiles every program the measured phase can touch: the
    # cold full-window prefill + decode chunk (request 1), then the COW
    # copy + bucketed tail prefill (request 2 shares the warm prompt
    # plus the first 4 suffix tokens — a mid-page split)
    warm_sys = rng.integers(1, cfg.vocab_size, sys_len).astype(np.int32)
    warm_sys[0] = 2
    wsuf = rng.integers(1, cfg.vocab_size, suf_len).astype(np.int32)
    serve(_Request(np.concatenate([warm_sys, wsuf]), new))
    wsuf2 = wsuf.copy()
    wsuf2[4:] = rng.integers(1, cfg.vocab_size, suf_len - 4)
    serve(_Request(np.concatenate([warm_sys, wsuf2]), new))

    # measured workload: a FRESH system prompt (first token distinct
    # from the warm one, so request 1 is genuinely uncached) and
    # suffixes whose first tokens are pairwise distinct (no accidental
    # partial-page match — cached admissions all hit the same bucket)
    sys_p = rng.integers(1, cfg.vocab_size, sys_len).astype(np.int32)
    sys_p[0] = 1
    mark = len(default_event_log.events("engine_chunk"))
    ttfts = []
    for i in range(n_req):
        suf = rng.integers(1, cfg.vocab_size, suf_len).astype(np.int32)
        suf[0] = 3 + i
        ttfts.append(serve(_Request(np.concatenate([sys_p, suf]), new)))
    chunks = default_event_log.events("engine_chunk")[mark:]
    dev_tokens = sum(c["steps"] * c["rows"] for c in chunks)
    decode_tps = dev_tokens / max(sum(c["wall_s"] for c in chunks), 1e-9)
    uncached_ms = ttfts[0] * 1e3
    cached_ms = sum(ttfts[1:]) / len(ttfts[1:]) * 1e3
    stats = eng.stats()
    snap_path = _dump_metrics_snapshot(eng, "prefix")
    print(json.dumps({
        "metric": "prefix_cached_ttft_ms",
        "value": round(cached_ms, 3),
        "unit": "ms",
        "vs_baseline": round(uncached_ms / max(cached_ms, 1e-9), 4),
        "extra": {"uncached_ttft_ms": round(uncached_ms, 3),
                  "decode_tokens_per_sec": round(decode_tps, 1),
                  "requests": n_req, "sys_tokens": sys_len,
                  "suffix_tokens": suf_len, "block_size": bs,
                  "s_max": s_max,
                  "prefix_hit_tokens": stats["prefix_hit_tokens"],
                  "prefix_cache": stats["prefix_cache"],
                  "pool": stats["pool"],
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_fleet():
    """Fleet routing: prefix-affinity vs round-robin TTFT on the
    shared-system-prompt workload (ISSUE 4). One 2-worker ServingFleet
    serves two measured phases over the SAME engines (so compiled
    programs are shared): phase 1 routes round-robin — every worker
    pays its own cold full-window prefill before its traffic starts
    hitting — phase 2 routes by GlobalPrefixDirectory affinity, so only
    ONE worker goes cold and every later request lands on its warm
    pages. Each phase uses a fresh system prompt (no cross-phase cache
    help). The metric is affinity-phase cached TTFT (mean over requests
    after the phase's first); vs_baseline is round-robin cached TTFT
    over it (>1 = affinity routing pays). The aggregated per-worker +
    merged registry snapshot is dumped next to the event log."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import ServingFleet
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
        sys_len, suf_len, new, n_req = 256, 32, 16, 8
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        # the cold/cached contrast needs a LONG shared prefix relative
        # to the tail: a 256-token full-window prefill is measurably
        # slower than the ~16-token bucketed tail even at debug size,
        # so round-robin's one-cold-prefill-per-worker tax shows up
        s_max, chunk, bs = 256, 4, 16
        sys_len, suf_len, new, n_req = 208, 8, 4, 8
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        import jax.numpy as jnp
        for p in model.parameters():
            p._in_place_update(p._value.astype(jnp.bfloat16))
    model.eval()
    rng = np.random.default_rng(0)
    fleet = ServingFleet(model, n_workers=2, policy="round_robin",
                         engine_kwargs=dict(capacity=2, s_max=s_max,
                                            chunk=chunk, block_size=bs))

    def serve(prompt):
        """One request end-to-end, serially: TTFT from its lifecycle
        trace (arrival -> first token, i.e. the admission prefill)."""
        req = fleet.submit(prompt, max_new_tokens=new)
        fleet.run_until_drained()
        req.wait(timeout=600)
        return req.trace.ttft

    def hit_tokens():
        return sum(w.engine.stats()["prefix_hit_tokens"]
                   for w in fleet.workers)

    # warmup compiles every program both phases touch, on BOTH workers
    # (round-robin alternation lines warm pairs up per worker): cold
    # full-window prefill + decode chunk, then the COW copy + bucketed
    # tail prefill against each worker's warm prompt
    warm_sys = rng.integers(1, cfg.vocab_size, sys_len).astype(np.int32)
    warm_sys[0] = 2
    wsufs = []
    for _ in range(2):
        wsuf = rng.integers(1, cfg.vocab_size,
                            suf_len).astype(np.int32)
        wsufs.append(wsuf)
        serve(np.concatenate([warm_sys, wsuf]))
    for wsuf in wsufs:
        wsuf2 = wsuf.copy()
        wsuf2[4:] = rng.integers(1, cfg.vocab_size, suf_len - 4)
        serve(np.concatenate([warm_sys, wsuf2]))

    def phase(first_tok):
        """n_req requests sharing one fresh system prompt whose FIRST
        token is distinct from the warm prompt's and the other
        phase's (a 1-token partial match against a stale first page
        would drag the cold request through an unwarmed COW + tail
        window); suffix first tokens pairwise distinct too (no
        accidental partial-page match between siblings)."""
        sys_p = rng.integers(1, cfg.vocab_size,
                             sys_len).astype(np.int32)
        sys_p[0] = first_tok
        h0, ttfts = hit_tokens(), []
        for i in range(n_req):
            suf = rng.integers(1, cfg.vocab_size,
                               suf_len).astype(np.int32)
            suf[0] = 3 + i
            ttfts.append(serve(np.concatenate([sys_p, suf])))
        return ttfts, hit_tokens() - h0

    rr_ttfts, rr_hits = phase(first_tok=1)
    fleet.policy = "affinity"
    af_ttfts, af_hits = phase(first_tok=3)

    # "cached" = everything after the phase's FIRST request; round
    # robin's second cold prefill (the other worker) stays IN its mean
    # — paying cold once per worker is exactly the cost affinity
    # routing removes
    rr_cached_ms = sum(rr_ttfts[1:]) / len(rr_ttfts[1:]) * 1e3
    af_cached_ms = sum(af_ttfts[1:]) / len(af_ttfts[1:]) * 1e3
    st = fleet.stats()
    agg = fleet.aggregator()
    snap_path = _dump_metrics_snapshot(None, "fleet",
                                       snapshot=agg.snapshot())
    fleet.close()
    print(json.dumps({
        "metric": "fleet_affinity_ttft_ms",
        "value": round(af_cached_ms, 3),
        "unit": "ms",
        "vs_baseline": round(rr_cached_ms / max(af_cached_ms, 1e-9), 4),
        "extra": {"round_robin_ttft_ms": round(rr_cached_ms, 3),
                  "affinity_uncached_ttft_ms": round(af_ttfts[0] * 1e3,
                                                     3),
                  "rr_prefix_hit_tokens": rr_hits,
                  "affinity_prefix_hit_tokens": af_hits,
                  "affinity_hits": st["affinity_hits"],
                  "workers": {w: s["admitted"]
                              for w, s in st["workers"].items()},
                  "requests_per_phase": n_req, "sys_tokens": sys_len,
                  "suffix_tokens": suf_len, "block_size": bs,
                  "s_max": s_max,
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_slo():
    """Telemetry tax on the serving hot path (ISSUE 5): the same warm
    2-worker fleet workload runs with the SLO engine + TelemetryShipper
    OFF and ON, interleaved; the metric is the ON step-wall overhead in
    percent (min-of-runs per config, so scheduler noise cancels) and
    vs_baseline is t_off/t_on (>= 0.95 means the observability layer
    costs under the 5% budget the slow smoke asserts). The ON config is
    the production cadence: shipper ``tick()`` every fleet step
    (flushing a merged snapshot + retired trace summaries to a JSONL
    sink on interval), SLO ``check`` at scrape cadence (every 8
    steps)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import ServingFleet
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import JsonlFileSink, SLORule
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
        p_len, new, n_req = 96, 16, 8
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 128, 4, 16
        p_len, new, n_req = 24, 48, 16
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    fleet = ServingFleet(model, n_workers=2, policy="round_robin",
                         engine_kwargs=dict(capacity=2, s_max=s_max,
                                            chunk=chunk, block_size=bs))
    prompts = [rng.integers(1, cfg.vocab_size, p_len).astype(np.int32)
               for _ in range(n_req)]

    def run_once(slo_on):
        """One full workload; returns summed step() wall seconds."""
        for p in prompts:
            fleet.submit(p, max_new_tokens=new)
        wall, steps = 0.0, 0
        while fleet.pending_work():
            t0 = time.perf_counter()
            fleet.step()
            if slo_on and steps % 8 == 0:
                fleet.check_slo()
            wall += time.perf_counter() - t0
            steps += 1
        return wall

    # warm both workers' compiled programs (prefill buckets + chunk)
    run_once(slo_on=False)
    run_once(slo_on=False)

    out_dir = os.environ.get("BENCH_METRICS_DIR", "log")
    try:
        os.makedirs(out_dir, exist_ok=True)
        sink_path = os.path.join(out_dir, "bench_slo_telemetry.jsonl")
    except OSError:
        sink_path = os.devnull
    slo_engine = None
    shipper = None
    t_off, t_on = float("inf"), float("inf")
    repeats = 5
    for _ in range(repeats):            # interleaved: off, on, off, on…
        fleet.slo, fleet.shipper = None, None
        t_off = min(t_off, run_once(slo_on=False))
        if slo_engine is None:
            slo_engine = fleet.enable_slo(rules=[
                SLORule("ttft_p99", "engine_ttft_seconds", "p99",
                        threshold=30.0, window_s=30.0, for_s=5.0),
                SLORule("error_rate", "engine_failed_total", "ratio",
                        threshold=0.01, window_s=30.0,
                        total=("engine_retired_total",
                               "engine_failed_total")),
            ])
            # 0.25s keeps >= 1 flush per ON run (the first tick after
            # an OFF run always flushes) without the pathological
            # every-step cadence that would dominate a sub-second run
            shipper = fleet.enable_shipper(
                [JsonlFileSink(sink_path)], interval_s=0.25)
        else:
            fleet.slo, fleet.shipper = slo_engine, shipper
        t_on = min(t_on, run_once(slo_on=True))
    overhead_pct = (t_on - t_off) / t_off * 100.0
    agg_snap = fleet.aggregator().snapshot()
    snap_path = _dump_metrics_snapshot(None, "slo", snapshot=agg_snap)
    ship_stats = shipper.stats()
    fleet.close()
    print(json.dumps({
        "metric": "slo_shipper_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "vs_baseline": round(t_off / max(t_on, 1e-9), 4),
        "extra": {"step_wall_off_s": round(t_off, 4),
                  "step_wall_on_s": round(t_on, 4),
                  "requests_per_run": n_req, "new_tokens": new,
                  "repeats": repeats,
                  "shipper": ship_stats,
                  "slo_states": slo_engine.states(),
                  "telemetry_jsonl": sink_path,
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_overload():
    """Multi-tenant overload harness (ISSUE 6): a bursty, heavy-tailed,
    tenant-skewed synthetic flood (seeded :class:`TrafficGenerator`)
    drives a 2-worker fleet far past capacity for a fixed virtual-time
    window — once WITHOUT QoS (FCFS baseline) and twice WITH the QoS
    stack armed (token bucket on the flooding tenant, weighted fair
    sharing, SLO-driven shedding above a backlog target). Every policy
    decision runs on a VIRTUAL clock, so per-tenant admitted/throttled/
    shed/served accounting must replay bit-identically — the repeated
    QoS run checks exactly that and ``extra.qos.deterministic`` records
    the outcome. The metric is fleet p99 TTFT (ms) under overload with
    QoS on; vs_baseline is Jain's fairness index over per-tenant served
    tokens, QoS-on / QoS-off (> 1 means fair sharing equalized service
    the FCFS baseline skewed toward the flooding tenant)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import ServingFleet
    from paddle_tpu.inference.qos import QoSPolicy, TenantPolicy
    from paddle_tpu.inference.traffic import (TenantProfile,
                                              TrafficGenerator,
                                              jain_index)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import SLORule
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 64, 4, 16
    model = LlamaForCausalLM(cfg)
    model.eval()

    gen = TrafficGenerator(
        [TenantProfile("t_heavy", share=8.0),
         TenantProfile("t_light", share=2.0)],
        rate=4.0, seed=0, process="bursty", prompt_dist="heavy_tail",
        prompt_min=4, prompt_max=24, max_new=8)
    arrivals = gen.arrivals(12.0)
    dt, n_steps = 0.25, 72      # virtual window: 18 s, past the flood

    def tally(reqs):
        """Per-tenant outcome counts from the traces (works with or
        without QoS — the shed path stamps ``shed_reason``)."""
        out = {}
        for r in reqs:
            d = out.setdefault(str(r.tenant), dict(
                submitted=0, retired=0, shed=0, rejected=0, pending=0,
                served_tokens=0))
            d["submitted"] += 1
            term = r.trace.terminal
            if term == "retired":
                d["retired"] += 1
                d["served_tokens"] += r.max_new
            elif term == "failed":
                key = ("shed" if "shed_reason" in r.trace.attrs
                       else "rejected")
                d[key] += 1
            else:
                d["pending"] += 1
        return out

    def run_once(use_qos):
        vt = [0.0]
        qos = None
        if use_qos:
            qos = QoSPolicy([
                # the flooding tenant: rate-limited, shed first
                TenantPolicy("t_heavy", rate=100.0, burst=280.0,
                             weight=1.0, tier=0, shed_floor=1),
                # the interactive tenant: unthrottled, shed-protected
                TenantPolicy("t_light", weight=1.0, tier=1,
                             shed_floor=1),
            ], clock=lambda: vt[0])
        fleet = ServingFleet(model, n_workers=2, policy="round_robin",
                             engine_kwargs=dict(capacity=2, s_max=s_max,
                                                chunk=chunk,
                                                block_size=bs),
                             qos=qos)
        if use_qos:
            fleet.enable_slo(rules=[
                SLORule("backlog", "engine_backlog", "value",
                        threshold=12.0, window_s=60.0, for_s=0.5,
                        clear_for_s=1.0)],
                shed=True, shed_target_backlog=8)
        reqs, idx = [], 0
        for _ in range(n_steps):
            while idx < len(arrivals) and arrivals[idx].t <= vt[0]:
                sr = arrivals[idx]
                ids = gen.prompt_ids(sr, cfg.vocab_size, index=idx)
                reqs.append(fleet.submit(ids, max_new_tokens=sr.max_new,
                                         tenant=sr.tenant))
                idx += 1
            fleet.step()
            if use_qos:
                fleet.check_slo(now=vt[0])
            vt[0] += dt
        per_tenant = tally(reqs)
        # the deterministic signature: everything the virtual clock
        # controls (admission, throttling, shedding, service), nothing
        # the wall clock touches (TTFT histograms)
        sig = {"tally": per_tenant,
               "qos": fleet.qos.stats() if use_qos else None,
               "shed": int(fleet._c_shed.value) if use_qos else 0,
               "arrivals_submitted": idx}
        snap = fleet.aggregator().snapshot()
        fleet.close()
        return sig, snap

    sig_off, _ = run_once(use_qos=False)
    sig_on, snap_on = run_once(use_qos=True)
    sig_on2, _ = run_once(use_qos=True)

    def jain_of(sig):
        return jain_index(sig["tally"][t]["served_tokens"]
                          for t in sorted(sig["tally"]))

    jain_off = jain_of(sig_off)
    jain_on = jain_of(sig_on)
    ttft = snap_on["fleet"]["histograms"].get("engine_ttft_seconds", {})
    p99_ms = (ttft.get("p99") or 0.0) * 1e3
    shed_on = sig_on["shed"]
    submitted = sig_on["arrivals_submitted"]
    snap_path = _dump_metrics_snapshot(None, "overload",
                                       snapshot=snap_on)
    print(json.dumps({
        "metric": "overload_p99_ttft_ms",
        "value": round(p99_ms, 2),
        "unit": "ms",
        "vs_baseline": round(jain_on / max(jain_off, 1e-9), 4),
        "extra": {"arrivals": len(arrivals),
                  "submitted": submitted,
                  "virtual_window_s": round(n_steps * dt, 2),
                  "jain_fairness_on": round(jain_on, 4),
                  "jain_fairness_off": round(jain_off, 4),
                  "shed_rate": round(shed_on / max(submitted, 1), 4),
                  "qos": {"deterministic": sig_on == sig_on2,
                          "shed_total": shed_on,
                          "per_tenant": sig_on["qos"]},
                  "tally_on": sig_on["tally"],
                  "tally_off": sig_off["tally"],
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_mixed():
    """Chunked-prefill mixed flood (ISSUE 7): a seeded long/short-prompt
    flood (bounded-Pareto prompt lengths from :class:`TrafficGenerator`,
    tick-injected as virtual arrivals) drives ONE engine config twice —
    admission (monolithic) prefill vs chunked prefill under a per-step
    token budget. Both runs see identical prompts and greedy decode, so
    the outputs-identical oracle rides in ``extra``. The metric is
    chunked p99 TTFT (ms); vs_baseline is admission_p99 / chunked_p99
    (> 1 means chunking flattened the tail — short prompts stop paying
    for full-window prefills and long prompts stop stalling the step)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine
    from paddle_tpu.inference.traffic import (TenantProfile,
                                              TrafficGenerator)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs, p_max = 512, 8, 16, 384
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs, p_max = 128, 4, 16, 96
    model = LlamaForCausalLM(cfg)
    model.eval()

    gen = TrafficGenerator(
        [TenantProfile("t0")], rate=6.0, seed=0, process="bursty",
        prompt_dist="heavy_tail", prompt_min=4, prompt_max=p_max,
        max_new=8)
    arrivals = gen.arrivals(8.0)
    dt, max_steps = 0.25, 4000

    def run_once(chunked):
        eng = DecodeEngine(
            model, capacity=4, s_max=s_max, chunk=chunk, block_size=bs,
            chunked_prefill=chunked,
            # ISSUE 13: both modes profiled, so the phase breakdown is
            # a fair comparison and the dumped profile explains where
            # each mode's TTFT went (outputs stay bit-identical —
            # regression-tested)
            profile=True,
            # one page-chunk per idle lane: several chunks per step so
            # the budget shapes, not starves, the flood
            step_budget=(4 * chunk + 4 * bs) if chunked else None)
        # warmup outside the measurement: compile the decode program
        # and the prefill shape this mode rides (full window vs the
        # 16-slot chunk bucket) so TTFT measures steady-state service
        w = eng.submit(np.arange(1, p_max + 1, dtype=np.int32),
                       max_new_tokens=4)
        while not (eng.idle() and not eng.backlog):
            eng.admit([])
            eng.decode_once()
        w.wait(timeout=120)
        reqs, idx = [], 0
        for step in range(max_steps):
            while idx < len(arrivals) and arrivals[idx].t <= step * dt:
                sr = arrivals[idx]
                ids = gen.prompt_ids(sr, cfg.vocab_size, index=idx)
                reqs.append(eng.submit(ids,
                                       max_new_tokens=sr.max_new))
                idx += 1
            eng.admit([])
            eng.decode_once()
            if idx >= len(arrivals) and eng.idle() and not eng.backlog:
                break
        outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        ttfts = np.array([r.trace.ttft for r in reqs], dtype=np.float64)
        tpots = [t for t in (r.trace.tpot(r.max_new) for r in reqs)
                 if t is not None]
        return eng, outs, ttfts, tpots

    eng_mono, outs_mono, ttft_mono, tpot_mono = run_once(False)
    eng_ch, outs_ch, ttft_ch, tpot_ch = run_once(True)
    identical = (len(outs_mono) == len(outs_ch)
                 and all(np.array_equal(a, b)
                         for a, b in zip(outs_mono, outs_ch)))
    p99_mono = float(np.percentile(ttft_mono, 99)) * 1e3
    p99_ch = float(np.percentile(ttft_ch, 99)) * 1e3
    snap_path = _dump_metrics_snapshot(eng_ch, "mixed")
    prof_path = _dump_profile("mixed", {
        "admission": eng_mono.profile.summary(),
        "chunked": eng_ch.profile.summary(),
        "compiles": {"admission": eng_mono.compiles.stats(),
                     "chunked": eng_ch.compiles.stats()},
        "compile_log": eng_ch.compiles.compile_log()})
    print(json.dumps({
        "metric": "mixed_p99_ttft_ms",
        "value": round(p99_ch, 2),
        "unit": "ms",
        "vs_baseline": round(p99_mono / max(p99_ch, 1e-9), 4),
        "extra": {"arrivals": len(arrivals),
                  "outputs_identical": identical,
                  "admission_p99_ttft_ms": round(p99_mono, 2),
                  "chunked_p99_ttft_ms": round(p99_ch, 2),
                  "admission_mean_tpot_ms": round(
                      float(np.mean(tpot_mono)) * 1e3, 3),
                  "chunked_mean_tpot_ms": round(
                      float(np.mean(tpot_ch)) * 1e3, 3),
                  "prefill_chunks": int(
                      eng_ch.stats()["prefill_chunks"]),
                  "chunk_prog_windows": sorted(eng_ch._prefix_progs),
                  "metrics_snapshot": snap_path,
                  "profile_snapshot": prof_path,
                  "backend": jax.default_backend()},
    }))


def bench_spec():
    """Self-speculative decoding (ISSUE 8): a seeded repetitive-vs-
    random prompt mix drives the SAME paged engine config twice — spec
    OFF (plain greedy) vs spec ON (n-gram draft, one-step batched
    verify, longest-matching-prefix accept). Identical arrivals, and
    the outputs-identical oracle rides in ``extra`` (every accepted
    token IS the verify program's argmax, so spec is pure accounting,
    never a quality trade). value = tokens emitted per verify step on
    the draft-friendly REPETITIVE mix (the number the accept-rate
    machinery earns; 1.0 means speculation never paid); vs_baseline =
    tokens/verify-step on the FULL mix, i.e. per-row model invocations
    saved against one-token-at-a-time decode (>1 = speculation pays —
    raw device-step counts for both runs ride in extra, but they are
    not directly comparable: the plain engine batches every row into
    one chunked program per step while verify launches per row). extra
    carries accept rates, per-mix tokens/step, ms/token both ways, and
    the spec engine's metrics snapshot (proposed/accepted counters +
    accept-length histogram)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine, _Request
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 128, 4, 16
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    # draft-friendly half: tiled motifs (the prompt-lookup drafter's
    # home turf, and greedy tails loop on a tiny model); hostile half:
    # uniform-random prompts where almost every draft gets rejected
    rep = [np.tile(rng.randint(1, cfg.vocab_size,
                               (rng.randint(4, 9),)).astype(np.int32),
                   rng.randint(3, 6)) for _ in range(8)]
    rand = [rng.randint(1, cfg.vocab_size,
                        (rng.randint(12, 41),)).astype(np.int32)
            for _ in range(8)]
    max_new = 24

    def run_once(spec, prompts):
        eng = DecodeEngine(model, capacity=4, s_max=s_max, chunk=chunk,
                           block_size=bs, spec_decode=spec)
        # warmup outside the measurement: compile this mode's programs
        w = _Request(np.tile(prompts[0][:4], 3), max_new)
        pending = [w]
        while pending or not eng.idle():
            eng.admit(pending)
            eng.decode_once()
        w.wait(timeout=120)
        reqs = [_Request(p, max_new) for p in prompts]
        pending = list(reqs)
        steps0 = eng.device_steps
        t0 = time.perf_counter()
        for _ in range(20000):
            eng.admit(pending)
            eng.decode_once()
            if eng.idle() and not pending:
                break
        wall = time.perf_counter() - t0
        outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        return eng, outs, eng.device_steps - steps0, wall

    mix = rep + rand
    eng_off, out_off, steps_off, wall_off = run_once(False, mix)
    eng_on, out_on, steps_on, wall_on = run_once(True, mix)
    identical = all(np.array_equal(a, b)
                    for a, b in zip(out_off, out_on))
    eng_rep, _, _, _ = run_once(True, rep)
    sp_mix, sp_rep = eng_on.stats()["spec"], eng_rep.stats()["spec"]
    n_tok = len(mix) * max_new
    snap_path = _dump_metrics_snapshot(eng_on, "spec")
    print(json.dumps({
        "metric": "spec_tokens_per_step",
        "value": round(sp_rep["tokens_per_step"], 4),
        "unit": "tokens/step",
        "vs_baseline": round(sp_mix["tokens_per_step"], 4),
        "extra": {"outputs_identical": identical,
                  "accept_rate_repetitive": round(
                      sp_rep["accept_rate"], 4),
                  "accept_rate_mix": round(sp_mix["accept_rate"], 4),
                  "tokens_per_step_mix": round(
                      sp_mix["tokens_per_step"], 4),
                  "plain_device_steps": steps_off,
                  "spec_device_steps": steps_on,
                  "plain_ms_per_token": round(
                      wall_off / n_tok * 1e3, 3),
                  "spec_ms_per_token": round(wall_on / n_tok * 1e3, 3),
                  "proposed": sp_mix["proposed"],
                  "accepted": sp_mix["accepted"],
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_tp():
    """Tensor-parallel sharded engine (ISSUE 10): seeded identical
    arrivals drive the SAME paged config (chunked prefill + spec decode
    ON — the launch-heavy mode the single-launch mixed step was built
    to collapse) unsharded vs sharded over a tp=2 and tp=4 kv-head
    mesh, plus a tp=2 REPEAT on the same seed. Oracles ride in
    ``extra``: outputs bit-identical across every run (sharding is
    wiring, never a quality trade) and the repeat bit-for-bit
    (determinism). value = device launches per engine step on the tp=2
    sharded engine (batched verify + mixed step fold O(rows) calls into
    O(1)); vs_baseline = unsharded calls-per-step / sharded
    calls-per-step (>1 = the collapse pays). extra carries raw call and
    step counts, walls, and the per-degree parity flags."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine, _Request
    from paddle_tpu.inference.sharding import make_tp_mesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
    else:
        # head counts divisible by BOTH degrees (8 heads / 4 kv heads),
        # ff 344 = 4 x 86
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=4)
        s_max, chunk, bs = 128, 4, 16
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    rep = [np.tile(rng.randint(1, cfg.vocab_size,
                               (rng.randint(4, 9),)).astype(np.int32),
                   rng.randint(3, 6)) for _ in range(4)]
    rand = [rng.randint(1, cfg.vocab_size,
                        (rng.randint(12, 41),)).astype(np.int32)
            for _ in range(4)]
    prompts = rep + rand
    max_new = 16

    def run_once(tp):
        eng = DecodeEngine(
            model, capacity=4, s_max=s_max, chunk=chunk, block_size=bs,
            chunked_prefill=True, spec_decode=True,
            mesh=make_tp_mesh(tp) if tp else None)
        # warmup outside the measurement: compile this mode's programs
        w = _Request(np.tile(prompts[0][:4], 3), max_new)
        pending = [w]
        while pending or not eng.idle():
            eng.admit(pending)
            eng.decode_once()
        w.wait(timeout=120)
        calls0 = eng.stats()["device_calls"]
        reqs = [_Request(p, max_new) for p in prompts]
        pending = list(reqs)
        loops = 0       # engine steps = decode_once invocations: the
        #                 denominator the O(rows)->O(1) claim is about
        t0 = time.perf_counter()
        for _ in range(20000):
            eng.admit(pending)
            eng.decode_once()
            loops += 1
            if eng.idle() and not pending:
                break
        wall = time.perf_counter() - t0
        outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        return (outs, eng.stats()["device_calls"] - calls0,
                loops, wall, eng)

    out0, calls0, steps0, wall0, _ = run_once(None)
    out2, calls2, steps2, wall2, eng2 = run_once(2)
    out2b, calls2b, _, _, _ = run_once(2)          # determinism repeat
    n_dev = len(jax.devices())
    out4 = calls4 = None
    if n_dev >= 4:
        out4, calls4, _, _, _ = run_once(4)
    parity2 = all(np.array_equal(a, b) for a, b in zip(out0, out2))
    repeat2 = all(np.array_equal(a, b) for a, b in zip(out2, out2b)) \
        and calls2 == calls2b
    parity4 = (all(np.array_equal(a, b) for a, b in zip(out0, out4))
               if out4 is not None else None)
    cps0 = calls0 / max(steps0, 1)
    cps2 = calls2 / max(steps2, 1)
    snap_path = _dump_metrics_snapshot(eng2, "tp")
    print(json.dumps({
        "metric": "tp_device_calls_per_step",
        "value": round(cps2, 4),
        "unit": "launches/step",
        "vs_baseline": round(cps0 / max(cps2, 1e-9), 4),
        "extra": {"outputs_identical_tp2": parity2,
                  "outputs_identical_tp4": parity4,
                  "repeat_bit_identical": repeat2,
                  "unsharded_device_calls": calls0,
                  "tp2_device_calls": calls2,
                  "tp4_device_calls": calls4,
                  "unsharded_steps": steps0,
                  "tp2_steps": steps2,
                  "unsharded_calls_per_step": round(cps0, 4),
                  "unsharded_wall_s": round(wall0, 3),
                  "tp2_wall_s": round(wall2, 3),
                  "devices": n_dev,
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_cp():
    """Sequence-parallel 2-D mesh under a long-prompt flood (ISSUE
    16): seeded identical arrivals — every prompt long enough to need
    many prefill chunks — drive the SAME chunked-prefill config three
    ways on one 8-device box: unsharded (parity oracle), 1-D tp at the
    kv-head cap (tp=4 on a 4-kv-head model: HALF the box, the most a
    kv-head-only mesh can legally use), and the 2-D (seq=2, tp=4) mesh
    over ALL 8 devices, plus a 2-D REPEAT on the same seed. The 2-D
    engine's default prefill chunk widens to block_size x seq — each
    chunk's window spreads across the seq shards (context parallelism)
    — so a long prompt needs seq-fold fewer prefill launches and stops
    monopolizing the step budget. value = 2-D p99 TTFT in ENGINE STEPS
    (decode_once calls from submit to first token): on hardware every
    step is one bounded device launch round, so steps is the unit the
    step-budget claim transfers in, whereas wall-clock on a forced-CPU
    box times XLA's serial 8-device emulation, not the engine
    (wall numbers still ride in extra). vs_baseline = 1-D p99 steps /
    2-D p99 steps (> 1 = the second axis pays). Oracles ride in
    ``extra``: every mode's outputs bit-match the unsharded oracle, and
    the repeat is bit-for-bit with an equal device-call count
    (determinism)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import DecodeEngine
    from paddle_tpu.inference.sharding import make_mesh, make_tp_mesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=4,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs, p_min, p_max = 512, 8, 16, 256, 384
    else:
        # 4 kv heads: tp caps at 4, so the 2-D (2 x 4) mesh is the only
        # way to harness all 8 virtual devices
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=8, num_key_value_heads=4)
        s_max, chunk, bs, p_min, p_max = 160, 4, 16, 64, 120
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    # long-prompt flood: every arrival needs >= p_min/bs prefill
    # chunks, several times the engine capacity, all queued at t=0
    prompts = [rng.randint(1, cfg.vocab_size,
                           (rng.randint(p_min, p_max + 1),))
               .astype(np.int32) for _ in range(10)]
    max_new = 8

    def run_once(mesh):
        eng = DecodeEngine(
            model, capacity=4, s_max=s_max, chunk=chunk, block_size=bs,
            chunked_prefill=True, mesh=mesh)
        # warmup outside the measurement: compile this mode's chunk
        # bucket + decode programs so TTFT measures service, not XLA
        w = eng.submit(np.arange(1, p_max + 1, dtype=np.int32),
                       max_new_tokens=4)
        while not (eng.idle() and not eng.backlog):
            eng.admit([])
            eng.decode_once()
        w.wait(timeout=120)
        calls0 = eng.stats()["device_calls"]
        reqs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        first_step = [None] * len(reqs)
        for step in range(20000):
            eng.admit([])
            eng.decode_once()
            for i, r in enumerate(reqs):
                if first_step[i] is None and r.trace.ttft is not None:
                    first_step[i] = step + 1
            if eng.idle() and not eng.backlog:
                break
        outs = [np.asarray(r.wait(timeout=120)) for r in reqs]
        steps = np.array(first_step, dtype=np.float64)
        walls = np.array([r.trace.ttft for r in reqs],
                         dtype=np.float64)
        return outs, steps, walls, \
            eng.stats()["device_calls"] - calls0, eng

    out0, _, _, _, _ = run_once(None)               # unsharded oracle
    out1, st1, wall1, calls1, _ = run_once(make_tp_mesh(4))
    mesh2d = make_mesh(4, 2)                        # (seq=2, tp=4)
    out2, st2, wall2, calls2, eng2 = run_once(mesh2d)
    out2b, _, _, calls2b, _ = run_once(make_mesh(4, 2))  # same-seed rep
    parity1 = all(np.array_equal(a, b) for a, b in zip(out0, out1))
    parity2 = all(np.array_equal(a, b) for a, b in zip(out0, out2))
    repeat2 = all(np.array_equal(a, b) for a, b in zip(out2, out2b)) \
        and calls2 == calls2b
    p99_1 = float(np.percentile(st1, 99))
    p99_2 = float(np.percentile(st2, 99))
    snap_path = _dump_metrics_snapshot(eng2, "cp")
    print(json.dumps({
        "metric": "cp_p99_ttft_steps",
        "value": round(p99_2, 2),
        "unit": "engine steps",
        "vs_baseline": round(p99_1 / max(p99_2, 1e-9), 4),
        "extra": {"outputs_identical_tp4": parity1,
                  "outputs_identical_2d": parity2,
                  "repeat_bit_identical": repeat2,
                  "tp4_p99_ttft_steps": round(p99_1, 2),
                  "seq2_tp4_p99_ttft_steps": round(p99_2, 2),
                  "tp4_mean_ttft_steps": round(float(np.mean(st1)), 3),
                  "seq2_tp4_mean_ttft_steps": round(
                      float(np.mean(st2)), 3),
                  "tp4_p99_ttft_wall_ms": round(
                      float(np.percentile(wall1, 99)) * 1e3, 2),
                  "seq2_tp4_p99_ttft_wall_ms": round(
                      float(np.percentile(wall2, 99)) * 1e3, 2),
                  "tp4_device_calls": calls1,
                  "seq2_tp4_device_calls": calls2,
                  "prefill_chunk_tp4": bs,
                  "prefill_chunk_2d": 2 * bs,
                  "mesh_shape": dict(eng2.stats()["mesh_shape"]),
                  "prompts": len(prompts),
                  "devices": len(jax.devices()),
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_chaos():
    """Self-healing under adversarial faults (ISSUE 9): overload-style
    seeded traffic drives a 3-worker fleet with auto-restart armed
    (capped exponential backoff on the virtual clock) — once FAULT-FREE
    and twice under the SAME seeded :class:`FaultPlan` (crashes, hangs
    long enough to trip the stall watchdog, slow steps, allocator OOMs,
    sink failures). Every fault, restart and re-route is step-indexed,
    so the repeated chaos run must replay bit-for-bit —
    ``extra.deterministic`` records the check. value = goodput
    (retired / submitted) under chaos; vs_baseline = chaos goodput /
    fault-free goodput (1.0 means every fault was healed). extra
    carries recovery time (steps from a capacity dip until the fleet is
    back to N healthy workers), the fired fault mix, restart/failover
    counters, and the completed-output bit-parity oracle — failover is
    recompute-resume, so every output completed under chaos must
    bit-match the fault-free run."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.chaos import FaultInjector, FaultPlan
    from paddle_tpu.inference.fleet import (NoHealthyWorkersError,
                                            RestartPolicy, ServingFleet)
    from paddle_tpu.inference.traffic import (TenantProfile,
                                              TrafficGenerator)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 64, 4, 16
    model = LlamaForCausalLM(cfg)
    model.eval()

    gen = TrafficGenerator(
        [TenantProfile("t_a", share=6.0),
         TenantProfile("t_b", share=4.0)],
        rate=2.5, seed=0, process="bursty", prompt_dist="heavy_tail",
        prompt_min=4, prompt_max=24, max_new=8)
    arrivals = gen.arrivals(10.0)
    dt, n_steps, n_workers = 0.25, 72, 3

    def run_once(fault_seed, profile=False, pdir=None):
        vt = [0.0]
        fleet = ServingFleet(
            model, n_workers=n_workers, policy="round_robin",
            engine_kwargs=dict(capacity=2, s_max=s_max, chunk=chunk,
                               block_size=bs),
            stall_s=1.0, profile=profile, postmortem_dir=pdir,
            restart=RestartPolicy(auto=True, backoff_base_s=0.5,
                                  backoff_max_s=4.0, probation_steps=2,
                                  clock=lambda: vt[0]))
        inj = None
        if fault_seed is not None:
            plan = FaultPlan.random(
                fault_seed, n_steps=n_steps,
                workers=[w.wid for w in fleet.workers],
                rate=0.10, duration=6, magnitude=0.4)
            inj = FaultInjector(plan).install(fleet)
        reqs, idx = [], 0
        healthy_hist = []

        def one_step():
            fleet.step()
            fleet.check_watchdogs(now=vt[0])
            healthy_hist.append(
                sum(1 for w in fleet.workers if w.healthy))
            vt[0] += dt

        for _ in range(n_steps):
            while idx < len(arrivals) and arrivals[idx].t <= vt[0]:
                sr = arrivals[idx]
                ids = gen.prompt_ids(sr, cfg.vocab_size, index=idx)
                try:
                    reqs.append(fleet.submit(
                        ids, max_new_tokens=sr.max_new,
                        tenant=sr.tenant))
                except NoHealthyWorkersError:
                    break       # total outage: retry the arrival next
                #                 step (deterministic — the outage
                #                 window is part of the schedule)
                idx += 1
            one_step()
        # drain: keep the virtual clock moving so scheduled restarts
        # fire and parked requests re-route
        extra = 0
        while fleet.pending_work() and extra < 800:
            one_step()
            extra += 1
        outs = {i: np.asarray(r.result) for i, r in enumerate(reqs)
                if r.trace.terminal == "retired"}
        st = fleet.stats()
        sig = {"submitted": idx,
               "retired": sorted(outs),
               "outputs": [(i, outs[i].tolist()) for i in sorted(outs)],
               "failovers": st["failovers"],
               "restarts": st["restarts"],
               "rerouted": st["rerouted"],
               "poisoned": st["poisoned"],
               "fired": inj.fired if inj is not None else []}
        # recovery episodes: maximal runs of below-N capacity, each
        # measured in steps until the fleet is whole again
        episodes, cur = [], 0
        for h in healthy_hist:
            if h < n_workers:
                cur += 1
            elif cur:
                episodes.append(cur)
                cur = 0
        if cur:
            episodes.append(cur)
        snap = fleet.aggregator().snapshot()
        final_healthy = sum(1 for w in fleet.workers if w.healthy)
        prof = None
        if profile:
            # ISSUE 13: same payloads the live /statusz + /compilez
            # endpoints serve, captured before close()
            surf = fleet.debug_surface()
            prof = {"statusz": surf["statusz"](),
                    "compilez": surf["compilez"]()}
        fleet.close()
        return sig, outs, episodes, final_healthy, snap, prof

    pdir = os.path.join(os.environ.get("BENCH_METRICS_DIR", "log"),
                        "postmortems_chaos")
    sig_free, outs_free, _, _, _, _ = run_once(None)
    # only the measured chaos run is profiled + bundle-dumping; the
    # repeat stays plain — the determinism signature carries no wall
    # times, so sig_a == sig_b also certifies the observability stack
    # didn't perturb the schedule
    sig_a, outs_a, episodes, healthy_end, snap, prof = run_once(
        9, profile=True, pdir=pdir)
    sig_b, _, _, _, _, _ = run_once(9)

    both = sorted(set(outs_free) & set(outs_a))
    parity = all(np.array_equal(outs_free[i], outs_a[i]) for i in both)
    goodput = len(outs_a) / max(sig_a["submitted"], 1)
    goodput_free = len(outs_free) / max(sig_free["submitted"], 1)
    fired_mix: dict = {}
    for _, kind, _ in sig_a["fired"]:
        fired_mix[kind] = fired_mix.get(kind, 0) + 1
    snap_path = _dump_metrics_snapshot(None, "chaos", snapshot=snap)
    try:
        bundles = sorted(f for f in os.listdir(pdir)
                         if f.startswith("postmortem_"))
    except OSError:
        bundles = []
    prof["postmortems"] = bundles
    prof_path = _dump_profile("chaos", prof)
    print(json.dumps({
        "metric": "chaos_goodput_ratio",
        "value": round(goodput, 4),
        "unit": "retired/submitted",
        "vs_baseline": round(goodput / max(goodput_free, 1e-9), 4),
        "extra": {"deterministic": sig_a == sig_b,
                  "outputs_bit_parity": parity,
                  "compared_outputs": len(both),
                  "submitted": sig_a["submitted"],
                  "retired": len(outs_a),
                  "faults_fired": fired_mix,
                  "failovers": sig_a["failovers"],
                  "restarts": sig_a["restarts"],
                  "rerouted": sig_a["rerouted"],
                  "poisoned": sig_a["poisoned"],
                  "healthy_workers_end": healthy_end,
                  "recovery_steps_max": max(episodes, default=0),
                  "recovery_episodes": episodes,
                  "virtual_window_s": round(n_steps * dt, 2),
                  "postmortem_bundles": len(bundles),
                  "metrics_snapshot": snap_path,
                  "profile_snapshot": prof_path,
                  "backend": jax.default_backend()},
    }))


def bench_disagg():
    """Prefill/decode disaggregation (ISSUE 14): a seeded two-tenant
    mix — a prompt-heavy tenant streaming LONG prompts against a chatty
    tenant holding many live decode rows — drives the SAME 2-worker
    fleet twice on identical arrivals: role-split (``roles=("prefill",
    "decode")``, prompts prefill on a dedicated worker and hand their
    KV pages off over the transplant path) vs unified (``roles=None``,
    both workers interleave prefill chunks with resident decode rows
    under the same per-step token budget). Decode residency is what
    the split removes: unified lanes stay occupied for a row's whole
    decode, so long prompts queue behind chat decodes and their chunks
    compete with decode tokens for the step budget; the split worker's
    lanes turn over at first token. Greedy decode + identical prompts
    means the outputs-bit-identical oracle rides in ``extra``, and a
    same-seed repeat of the split run must replay bit-for-bit (the
    signature carries tokens and migration counters, never wall
    times). value = split p99 TTFT (ms) for the prompt-heavy tenant;
    vs_baseline = unified_p99 / split_p99 (> 1 means disaggregation
    flattened the prompt tenant's tail)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import ServingFleet
    from paddle_tpu.inference.traffic import (TenantProfile,
                                              TrafficGenerator)
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    on_tpu = jax.default_backend() not in ("cpu",)
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=14336, num_hidden_layers=2,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096, dtype="bfloat16")
        s_max, chunk, bs = 512, 8, 16
        p_long, p_chat = (192, 320), (8, 24)
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        s_max, chunk, bs = 96, 4, 8
        p_long, p_chat = (32, 72), (4, 12)

    model = LlamaForCausalLM(cfg)
    model.eval()

    # two generators = two tenants with DIFFERENT prompt shapes (the
    # generator's prompt distribution is global, so each tenant gets
    # its own seeded stream); the merged list is the one arrival
    # schedule every run replays
    gen_long = TrafficGenerator(
        [TenantProfile("prompts")], rate=1.0, seed=0,
        process="poisson", prompt_dist="uniform",
        prompt_min=p_long[0], prompt_max=p_long[1], max_new=4)
    gen_chat = TrafficGenerator(
        [TenantProfile("chat")], rate=4.0, seed=1,
        process="bursty", prompt_dist="uniform",
        prompt_min=p_chat[0], prompt_max=p_chat[1], max_new=24)
    horizon = 8.0
    arrivals = sorted(
        [(sr, gen_long, i)
         for i, sr in enumerate(gen_long.arrivals(horizon))]
        + [(sr, gen_chat, i)
           for i, sr in enumerate(gen_chat.arrivals(horizon))],
        key=lambda a: (a[0].t, a[0].tenant))
    dt, max_steps = 0.25, 6000

    def run_once(roles):
        fleet = ServingFleet(
            model, n_workers=2, policy="round_robin",
            engine_kwargs=dict(capacity=8, s_max=s_max, chunk=chunk,
                               block_size=bs, chunked_prefill=True,
                               # tight budget: decode tokens and
                               # prefill chunks visibly compete on a
                               # unified worker
                               step_budget=chunk + bs),
            roles=roles)
        # warmup outside the measurement (mixed-preset idiom): compile
        # each worker's decode program and the chunk windows the long
        # prompts ride, so TTFT measures steady-state service, not XLA
        # compiles landing on whichever run goes first
        for w in fleet.workers:
            eng = w.engine
            wr = eng.submit(np.arange(1, p_long[1] + 1,
                                      dtype=np.int32),
                            max_new_tokens=2)
            while not (eng.idle() and not eng.backlog):
                eng.admit([])
                eng.decode_once()
            wr.wait(timeout=120)
        vt, reqs, idx = 0.0, [], 0
        for _ in range(max_steps):
            while idx < len(arrivals) and arrivals[idx][0].t <= vt:
                sr, g, gi = arrivals[idx]
                ids = g.prompt_ids(sr, cfg.vocab_size, index=gi)
                reqs.append((sr.tenant, fleet.submit(
                    ids, max_new_tokens=sr.max_new, tenant=sr.tenant)))
                idx += 1
            fleet.step()
            vt += dt
            if idx >= len(arrivals) and not fleet.pending_work():
                break
        outs = [np.asarray(r.result) for _, r in reqs]
        ttfts = {"prompts": [], "chat": []}
        for (tenant, r) in reqs:
            ttfts[tenant].append(r.trace.ttft)
        st = fleet.stats()
        sig = {"submitted": idx,
               "outputs": [o.tolist() for o in outs],
               "migrations": st["migrations"],
               "migrated_pages": st["migrated_pages"],
               "stale_hints": st["stale_hints"]}
        snap = fleet.aggregator().snapshot()
        fleet.close()
        return sig, outs, ttfts, st, snap

    # split FIRST so it pays the cold-compile steps — a split win is
    # then a floor, not a warm-cache artifact
    sig_a, outs_split, tt_split, st_split, snap = run_once(
        ("prefill", "decode"))
    sig_uni, outs_uni, tt_uni, _, _ = run_once(None)
    sig_b, _, _, _, _ = run_once(("prefill", "decode"))

    identical = (len(outs_uni) == len(outs_split)
                 and all(np.array_equal(a, b)
                         for a, b in zip(outs_uni, outs_split)))

    def p99_ms(vals):
        return float(np.percentile(np.asarray(vals, np.float64),
                                   99)) * 1e3

    split_p99 = p99_ms(tt_split["prompts"])
    uni_p99 = p99_ms(tt_uni["prompts"])
    snap_path = _dump_metrics_snapshot(None, "disagg", snapshot=snap)
    print(json.dumps({
        "metric": "disagg_p99_ttft_ms",
        "value": round(split_p99, 2),
        "unit": "ms",
        "vs_baseline": round(uni_p99 / max(split_p99, 1e-9), 4),
        "extra": {"arrivals": len(arrivals),
                  "prompt_tenant_arrivals": len(tt_split["prompts"]),
                  "chat_tenant_arrivals": len(tt_split["chat"]),
                  "outputs_identical": identical,
                  "deterministic": sig_a == sig_b,
                  "split_p99_ttft_ms": round(split_p99, 2),
                  "unified_p99_ttft_ms": round(uni_p99, 2),
                  "split_chat_p99_ttft_ms": round(
                      p99_ms(tt_split["chat"]), 2),
                  "unified_chat_p99_ttft_ms": round(
                      p99_ms(tt_uni["chat"]), 2),
                  "migrations": st_split["migrations"],
                  "migrated_pages": st_split["migrated_pages"],
                  "unified_migrations": sig_uni["migrations"],
                  "virtual_window_s": round(horizon, 2),
                  "metrics_snapshot": snap_path,
                  "backend": jax.default_backend()},
    }))


def bench_smoke():
    """Sub-minute pipeline probe: ONE tiny compiled train step
    (fwd+bwd+AdamW) plus ONE compiled flash-attention fwd+bwd. The
    metric is wall seconds against a 60s budget (vs_baseline > 1 means
    under budget) — a fast end-to-end 'compiles and trains' signal for
    CI, not a performance number."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.kernels.flash_attention import flash_attention
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_loss_fn)
    t0 = time.perf_counter()
    paddle.seed(0)
    ndev = len(jax.devices())
    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=172, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = dist.ProcessMesh(shape=[ndev], dim_names=["dp"])
    dist.shard_model_state(model, mesh)
    step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh)
    toks = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2 * ndev, 64)).astype(np.int32))
    loss0 = float(step(toks, toks))
    loss1 = float(step(toks, toks))

    rng = np.random.default_rng(1)

    def mk(h):
        return jnp.asarray(rng.standard_normal((1, 256, h, 128)),
                           jnp.float32)

    q, k, v = mk(4), mk(2), mk(2)

    interp = jax.default_backend() == "cpu"   # Pallas on CPU only runs
    #                                           in interpret mode

    def attn_loss(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=interp).astype(
            jnp.float32).sum()

    g = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
    tf = time.perf_counter()
    float(g(q, k, v)[0].sum())
    flash_s = time.perf_counter() - tf
    wall = time.perf_counter() - t0
    print(json.dumps({
        "metric": "smoke_wall_seconds",
        "value": round(wall, 2),
        "unit": "s",
        "vs_baseline": round(60.0 / max(wall, 1e-9), 4),
        "extra": {"train_loss_first": round(loss0, 4),
                  "train_loss_second": round(loss1, 4),
                  "flash_fwd_bwd_compile_s": round(flash_s, 2),
                  "devices": ndev,
                  "backend": jax.default_backend()},
    }))


def main():
    if os.environ.get("BENCH_PRESET") in ("tp", "cp") \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the tp/cp presets need a multi-device mesh; on forced-CPU
        # runs (smoke tests) carve 8 virtual devices BEFORE backend
        # init
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax
    on_tpu = jax.default_backend() not in ("cpu",)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_loss_fn)

    paddle.seed(0)
    preset = os.environ.get("BENCH_PRESET", "default")
    if preset == "flash32k":
        return bench_flash_32k()
    if preset == "decode":
        return bench_decode()
    if preset == "engine":
        return bench_engine()
    if preset == "prefix":
        return bench_prefix()
    if preset == "fleet":
        return bench_fleet()
    if preset == "slo":
        return bench_slo()
    if preset == "overload":
        return bench_overload()
    if preset == "mixed":
        return bench_mixed()
    if preset == "spec":
        return bench_spec()
    if preset == "chaos":
        return bench_chaos()
    if preset == "disagg":
        return bench_disagg()
    if preset == "tp":
        return bench_tp()
    if preset == "cp":
        return bench_cp()
    if preset == "smoke":
        return bench_smoke()
    if on_tpu:
        check_bf16_psum_parity()
    if on_tpu:
        # Two measured presets (see BASELINE.md "Measured" table):
        #   default — ~700M params at the 8B target's EXACT layer dims
        #     (hidden 4096, ff 14336, 32 heads / 8 kv heads, head_dim 128 —
        #     the llama3-8b preset), depth cut to 2 layers so fp32 master
        #     weights + Adam moments fit one v5e chip's 16G HBM. Per-layer
        #     arithmetic intensity is what the v5p-64 north star scales from.
        #   deep — 508M at d2048/ff5632/L8: validates that scan-over-layers
        #     + remat at real depth holds the MFU the 2-layer row reports.
        vocab_default = 32000
        if preset == "deep":
            # head_dim stays 128 (16 heads at d2048) — the MXU-friendly
            # head width the 8B target uses
            dims = dict(hidden=2048, ff=5632, layers=8, batch=8, heads=16)
        elif preset == "deep4096":
            # VERDICT r3 #6a: deepest d4096 config that fits 16G with
            # fp32 master + Adam moments — validates scan x remat x depth
            # at the 8B layer dims (closes the L=2 extrapolation). Vocab
            # cut to 8192 so the embed+head state (14 B/param) leaves
            # room for 4 full layers; FULL remat bounds activations.
            dims = dict(hidden=4096, ff=14336, layers=4, batch=4, heads=32)
            vocab_default = 8192
            os.environ.setdefault("BENCH_REMAT", "full")
        else:
            dims = dict(hidden=4096, ff=14336, layers=2, batch=6, heads=32)
        cfg = LlamaConfig(
            vocab_size=int(os.environ.get("BENCH_VOCAB", vocab_default)),
            hidden_size=int(os.environ.get("BENCH_HIDDEN", dims["hidden"])),
            intermediate_size=int(os.environ.get("BENCH_FF", dims["ff"])),
            num_hidden_layers=int(os.environ.get("BENCH_LAYERS",
                                                 dims["layers"])),
            num_attention_heads=int(os.environ.get(
                "BENCH_HEADS", dims["heads"])), num_key_value_heads=8,
            max_position_embeddings=4096, dtype="bfloat16",
            recompute=bool(int(os.environ.get("BENCH_RECOMPUTE", 1))),
            recompute_granularity=os.environ.get("BENCH_REMAT", "core_attn"))
        batch = int(os.environ.get("BENCH_BATCH", dims["batch"]))
        seq = int(os.environ.get("BENCH_SEQ", 2048))
        iters = int(os.environ.get("BENCH_ITERS", 20))
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=344, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        batch, seq, iters = 2, 128, 3

    model = LlamaForCausalLM(cfg)
    n_params = sum(p.size for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    mesh = dist.ProcessMesh(shape=[len(jax.devices())], dim_names=["dp"])
    dist.shard_model_state(model, mesh)

    step = dist.DistTrainStep(model, opt, llama_loss_fn, mesh, donate=True)

    # Fresh batch per step so the printed loss is a correctness signal,
    # not single-batch memorization. Sequences carry learnable structure
    # (noisy affine next-token process) so the loss FALLS from ~ln(V)
    # toward the process entropy as training proceeds — a causality or
    # optimizer bug shows up as a flat/rising loss.
    rng = np.random.default_rng(0)
    support = min(256, cfg.vocab_size)  # restricted support: the unigram
    # marginal (~ln(support)) is learnable within the bench's few steps,
    # so a falling loss is visible even in a 20-step timing run

    def fresh_batch():
        toks = np.empty((batch, seq), dtype=np.int32)
        toks[:, 0] = rng.integers(0, support, batch)
        noise = rng.integers(-2, 3, size=(batch, seq - 1))
        for t in range(1, seq):
            toks[:, t] = (toks[:, t - 1] * 5 + 17 + noise[:, t - 1]) \
                % support
        return paddle.to_tensor(toks)

    batches = [fresh_batch() for _ in range(iters + 1)]
    # compile + warmup (fetch to host: block_until_ready is a no-op through
    # the remote-TPU tunnel)
    loss_first = float(step(batches[-1], batches[-1]))
    loss = loss_first
    t0 = time.perf_counter()
    for i in range(iters):
        loss = step(batches[i], batches[i])
    float(loss)  # steps chain through donated params; fetch syncs them all
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * iters / dt
    # fwd+bwd dense approximation over MATMUL params only: the input
    # embedding is a gather, not a matmul, so counting it would inflate
    # MFU (standard MFU convention; lm_head IS a matmul and stays in)
    n_embed = cfg.vocab_size * cfg.hidden_size
    flops_per_token = 6.0 * (n_params - n_embed)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / (peak_flops_per_chip() * len(jax.devices()))
    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / len(jax.devices()), 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "params": int(n_params),
                  "batch": batch, "seq": seq, "preset": preset,
                  "loss_first": round(loss_first, 4),
                  "loss": round(float(loss), 4),
                  "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    if not _env_flag("BENCH_CHILD") and not _env_flag("BENCH_NO_WALL"):
        # probe FIRST, then charge its runtime against the TOTAL wall
        # budget: probe retries + bench must together stay under the
        # driver's own ~15-min kill or the infra-skip never emits
        _install_parent_handlers()
        _t0 = time.monotonic()
        probe_backend()
        _remaining = _WALL_TIMEOUT_S - (time.monotonic() - _t0)
        if _remaining < 120.0:
            # raised probe knobs ate the budget: say so honestly rather
            # than start a bench the driver will kill mid-run
            _emit_infra_skip(
                f"probe retries consumed the wall budget "
                f"({_remaining:.0f}s left of {_WALL_TIMEOUT_S}s)")
            sys.exit(0)
        run_walled(_remaining)
    probe_backend()
    try:
        main()
    except Exception as e:  # infra-only: real code errors still rc!=0
        if _is_infra_error(e):
            _emit_infra_skip(f"{type(e).__name__}: {e}")
            sys.exit(0)
        raise
