"""AMP: auto_cast + GradScaler (reference: python/paddle/amp/auto_cast.py:698,
grad_scaler.py:578; O1/O2 op lists in amp/amp_lists.py).

TPU-native: the native mixed-precision dtype is bfloat16 (no loss scaling
required — GradScaler degrades to a no-op scale of 1.0 for bf16, kept for
API parity and fp16 semantics)."""

from .auto_cast import (  # noqa: F401
    auto_cast, amp_guard, amp_state, decorate, white_list, black_list,
    is_auto_cast_enabled, get_amp_dtype,
)
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler",
           "is_auto_cast_enabled", "get_amp_dtype"]


def is_float16_supported(device=None):
    """fp16 compute support (reference: amp/auto_cast.py
    is_float16_supported). TPUs compute fp16 via upcast; bf16 is native."""
    import jax
    return jax.default_backend() in ("gpu", "tpu", "cpu")


def is_bfloat16_supported(device=None):
    """bf16 is the native TPU mixed-precision dtype."""
    return True


__all__ += ["is_float16_supported", "is_bfloat16_supported"]
