"""Autocast context (reference: python/paddle/amp/auto_cast.py).

Dispatch integration: core/dispatch.apply_op consults this module's state
and casts floating inputs of white-list ops to the amp dtype (the reference
bakes the same logic into every generated forward via AMP_LOGIC_TEMPLATE,
eager_gen.py:502)."""

from __future__ import annotations

import threading

from ..core.dtype import convert_dtype

# O1 white list: ops that run in low precision (matmul-class, conv-class) —
# reference python/paddle/amp/amp_lists.py WHITE_LIST
white_list = {
    "matmul", "mm", "bmm", "linear", "conv", "conv_transpose", "einsum",
    "scaled_dot_product_attention", "flash_attention", "lstm_layer",
    "gru_layer", "simple_rnn_layer", "embedding_lookup", "tensordot",
    # whole-model fused forwards (stacked-scan models): matmul-dominated
    "llama_forward", "gpt_forward",
}

# black list: numerically-sensitive ops stay fp32 —
# reference amp_lists.py BLACK_LIST
black_list = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax",
    "log_softmax", "cross_entropy", "bce_loss", "bce_with_logits",
    "mse_loss", "l1_loss", "nll_loss", "kl_div", "sum", "mean", "p_norm",
    "frobenius_norm", "layer_norm", "batch_norm_train", "batch_norm_infer",
    "rms_norm", "group_norm", "instance_norm", "softmax_with_cross_entropy",
    "cumsum", "cumprod", "pow", "square", "reciprocal", "rsqrt",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = None
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_STATE = _AmpState()


def is_auto_cast_enabled() -> bool:
    return _STATE.enabled


def get_amp_dtype():
    return _STATE.dtype


def amp_state():
    return _STATE


def _cast_for_op(op_name: str, arrays):
    """Called from dispatch: cast float arrays per amp policy."""
    import jax.numpy as jnp
    if not _STATE.enabled:
        return arrays
    wl = (white_list | _STATE.custom_white) - _STATE.custom_black
    bl = (black_list | _STATE.custom_black) - _STATE.custom_white
    if _STATE.level == "O2":
        in_low = op_name not in bl
    else:
        in_low = op_name in wl
    target = _STATE.dtype if in_low else jnp.float32
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and hasattr(a, "astype") \
                and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != target:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


class auto_cast:
    """paddle.amp.auto_cast parity (context manager / decorator)."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype)
        self.custom_white = set(custom_white_list or ())
        self.custom_black = set(custom_black_list or ())

    def __enter__(self):
        self._prev = (_STATE.enabled, _STATE.dtype, _STATE.level,
                      _STATE.custom_white, _STATE.custom_black)
        _STATE.enabled = self.enable
        _STATE.dtype = self.dtype
        _STATE.level = self.level
        _STATE.custom_white = self.custom_white
        _STATE.custom_black = self.custom_black
        return self

    def __exit__(self, *exc):
        (_STATE.enabled, _STATE.dtype, _STATE.level,
         _STATE.custom_white, _STATE.custom_black) = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*a, **k):
            with self:
                return fn(*a, **k)
        return wrapper


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """paddle.amp.decorate parity: O2 casts model params to the amp dtype
    (reference amp/auto_cast.py:782)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers
