"""GradScaler (reference: python/paddle/amp/grad_scaler.py:578).

Dynamic loss scaling for fp16; bf16 (TPU default) doesn't need it but the
API works identically so fp16-tuned recipes run unchanged."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        # per-optimizer unscale/inf state (reference grad_scaler.py
        # OptimizerState INIT/UNSCALED/STEPPED): prevents double unscaling
        # in the recipe unscale_(opt); clip; step(opt), and keeps inf
        # detection per optimizer for multi-optimizer setups
        self._unscaled_opts: set[int] = set()
        self._found_inf_per_opt: dict[int, bool] = {}

    @property
    def _found_inf(self):
        return any(self._found_inf_per_opt.values())

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _unscale(self, optimizer):
        params = optimizer._parameter_list
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._value
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad._in_place_update(g * inv)
        self._found_inf_per_opt[id(optimizer)] = found
        self._unscaled_opts.add(id(optimizer))

    def unscale_(self, optimizer):
        if self._enable and id(optimizer) not in self._unscaled_opts:
            self._unscale(optimizer)

    def step(self, optimizer):
        """Unscale (if not already) and step; does NOT update the scale —
        call update() once per iteration (reference semantics)."""
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled_opts:
            self._unscale(optimizer)
        if not self._found_inf_per_opt.get(id(optimizer), False):
            optimizer.step()

    def update(self):
        if not self._enable:
            return
        if self._dynamic:
            if self._found_inf:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every_n_nan_or_inf:
                    self._scale = max(self._scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            else:
                self._good_steps += 1
                self._bad_steps = 0
                if self._good_steps >= self._incr_every_n_steps:
                    self._scale *= self._incr_ratio
                    self._good_steps = 0
        self._unscaled_opts.clear()
        self._found_inf_per_opt.clear()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def set_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
