"""paddle_tpu.sysconfig (reference: python/paddle/sysconfig.py —
get_include/get_lib for building custom extensions)."""

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing this package's headers (the custom C++ op
    extension API lives beside utils/cpp_extension)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "lib")
